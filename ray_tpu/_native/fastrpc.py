"""ctypes binding for the native RPC I/O core (src/fastrpc.cpp).

One C epoll thread per process serves N inbound "rings": independent
event queues, each with its own notify eventfd. `NativeIO.get()` is the
legacy ring-0 singleton (the process-main io loop); `NativeIO.new_ring()`
hands an owner shard its own ring so its asyncio loop wakes only for its
own connections' frames (reference role: src/ray/rpc/ — gRPC's
completion-queue-per-thread layout). Connections are bound to a ring at
listen/connect time; accepted conns inherit the listener's ring.

All routing callbacks run on the asyncio event loop attached to the
owning ring.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
import os
import struct
import threading
from typing import Callable, Dict, List, Optional, Tuple

_U64 = struct.Struct("<Q")

from .build import build_library

logger = logging.getLogger(__name__)

# kind codes from the C core
KIND_FRAME = 0
KIND_ACCEPT = 1
KIND_CLOSED = 2
# decoded-path kinds (frpc_recv_decoded; src/fastrpc.cpp header comment
# documents each record layout)
KIND_DECODED_PUSH = 3        # decoded push_task request
KIND_DECODED_ACTOR_BATCH = 4  # decoded push_actor_tasks batch
KIND_DONE_STREAM = 5         # validated actor_tasks_done payload
KIND_DECREF_FOLD = 6         # accumulated borrow_decref_fold ids

_RECV_CAP = 1024

# Field order of the frpc_ring_stats C export — MUST match both the C
# side (src/fastrpc.cpp) and rpc_metrics.RING_STAT_FIELDS, which maps
# these onto the rtpu_ring_* series.
RING_STAT_FIELDS = (
    "frames_in", "frames_out", "bytes_in", "bytes_out",
    "decode_hits", "decode_fallbacks", "fold_batches",
    "notify_wakeups", "queue_depth", "depth_hwm")

_RECV_ARGTYPES = [
    ctypes.c_int,
    ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
    ctypes.c_char_p, ctypes.c_uint64,
    ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
    ctypes.c_int64]


def _load() -> Optional[ctypes.CDLL]:
    path = build_library("fastrpc")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.frpc_start.restype = ctypes.c_int
    lib.frpc_ring_create.restype = ctypes.c_int
    lib.frpc_ring_fd.restype = ctypes.c_int
    lib.frpc_ring_fd.argtypes = [ctypes.c_int]
    lib.frpc_listen2.restype = ctypes.c_int64
    lib.frpc_listen2.argtypes = [ctypes.c_char_p,
                                 ctypes.POINTER(ctypes.c_int),
                                 ctypes.c_int]
    lib.frpc_connect2.restype = ctypes.c_int64
    lib.frpc_connect2.argtypes = [ctypes.c_char_p, ctypes.c_int,
                                  ctypes.c_int, ctypes.c_int]
    lib.frpc_send.restype = ctypes.c_int
    lib.frpc_send.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                              ctypes.c_uint64]
    lib.frpc_out_bytes.restype = ctypes.c_uint64
    lib.frpc_out_bytes.argtypes = [ctypes.c_int64]
    lib.frpc_recv2.restype = ctypes.c_int64
    lib.frpc_recv2.argtypes = _RECV_ARGTYPES
    lib.frpc_recv_decoded.restype = ctypes.c_int64
    lib.frpc_recv_decoded.argtypes = _RECV_ARGTYPES
    lib.frpc_next_len2.restype = ctypes.c_uint64
    lib.frpc_next_len2.argtypes = [ctypes.c_int]
    lib.frpc_close.argtypes = [ctypes.c_int64]
    lib.frpc_decode_enable.argtypes = [ctypes.c_int]
    lib.frpc_decode_enabled.restype = ctypes.c_int
    lib.frpc_tmpl_register.argtypes = [ctypes.c_char_p]
    lib.frpc_tmpl_known.restype = ctypes.c_int
    lib.frpc_tmpl_known.argtypes = [ctypes.c_char_p]
    lib.frpc_test_decode.restype = ctypes.c_int64
    lib.frpc_test_decode.argtypes = [
        ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
        ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint8)]
    # Older cached .so builds predate the ring-stats export; guard so a
    # stale RTPU_NATIVE_CACHE keeps working (ring_stats() returns None).
    if hasattr(lib, "frpc_ring_stats"):
        lib.frpc_ring_stats.restype = ctypes.c_int
        lib.frpc_ring_stats.argtypes = [ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_uint64),
                                        ctypes.c_int]
    return lib


# Library handle shared by the io singleton and the loop-free helpers
# below (test_decode/mirror_template can run without starting the io
# thread — the decoder itself has no dependency on the epoll core).
_lib_cached: Optional[ctypes.CDLL] = None
_lib_checked = False
_lib_lock = threading.Lock()


def _lib() -> Optional[ctypes.CDLL]:
    global _lib_cached, _lib_checked
    with _lib_lock:
        if not _lib_checked:
            try:
                _lib_cached = _load()
            except Exception:
                logger.exception("fastrpc library unavailable")
                _lib_cached = None
            _lib_checked = True
        return _lib_cached


def mirror_template(tid: bytes) -> None:
    """Mirror one announced template id into the C decoder's table (the
    receive-side twin of task_spec.register_template). No-op when the
    native library is unavailable."""
    lib = _lib()
    if lib is not None:
        lib.frpc_tmpl_register(tid)


def template_known(tid: bytes) -> bool:
    lib = _lib()
    return bool(lib is not None and lib.frpc_tmpl_known(tid))


def test_decode(body: bytes, cap: int = 1 << 20, buf=None):
    """Run the C classifier/decoder on one frame body (unit tests and
    the --codec microbench). Returns (kind, decoded bytes) — kind 0
    means passthrough (decoded is the untouched body), kind 6 means the
    frame would be absorbed into the ring's decref fold. None when the
    native library is unavailable. Pass a reusable
    ctypes.create_string_buffer as `buf` to keep a timing loop free of
    per-call allocations."""
    lib = _lib()
    if lib is None:
        return None
    out = buf if buf is not None else ctypes.create_string_buffer(cap)
    kind = ctypes.c_uint8(0)
    n = lib.frpc_test_decode(body, len(body), out, len(out),
                             ctypes.byref(kind))
    if n == -2:
        raise ValueError("frpc_test_decode: output buffer too small")
    if n == 0:
        return 0, body
    return kind.value, out.raw[:n]


class NativeIO:
    """One inbound ring of the native core + its asyncio integration.

    Ring 0 is the process singleton (``get()``); additional rings are
    created (and pooled across init/shutdown cycles) via ``new_ring()``.
    ``send``/``out_bytes``/``close`` address conns by their global id and
    work on any instance.
    """

    _instance: Optional["NativeIO"] = None
    _lock = threading.Lock()
    # Rings released by a torn-down shard set, reused by the next init —
    # ring fds are a process-lifetime resource in the C core (capped at
    # 64), so repeated init/shutdown cycles must recycle them.
    _ring_pool: List["NativeIO"] = []
    # Native receive decode: process-wide (the C flag is global), applied
    # by CoreWorker.start per init so the RTPU_NO_NATIVE_DECODE A/B can
    # flip between init/shutdown cycles in one process.
    _decode_on = False
    # Ring-level sink for kind-6 decref folds (process-global: exactly
    # one CoreWorker per process owns borrow-decref handling). Runs on
    # whichever loop drains the ring; the fold consumer is thread-safe.
    _fold_sink: Optional[Callable[[memoryview], None]] = None
    # Every ring ever created in this process, by ring index — the
    # transport observatory walks this to export per-ring stats. Rings
    # are process-lifetime resources in the C core, so entries are never
    # removed (a pooled ring keeps reporting its totals, which is what a
    # monotonic counter wants).
    _ring_registry: Dict[int, "NativeIO"] = {}

    def __init__(self, lib: ctypes.CDLL, notify_fd: int, ring: int = 0):
        self._lib = lib
        self._ring = ring
        self._notify_fd = notify_fd
        self._attached_loop = None
        # conn_id -> callable(kind, memoryview-body)
        self._sinks: Dict[int, Callable[[int, memoryview], None]] = {}
        # listener_id -> callable(conn_id) -> sink for accepted conns
        self._listeners: Dict[int, Callable[[int], Callable]] = {}
        # Events that raced registration: the C thread can deliver for a
        # conn/listener id before connect()/listen() returns it to the
        # caller. Buffered (copied) and flushed on registration.
        self._orphans: Dict[int, list] = {}
        self._buf = ctypes.create_string_buffer(4 << 20)
        self._conn_ids = (ctypes.c_int64 * _RECV_CAP)()
        self._kinds = (ctypes.c_uint8 * _RECV_CAP)()
        self._offsets = (ctypes.c_uint64 * _RECV_CAP)()
        self._lengths = (ctypes.c_uint64 * _RECV_CAP)()

    @classmethod
    def get(cls) -> Optional["NativeIO"]:
        with cls._lock:
            return cls._get_locked()

    @classmethod
    def _get_locked(cls) -> Optional["NativeIO"]:
        if cls._instance is None:
            if os.environ.get("RTPU_DISABLE_NATIVE_RPC"):
                return None
            lib = _lib()
            if lib is None:
                return None
            fd = lib.frpc_start()
            if fd < 0:
                return None
            cls._instance = cls(lib, fd)
            cls._ring_registry[0] = cls._instance
        return cls._instance

    @classmethod
    def apply_decode_config(cls, enabled: bool) -> bool:
        """Arm (or disarm) the in-ring native decode, process-wide.
        Called once per CoreWorker.start with the resolved
        RTPU_NO_NATIVE_DECODE setting; returns the effective state.
        Every ring of this process switches drain entry points together
        — frpc_recv_decoded is the only drain that delivers the decref
        fold."""
        lib = _lib()
        if lib is None:
            cls._decode_on = False
            return False
        lib.frpc_decode_enable(1 if enabled else 0)
        cls._decode_on = enabled
        return enabled

    @classmethod
    def set_fold_sink(cls, sink: Optional[Callable]) -> None:
        cls._fold_sink = sink

    @classmethod
    def new_ring(cls) -> Optional["NativeIO"]:
        """A fresh (or recycled) ring for an owner shard, or None when
        the native core is unavailable / the ring table is full —
        callers fall back to the asyncio transport or ring 0."""
        with cls._lock:
            base = cls._get_locked()
            if base is None:
                return None
            if cls._ring_pool:
                return cls._ring_pool.pop()
            ring = base._lib.frpc_ring_create()
            if ring < 0:
                return None
            fd = base._lib.frpc_ring_fd(ring)
            if fd < 0:
                return None
            io = cls(base._lib, fd, ring=ring)
            cls._ring_registry[ring] = io
            return io

    @classmethod
    def release_ring(cls, ring: "NativeIO"):
        """Return a shard's ring to the pool at shard-set teardown. The
        caller has already closed the ring's conns/listeners; routing
        state is cleared so the next user starts clean."""
        if ring is None or ring._ring == 0:
            return
        ring._sinks.clear()
        ring._listeners.clear()
        ring._orphans.clear()
        with cls._lock:
            cls._ring_pool.append(ring)

    @classmethod
    def all_instances(cls) -> List[Tuple[int, "NativeIO"]]:
        """Snapshot of every ring this process has created, as
        ``(ring_index, io)`` pairs sorted by index — the stats exporter
        iterates this without holding the class lock for long."""
        with cls._lock:
            return sorted(cls._ring_registry.items())

    def ring_stats(self) -> Optional[Dict[str, int]]:
        """Lock-free stats snapshot of this ring from the C core, keyed
        by ``RING_STAT_FIELDS``. None when the loaded library predates
        the export (stale build cache) or the ring is gone."""
        lib = self._lib
        if not hasattr(lib, "frpc_ring_stats"):
            return None
        out = (ctypes.c_uint64 * len(RING_STAT_FIELDS))()
        n = lib.frpc_ring_stats(self._ring, out, len(RING_STAT_FIELDS))
        if n < len(RING_STAT_FIELDS):
            return None
        return dict(zip(RING_STAT_FIELDS, out))

    # -- loop integration ------------------------------------------------

    def attach(self, loop):
        """Watch this ring's notify eventfd on `loop`; must run on the
        loop.

        First-wins: once attached to a live loop, later attach attempts
        from OTHER loops are ignored — moving the reader would strand
        every connection whose sink/futures live on the first loop
        (frames would drain on the wrong thread and replies silently
        vanish). Re-attach only if the original loop is closed."""
        if self._attached_loop is loop:
            return
        if self._attached_loop is not None:
            if (not self._attached_loop.is_closed()
                    and self._attached_loop.is_running()):
                logger.warning(
                    "NativeIO.attach ignored: already attached to a live "
                    "loop; refusing to move the eventfd reader")
                return
            # stopped or closed loop: the reader would never fire — move it
            try:
                self._attached_loop.remove_reader(self._notify_fd)
            except Exception:
                logger.debug("remove_reader on dead loop failed",
                             exc_info=True)
        self._attached_loop = loop
        loop.add_reader(self._notify_fd, self._drain)

    def detach(self, loop):
        """Stop watching the notify fd on `loop` (shard teardown; the
        ring is then recycled via release_ring)."""
        if self._attached_loop is not loop:
            return
        try:
            loop.remove_reader(self._notify_fd)
        except Exception:
            logger.debug("remove_reader during ring detach failed",
                         exc_info=True)
        self._attached_loop = None

    def _drain(self):
        lib = self._lib
        recv = lib.frpc_recv_decoded if NativeIO._decode_on \
            else lib.frpc_recv2
        while True:
            n = recv(self._ring, self._conn_ids, self._kinds,
                     self._buf, len(self._buf), self._offsets,
                     self._lengths, _RECV_CAP)
            if n == 0:
                need = lib.frpc_next_len2(self._ring)
                if need > len(self._buf):
                    self._buf = ctypes.create_string_buffer(
                        int(need) + (1 << 20))
                    continue
                return
            mv = memoryview(self._buf)
            for i in range(n):
                conn = self._conn_ids[i]
                kind = self._kinds[i]
                body = mv[self._offsets[i]:self._offsets[i] + self._lengths[i]]
                self._dispatch(conn, kind, body)
            if n < _RECV_CAP:
                # queue drained (or next frame needs a larger buffer)
                if lib.frpc_next_len2(self._ring) == 0:
                    return

    def _dispatch(self, conn: int, kind: int, body):
        if kind == KIND_DECREF_FOLD:
            # Ring-scoped (conn id 0), always the LAST event of a drain
            # (the C side orders it after the queued frames). Apply via
            # call_soon rather than synchronously: the frame events of
            # this same drain dispatch their handlers through
            # ensure_future, and a decrement must never run before an
            # earlier-arrived borrow_addref frame's handler — late
            # decrements only delay a free, early ones corrupt the
            # count. The consumer (the lock-striped reference counter)
            # is thread-safe, so WHICH loop runs it doesn't matter,
            # only the ordering on this one.
            sink = NativeIO._fold_sink
            if sink is None:
                logger.warning("decref fold dropped: no sink registered")
                return
            data = bytes(body)  # the recv buffer is reused

            def _apply():
                try:
                    sink(data)
                except Exception:
                    logger.exception("decref fold sink failed")
            try:
                asyncio.get_running_loop().call_soon(_apply)
            except RuntimeError:
                _apply()  # no loop (tests driving _drain by hand)
            return
        if kind == KIND_ACCEPT:
            (lid,) = _U64.unpack(body)
            factory = self._listeners.get(lid)
            if factory is None:
                # listen() hasn't registered the id yet — buffer (copy:
                # the recv buffer is reused).
                self._orphans.setdefault(lid, []).append(
                    (conn, kind, bytes(body)))
                return
            self._register_accepted(conn, factory)
            return
        sink = self._sinks.get(conn)
        if sink is None:
            if len(self._orphans) > 1024:  # rogue peers must not leak
                self._orphans.pop(next(iter(self._orphans)))
            self._orphans.setdefault(conn, []).append(
                (conn, kind, bytes(body)))
            return
        if kind == KIND_CLOSED:
            self._sinks.pop(conn, None)
        try:
            sink(kind, body)
        except Exception:
            logger.exception("native rpc sink failed")

    def _register_accepted(self, conn: int, factory):
        self._sinks[conn] = factory(conn)
        self._flush_orphans_for_conn(conn)

    def _flush_orphans_for_conn(self, conn: int):
        for c, kind, body in self._orphans.pop(conn, ()):
            self._dispatch(c, kind, body)

    # -- operations ------------------------------------------------------
    # listen/register run on the ring's event loop (same thread as
    # _drain), so the orphan-buffer check-then-act sequences cannot
    # interleave.

    def listen(self, host: str, port: int,
               accept_factory: Callable[[int], Callable]
               ) -> Optional[Tuple[int, int]]:
        p = ctypes.c_int(port)
        lid = self._lib.frpc_listen2(host.encode(), ctypes.byref(p),
                                     self._ring)
        if lid < 0:
            return None
        self._listeners[lid] = accept_factory
        for conn, kind, body in self._orphans.pop(lid, ()):
            self._dispatch(conn, kind, body)
        return lid, p.value

    def connect(self, host: str, port: int, timeout_ms: int) -> Optional[int]:
        """Raw connect (blocking; call off the loop). The caller must then
        register(conn, sink) ON the loop before using the conn.
        Returns the conn id, None on hard failure (refused/unreachable),
        or raises TimeoutError on a connect timeout — the distinction
        matters for liveness decisions (refused proves the process is
        gone; a timeout proves nothing)."""
        conn = self._lib.frpc_connect2(host.encode(), port, timeout_ms,
                                       self._ring)
        if conn == -2:
            raise TimeoutError(f"connect to {host}:{port} timed out")
        return None if conn < 0 else conn

    def register(self, conn_id: int, sink: Callable[[int, memoryview], None]):
        self._sinks[conn_id] = sink
        self._flush_orphans_for_conn(conn_id)

    def send(self, conn_id: int, frame: bytes) -> bool:
        return self._lib.frpc_send(conn_id, frame, len(frame)) == 0

    def out_bytes(self, conn_id: int) -> int:
        return self._lib.frpc_out_bytes(conn_id)

    def close(self, conn_id: int, listener_id: Optional[int] = None):
        self._sinks.pop(conn_id, None)
        if listener_id is not None:
            self._listeners.pop(listener_id, None)
        self._lib.frpc_close(conn_id)
