"""ctypes binding for the native RPC I/O core (src/fastrpc.cpp).

One NativeIO per process: owns the C epoll thread, routes received frames
to the RpcServer / RpcClient that own each connection, and wakes the
asyncio loop once per *batch* of messages via the core's notify eventfd
(reference role: src/ray/rpc/ — gRPC's completion-queue threads).

All routing callbacks run on the asyncio event loop thread.
"""

from __future__ import annotations

import ctypes
import logging
import os
import struct
import threading
from typing import Callable, Dict, Optional, Tuple

_U64 = struct.Struct("<Q")

from .build import build_library

logger = logging.getLogger(__name__)

# kind codes from the C core
KIND_FRAME = 0
KIND_ACCEPT = 1
KIND_CLOSED = 2

_RECV_CAP = 1024


def _load() -> Optional[ctypes.CDLL]:
    path = build_library("fastrpc")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.frpc_start.restype = ctypes.c_int
    lib.frpc_listen.restype = ctypes.c_int64
    lib.frpc_listen.argtypes = [ctypes.c_char_p,
                                ctypes.POINTER(ctypes.c_int)]
    lib.frpc_connect.restype = ctypes.c_int64
    lib.frpc_connect.argtypes = [ctypes.c_char_p, ctypes.c_int, ctypes.c_int]
    lib.frpc_send.restype = ctypes.c_int
    lib.frpc_send.argtypes = [ctypes.c_int64, ctypes.c_char_p,
                              ctypes.c_uint64]
    lib.frpc_out_bytes.restype = ctypes.c_uint64
    lib.frpc_out_bytes.argtypes = [ctypes.c_int64]
    lib.frpc_recv.restype = ctypes.c_int64
    lib.frpc_recv.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_uint8),
        ctypes.c_char_p, ctypes.c_uint64,
        ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
        ctypes.c_int64]
    lib.frpc_next_len.restype = ctypes.c_uint64
    lib.frpc_close.argtypes = [ctypes.c_int64]
    return lib


class NativeIO:
    """Process singleton wrapping the native core + asyncio integration."""

    _instance: Optional["NativeIO"] = None
    _lock = threading.Lock()

    def __init__(self, lib: ctypes.CDLL, notify_fd: int):
        self._lib = lib
        self._notify_fd = notify_fd
        self._attached_loop = None
        # conn_id -> callable(kind, memoryview-body)
        self._sinks: Dict[int, Callable[[int, memoryview], None]] = {}
        # listener_id -> callable(conn_id) -> sink for accepted conns
        self._listeners: Dict[int, Callable[[int], Callable]] = {}
        # Events that raced registration: the C thread can deliver for a
        # conn/listener id before connect()/listen() returns it to the
        # caller. Buffered (copied) and flushed on registration.
        self._orphans: Dict[int, list] = {}
        self._buf = ctypes.create_string_buffer(4 << 20)
        self._conn_ids = (ctypes.c_int64 * _RECV_CAP)()
        self._kinds = (ctypes.c_uint8 * _RECV_CAP)()
        self._offsets = (ctypes.c_uint64 * _RECV_CAP)()
        self._lengths = (ctypes.c_uint64 * _RECV_CAP)()

    @classmethod
    def get(cls) -> Optional["NativeIO"]:
        with cls._lock:
            if cls._instance is None:
                if os.environ.get("RTPU_DISABLE_NATIVE_RPC"):
                    return None
                lib = _load()
                if lib is None:
                    return None
                fd = lib.frpc_start()
                if fd < 0:
                    return None
                cls._instance = cls(lib, fd)
            return cls._instance

    # -- loop integration ------------------------------------------------

    def attach(self, loop):
        """Watch the notify eventfd on `loop`; must run on the loop.

        First-wins: once attached to a live loop, later attach attempts
        from OTHER loops are ignored — moving the reader would strand
        every connection whose sink/futures live on the first loop
        (frames would drain on the wrong thread and replies silently
        vanish). Re-attach only if the original loop is closed."""
        if self._attached_loop is loop:
            return
        if self._attached_loop is not None:
            if (not self._attached_loop.is_closed()
                    and self._attached_loop.is_running()):
                logger.warning(
                    "NativeIO.attach ignored: already attached to a live "
                    "loop; refusing to move the eventfd reader")
                return
            # stopped or closed loop: the reader would never fire — move it
            try:
                self._attached_loop.remove_reader(self._notify_fd)
            except Exception:
                logger.debug("remove_reader on dead loop failed",
                             exc_info=True)
        self._attached_loop = loop
        loop.add_reader(self._notify_fd, self._drain)

    def _drain(self):
        lib = self._lib
        while True:
            n = lib.frpc_recv(self._conn_ids, self._kinds, self._buf,
                              len(self._buf), self._offsets, self._lengths,
                              _RECV_CAP)
            if n == 0:
                need = lib.frpc_next_len()
                if need > len(self._buf):
                    self._buf = ctypes.create_string_buffer(
                        int(need) + (1 << 20))
                    continue
                return
            mv = memoryview(self._buf)
            for i in range(n):
                conn = self._conn_ids[i]
                kind = self._kinds[i]
                body = mv[self._offsets[i]:self._offsets[i] + self._lengths[i]]
                self._dispatch(conn, kind, body)
            if n < _RECV_CAP:
                # queue drained (or next frame needs a larger buffer)
                if lib.frpc_next_len() == 0:
                    return

    def _dispatch(self, conn: int, kind: int, body):
        if kind == KIND_ACCEPT:
            (lid,) = _U64.unpack(body)
            factory = self._listeners.get(lid)
            if factory is None:
                # listen() hasn't registered the id yet — buffer (copy:
                # the recv buffer is reused).
                self._orphans.setdefault(lid, []).append(
                    (conn, kind, bytes(body)))
                return
            self._register_accepted(conn, factory)
            return
        sink = self._sinks.get(conn)
        if sink is None:
            if len(self._orphans) > 1024:  # rogue peers must not leak
                self._orphans.pop(next(iter(self._orphans)))
            self._orphans.setdefault(conn, []).append(
                (conn, kind, bytes(body)))
            return
        if kind != KIND_FRAME:
            self._sinks.pop(conn, None)
        try:
            sink(kind, body)
        except Exception:
            logger.exception("native rpc sink failed")

    def _register_accepted(self, conn: int, factory):
        self._sinks[conn] = factory(conn)
        self._flush_orphans_for_conn(conn)

    def _flush_orphans_for_conn(self, conn: int):
        for c, kind, body in self._orphans.pop(conn, ()):
            self._dispatch(c, kind, body)

    # -- operations ------------------------------------------------------
    # listen/register run on the event loop (same thread as _drain), so
    # the orphan-buffer check-then-act sequences cannot interleave.

    def listen(self, host: str, port: int,
               accept_factory: Callable[[int], Callable]
               ) -> Optional[Tuple[int, int]]:
        p = ctypes.c_int(port)
        lid = self._lib.frpc_listen(host.encode(), ctypes.byref(p))
        if lid < 0:
            return None
        self._listeners[lid] = accept_factory
        for conn, kind, body in self._orphans.pop(lid, ()):
            self._dispatch(conn, kind, body)
        return lid, p.value

    def connect(self, host: str, port: int, timeout_ms: int) -> Optional[int]:
        """Raw connect (blocking; call off the loop). The caller must then
        register(conn, sink) ON the loop before using the conn.
        Returns the conn id, None on hard failure (refused/unreachable),
        or raises TimeoutError on a connect timeout — the distinction
        matters for liveness decisions (refused proves the process is
        gone; a timeout proves nothing)."""
        conn = self._lib.frpc_connect(host.encode(), port, timeout_ms)
        if conn == -2:
            raise TimeoutError(f"connect to {host}:{port} timed out")
        return None if conn < 0 else conn

    def register(self, conn_id: int, sink: Callable[[int, memoryview], None]):
        self._sinks[conn_id] = sink
        self._flush_orphans_for_conn(conn_id)

    def send(self, conn_id: int, frame: bytes) -> bool:
        return self._lib.frpc_send(conn_id, frame, len(frame)) == 0

    def out_bytes(self, conn_id: int) -> int:
        return self._lib.frpc_out_bytes(conn_id)

    def close(self, conn_id: int, listener_id: Optional[int] = None):
        self._sinks.pop(conn_id, None)
        if listener_id is not None:
            self._listeners.pop(listener_id, None)
        self._lib.frpc_close(conn_id)
