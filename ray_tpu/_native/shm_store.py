"""ctypes binding for the C++ shared-memory object store core
(src/shm_store.cpp — the TPU-native equivalent of the reference's plasma
allocator/dlmalloc + object tables + LRU eviction, N9 in SURVEY §2a).

One arena file per node in /dev/shm; every process maps the same file, so
offsets returned by the C side are valid views in all of them. Object ids
are the 20-byte ObjectID digests."""

from __future__ import annotations

import ctypes
import mmap
import os
from typing import Optional, Tuple

from .build import build_library


class ArenaStoreError(Exception):
    pass


class ArenaFullError(ArenaStoreError):
    pass


_lib = None


def load() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    path = build_library("shm_store")
    if path is None:
        return None
    lib = ctypes.CDLL(path)
    lib.store_init.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
    lib.store_init.restype = ctypes.c_int
    lib.store_is_initialized.argtypes = [ctypes.c_void_p]
    lib.store_is_initialized.restype = ctypes.c_int
    lib.store_create.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                 ctypes.c_uint64, ctypes.c_int,
                                 ctypes.POINTER(ctypes.c_int)]
    lib.store_create.restype = ctypes.c_uint64
    lib.store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.store_seal.restype = ctypes.c_int
    lib.store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                              ctypes.POINTER(ctypes.c_uint64)]
    lib.store_get.restype = ctypes.c_uint64
    for fn in ("store_release", "store_delete", "store_contains"):
        getattr(lib, fn).argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        getattr(lib, fn).restype = ctypes.c_int
    lib.store_used_bytes.argtypes = [ctypes.c_void_p]
    lib.store_used_bytes.restype = ctypes.c_uint64
    lib.store_capacity.argtypes = [ctypes.c_void_p]
    lib.store_capacity.restype = ctypes.c_uint64
    _lib = lib
    return lib


class ArenaStore:
    """One node-wide arena segment, shared by all local processes."""

    def __init__(self, path: str, capacity: int, create: bool):
        lib = load()
        if lib is None:
            raise ArenaStoreError("native library unavailable")
        self._lib = lib
        self.path = path
        total = capacity
        if create:
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
            os.ftruncate(fd, total)
        else:
            fd = os.open(path, os.O_RDWR)
            total = os.fstat(fd).st_size
        try:
            self._mm = mmap.mmap(fd, total)
        finally:
            os.close(fd)
        # Hold the buffer export for the map's lifetime: it pins the mmap
        # (close() raises BufferError while exported), so a concurrent
        # store_* call can never dereference an unmapped segment.
        self._keepalive = ctypes.c_char.from_buffer(self._mm)
        self._addr = ctypes.addressof(self._keepalive)
        if create and not lib.store_is_initialized(self._addr):
            rc = lib.store_init(self._addr, total)
            if rc != 0:
                raise ArenaStoreError(f"store_init rc={rc}")
        else:
            # Attacher: wait for the creator's init publication.
            import time
            deadline = time.monotonic() + 10
            while not lib.store_is_initialized(self._addr):
                if time.monotonic() > deadline:
                    raise ArenaStoreError("segment never initialized")
                time.sleep(0.005)

    # -- producer ----------------------------------------------------------

    def create(self, object_id: bytes, size: int,
               allow_evict: bool = False) -> memoryview:
        err = ctypes.c_int(0)
        off = self._lib.store_create(self._addr, object_id, size,
                                     1 if allow_evict else 0,
                                     ctypes.byref(err))
        if off == 0:
            if err.value == 1:
                raise ArenaStoreError("object already exists")
            if err.value == 2:
                raise ArenaFullError(
                    f"arena full ({self.used_bytes()}/{self.capacity()})")
            raise ArenaStoreError(f"create failed err={err.value}")
        return memoryview(self._mm)[off:off + size]

    def seal(self, object_id: bytes):
        rc = self._lib.store_seal(self._addr, object_id)
        if rc != 0:
            raise ArenaStoreError(f"seal rc={rc}")

    # -- consumer ----------------------------------------------------------

    def get(self, object_id: bytes) -> Optional[memoryview]:
        """Pinned zero-copy view; call release(id) when done."""
        size = ctypes.c_uint64(0)
        off = self._lib.store_get(self._addr, object_id,
                                  ctypes.byref(size))
        if off == 0:
            return None
        return memoryview(self._mm)[off:off + size.value]

    def release(self, object_id: bytes):
        self._lib.store_release(self._addr, object_id)

    def size_of(self, object_id: bytes) -> Optional[int]:
        """Size of a sealed object without copying it out (store_get
        reports the size; the momentary pin is dropped immediately)."""
        size = ctypes.c_uint64(0)
        off = self._lib.store_get(self._addr, object_id,
                                  ctypes.byref(size))
        if off == 0:
            return None
        self._lib.store_release(self._addr, object_id)
        return size.value

    def delete(self, object_id: bytes) -> bool:
        return self._lib.store_delete(self._addr, object_id) == 0

    def contains(self, object_id: bytes) -> bool:
        return bool(self._lib.store_contains(self._addr, object_id))

    def used_bytes(self) -> int:
        return self._lib.store_used_bytes(self._addr)

    def capacity(self) -> int:
        return self._lib.store_capacity(self._addr)

    def close(self):
        try:
            del self._keepalive
            del self._addr
            self._mm.close()
        except (BufferError, AttributeError):
            pass  # exported views keep the map alive
