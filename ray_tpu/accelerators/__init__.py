from . import tpu
from .flops import PEAK_FLOPS, peak_flops, peak_flops_for_kind

__all__ = ["tpu", "PEAK_FLOPS", "peak_flops", "peak_flops_for_kind"]
