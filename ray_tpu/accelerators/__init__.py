from . import tpu

__all__ = ["tpu"]
