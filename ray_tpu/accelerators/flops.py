"""Peak-FLOP/s table: the single source of truth for MFU arithmetic.

Promoted out of bench.py so the offline bench and the live
accelerator-plane MFU gauge (`_internal/accel.py` report_step) divide by
the SAME denominator — two diverging tables would make "bench says 65%
MFU, the gauge says 40%" a permanent support thread.

Keys are device-kind substrings (matched against
``jax.Device.device_kind.lower()``, first match wins — more specific
generations first). Values are peak dense bf16 FLOP/s per chip from the
published TPU specs; "cpu" is a nominal 1 TFLOP/s so CPU smoke runs
still produce a finite MFU line.
"""

from __future__ import annotations

from typing import Optional

PEAK_FLOPS = {
    "v6e": 918e12,
    "v6": 918e12,
    "v5p": 459e12,
    "v5 lite": 197e12,  # device_kind spelling of v5e
    "v5litepod": 197e12,
    "v5e": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "cpu": 1e12,  # nominal, so CPU smoke runs produce a line
}

# Unknown accelerator kinds fall back to the v5e figure — wrong MFU
# beats no MFU, and the table is one entry away from correct.
DEFAULT_PEAK_FLOPS = 197e12


def peak_flops_for_kind(device_kind: Optional[str]) -> float:
    """Peak bf16 FLOP/s for a device-kind string (substring match)."""
    kind = (device_kind or "cpu").lower()
    for key, value in PEAK_FLOPS.items():
        if key in kind:
            return value
    return DEFAULT_PEAK_FLOPS


def peak_flops(device) -> float:
    """Peak bf16 FLOP/s for a ``jax.Device`` (or anything with a
    ``device_kind`` attribute)."""
    return peak_flops_for_kind(getattr(device, "device_kind", "cpu"))
