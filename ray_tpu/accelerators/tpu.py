"""TPU accelerator support.

Equivalent of the reference's TPU accelerator manager
(python/ray/_private/accelerators/tpu.py:199-578), made first-class:

- chip autodetection via /dev/accel* and /dev/vfio (mockable via glob)
- `TPU_VISIBLE_CHIPS` isolation for sub-host scheduling, including the
  host-bounds env rewriting that 1/2-chip subsets require
- slice name / topology / worker-id discovery from GCE metadata or GKE env
- per-node extra resources: `{<slice-name>: 1}` on every host of a slice and
  `TPU-<pod-type>-head: 1` on worker 0 — the gang-reservation anchor
- node labels `rtpu.io/tpu-{slice-name,worker-id,topology,pod-type}`
- `reserve_tpu_slice`: gang-reserve a whole slice via a placement group on
  the head resource (used by the Train library for multi-host SPMD groups)

Valid chip counts per worker mirror the reference: {1, 2, 4, 8}.
"""

from __future__ import annotations

import glob as _glob_module
import logging
import os
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

NUM_TPUS_PER_HOST = 8  # v5p default host size; detection below refines
TPU_VALID_CHIP_COUNTS = (1, 2, 4, 8)
TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_HEAD_RESOURCE_PREFIX = "TPU-"
TPU_HEAD_RESOURCE_SUFFIX = "-head"

# Node label keys (reference: ray.io/tpu-* labels, tpu.py:548-578)
LABEL_SLICE_NAME = "rtpu.io/tpu-slice-name"
LABEL_WORKER_ID = "rtpu.io/tpu-worker-id"
LABEL_TOPOLOGY = "rtpu.io/tpu-topology"
LABEL_POD_TYPE = "rtpu.io/tpu-pod-type"

# GKE env vars (reference: tpu.py:326-433)
GKE_TPU_ACCELERATOR_ENV = "TPU_ACCELERATOR_TYPE"
GKE_TPU_TOPOLOGY_ENV = "TPU_TOPOLOGY"
GKE_TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
GKE_TPU_NAME_ENV = "TPU_NAME"


def _visible_chip_count() -> Optional[int]:
    visible = os.environ.get(TPU_VISIBLE_CHIPS_ENV)
    if visible is None or visible == "":
        return None
    return len([c for c in visible.split(",") if c != ""])


def autodetect_num_chips(glob=_glob_module.glob) -> int:
    """Count TPU chips on this host (reference: tpu.py:226-245).

    Order: explicit RTPU_NUM_TPU_CHIPS override, TPU_VISIBLE_CHIPS
    restriction, /dev/accel* devices, /dev/vfio/*. JAX is deliberately never
    initialized from here — that would grab the host's chip lock."""
    override = os.environ.get("RTPU_NUM_TPU_CHIPS")
    if override is not None:
        return int(override)
    visible = _visible_chip_count()
    if visible is not None:
        return visible
    accel = glob("/dev/accel*")
    if accel:
        return len(accel)
    vfio = glob("/dev/vfio/[0-9]*")
    if vfio:
        return len(vfio)
    return 0


def validate_chip_request(num_chips: float) -> None:
    if num_chips < 1:
        return  # fractional/zero handled by generic resource accounting
    if int(num_chips) not in TPU_VALID_CHIP_COUNTS:
        raise ValueError(
            f"TPU chip requests must be one of {TPU_VALID_CHIP_COUNTS} "
            f"(got {num_chips}); a multi-host slice is reserved via "
            "reserve_tpu_slice / placement groups instead")


def visible_chips_env(chip_ids: List[int], total_on_host: int
                      ) -> Dict[str, str]:
    """Env for a worker granted a chip subset (reference: tpu.py:283-323).

    For 1- or 2-chip subsets libtpu also needs the host bounds rewritten so
    it doesn't try to initialize the full host topology."""
    env = {TPU_VISIBLE_CHIPS_ENV: ",".join(str(c) for c in chip_ids)}
    n = len(chip_ids)
    if n in (1, 2) and n < total_on_host:
        env["TPU_CHIPS_PER_HOST_BOUNDS"] = f"1,{n},1"
        env["TPU_HOST_BOUNDS"] = "1,1,1"
    return env


# ---------------------------------------------------------------------------
# Slice metadata (GKE env or GCE metadata server; both absent on dev boxes)
# ---------------------------------------------------------------------------

def _gce_metadata(key: str) -> Optional[str]:
    # Zero-egress environments have no metadata server; env override only.
    return os.environ.get(f"RTPU_FAKE_GCE_{key.upper().replace('-', '_')}")

def get_tpu_pod_type() -> Optional[str]:
    """e.g. 'v5p-64' — accelerator type of the slice this host is part of."""
    accel = os.environ.get(GKE_TPU_ACCELERATOR_ENV) \
        or _gce_metadata("accelerator-type")
    if accel:
        return accel.lower()
    return None


def get_tpu_topology() -> Optional[str]:
    return os.environ.get(GKE_TPU_TOPOLOGY_ENV) or _gce_metadata("topology")


def get_tpu_worker_id() -> Optional[int]:
    wid = os.environ.get(GKE_TPU_WORKER_ID_ENV) \
        or _gce_metadata("agent-worker-number")
    return int(wid) if wid is not None else None


def get_tpu_slice_name() -> Optional[str]:
    name = os.environ.get(GKE_TPU_NAME_ENV) or _gce_metadata("instance-id")
    return name


# Chips per host by generation. v5e/v6e multi-host slices use 4-chip hosts;
# their 8-chip slices (ct5lp-hightpu-8t / ct6e-standard-8t, topology 2x4)
# are a single 8-chip host and are special-cased below.
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4,
                   "v5litepod": 4, "v5e": 4, "v6e": 4}

_CHIP_SUFFIX_SINGLE_HOST_8 = ("v5litepod", "v5e", "v6e")

# Generations whose pod-type suffix counts TensorCores (2 per chip), not
# chips (reference: _private/accelerators/tpu.py SINGLE_CORE_TPU_TYPES —
# v2/v3/v4/v5p all name slices by core count: v5p-8 is one 4-chip host).
_CORE_SUFFIX_GENERATIONS = ("v2", "v3", "v4", "v5p")


def num_workers_in_slice(pod_type: str, topology: Optional[str]) -> int:
    """Hosts in the slice = total chips / chips per host."""
    try:
        chips = int(pod_type.rsplit("-", 1)[1])
    except (IndexError, ValueError):
        return 1
    generation = pod_type.split("-")[0]
    if generation in _CORE_SUFFIX_GENERATIONS:
        chips //= 2  # suffix counts TensorCores
    if generation in _CHIP_SUFFIX_SINGLE_HOST_8 and chips == 8:
        return 1  # one 8-chip host, not two 4-chip hosts
    per_host = _CHIPS_PER_HOST.get(generation, 4)
    chips_per_host = min(chips, per_host)
    return max(1, chips // chips_per_host)


def node_tpu_labels() -> Dict[str, str]:
    labels = {}
    pod_type = get_tpu_pod_type()
    if pod_type:
        labels[LABEL_POD_TYPE] = pod_type
    topology = get_tpu_topology()
    if topology:
        labels[LABEL_TOPOLOGY] = topology
    worker_id = get_tpu_worker_id()
    if worker_id is not None:
        labels[LABEL_WORKER_ID] = str(worker_id)
    slice_name = get_tpu_slice_name()
    if slice_name:
        labels[LABEL_SLICE_NAME] = slice_name
    return labels


def node_tpu_resources() -> Dict[str, float]:
    """Extra per-node resources advertising slice membership
    (reference: tpu.py:482-545)."""
    resources: Dict[str, float] = {}
    slice_name = get_tpu_slice_name()
    pod_type = get_tpu_pod_type()
    if slice_name and autodetect_num_chips() > 0:
        resources[slice_name] = 1.0
        if get_tpu_worker_id() == 0 and pod_type:
            resources[
                f"{TPU_HEAD_RESOURCE_PREFIX}{pod_type}"
                f"{TPU_HEAD_RESOURCE_SUFFIX}"] = 1.0
    return resources


def reserve_tpu_slice(pod_type: str, timeout: float = 600.0):
    """Gang-reserve one whole TPU slice; returns its slice name
    (reference: tpu.py:145-196).

    Places a 1-bundle placement group on the `TPU-<pod-type>-head` resource
    (only worker 0 of each slice advertises it), then reads the slice name
    from that node's labels. Training then targets every host of the slice
    via the `{slice_name: 1}` per-host resource."""
    import ray_tpu
    from ray_tpu.util.placement_group import placement_group

    head_resource = (f"{TPU_HEAD_RESOURCE_PREFIX}{pod_type}"
                     f"{TPU_HEAD_RESOURCE_SUFFIX}")
    pg = placement_group([{head_resource: 1}], strategy="STRICT_PACK",
                         name=f"tpu-slice-{pod_type}")
    ready = pg.wait(timeout)
    if not ready:
        raise TimeoutError(
            f"could not reserve a {pod_type} slice within {timeout}s")

    @ray_tpu.remote(num_cpus=0, resources={head_resource: 0.001},
                    scheduling_strategy=ray_tpu.util.scheduling_strategies.
                    PlacementGroupSchedulingStrategy(placement_group=pg))
    def _read_slice_name():
        return get_tpu_slice_name()

    name = ray_tpu.get(_read_slice_name.remote(), timeout=timeout)
    return pg, name
