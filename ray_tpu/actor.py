"""Actors (reference: python/ray/actor.py — ActorClass, ActorMethod).

`@ray_tpu.remote` on a class yields an ActorClass; `.remote(...)` registers
the actor with the GCS (which schedules, restarts, and tracks it) and returns
an ActorHandle. Method calls are pushed directly worker-to-worker with
sequence numbers; async actors (any coroutine method) run on the worker's
event loop with `max_concurrency` in-flight calls.
"""

from __future__ import annotations

import inspect
from typing import Any, Dict, Optional

from ._internal.config import CONFIG
from ._internal.core_worker import get_core_worker
from ._internal.ids import ActorID, TaskID
from ._internal.options import (normalize_strategy, resources_from_options,
                                validate_options)
from ._internal.runtime_env import upload_packages
from ._internal.task_spec import (ACTOR_CREATION_TASK, ACTOR_TASK,
                                  FunctionDescriptor, TaskSpec)
from .remote_function import _trace_ctx, pack_args


def method(**options):
    """Per-method options, e.g. `@ray_tpu.method(num_returns=2)`."""

    def decorator(fn):
        fn.__rtpu_method_options__ = options
        return fn
    return decorator


class ActorMethod:
    def __init__(self, handle: "ActorHandle", method_name: str,
                 options: Optional[Dict[str, Any]] = None):
        self._handle = handle
        self._method_name = method_name
        self._options = dict(options or {})

    def options(self, **new_options) -> "ActorMethod":
        merged = dict(self._options)
        merged.update(new_options)
        return ActorMethod(self._handle, self._method_name, merged)

    def remote(self, *args, **kwargs):
        return self._handle._submit_method(
            self._method_name, args, kwargs, self._options)

    def bind(self, *args, **kwargs):
        """Build a compiled-graph node (reference: dag method binding)."""
        from .dag.nodes import bind as _bind
        return _bind(self, *args, **kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor method {self._method_name} cannot be called directly; "
            "use .remote()")


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str,
                 method_options: Dict[str, Dict[str, Any]],
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_options = method_options
        self._max_task_retries = max_task_retries
        # constant across calls — built once, not per _submit_method
        self._descriptor = FunctionDescriptor("", class_name, "")
        # flat-wire templates per (method, num_returns, max_retries):
        # value = (core_worker, job_id, SpecTemplate) — see task_spec
        # make_template. ActorMethod objects are born per attribute
        # access, so the cache must live on the handle.
        self._tmpl_cache: Dict[Any, Any] = {}

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        return ActorMethod(self, name, self._method_options.get(name))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]})"

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_options, self._max_task_retries))

    def _submit_method(self, method_name: str, args, kwargs,
                       options: Dict[str, Any]):
        worker = get_core_worker()
        job_id = worker.current_job_id()
        num_returns = options.get("num_returns", 1)
        max_retries = options.get("max_task_retries",
                                  self._max_task_retries)
        spec = TaskSpec(
            task_id=TaskID.of(job_id),
            job_id=job_id,
            task_type=ACTOR_TASK,
            function=self._descriptor,
            args=pack_args(args, kwargs),
            num_returns=num_returns,
            resources={},
            owner_address=worker.rpc_address,
            owner_worker_id=worker.worker_id,
            name=f"{self._class_name}.{method_name}",
            actor_id=self._actor_id,
            method_name=method_name,
            max_retries=max_retries,
            trace_context=_trace_ctx(),
        )
        cache_key = (method_name, num_returns, max_retries)
        entry = self._tmpl_cache.get(cache_key)
        if entry is None or entry[0] is not worker or entry[1] != job_id:
            from ._internal.task_spec import make_template
            entry = (worker, job_id, make_template(spec))
            self._tmpl_cache[cache_key] = entry
        spec.flat_template = entry[2]
        refs = worker.submit_task(spec)
        if num_returns == "streaming":
            from ._internal.object_ref import ObjectRefGenerator
            return ObjectRefGenerator(generator_ref=refs[0])
        if num_returns == "dynamic":
            return refs[0]
        if num_returns == 0:
            return None
        return refs[0] if num_returns == 1 else refs

    def terminate(self):
        """Graceful exit: flush queued work, then exit the actor process."""
        return self._submit_method("__rtpu_terminate__", (), {}, {})


class ActorClass:
    def __init__(self, cls, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        validate_options(self._options, for_actor=True)
        self._descriptor = None
        self._descriptor_owner = None

    def options(self, **new_options) -> "ActorClass":
        merged = dict(self._options)
        merged.update(new_options)
        return ActorClass(self._cls, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"actor class {self._cls.__name__} cannot be instantiated "
            "directly; use .remote()")

    def _method_options(self) -> Dict[str, Dict[str, Any]]:
        out = {}
        for name, member in inspect.getmembers(self._cls):
            opts = getattr(member, "__rtpu_method_options__", None)
            if opts:
                out[name] = opts
        return out

    def _is_asyncio(self) -> bool:
        return any(inspect.iscoroutinefunction(m)
                   for _, m in inspect.getmembers(
                       self._cls, inspect.isfunction))

    def remote(self, *args, **kwargs) -> ActorHandle:
        worker = get_core_worker()
        job_id = worker.current_job_id()
        # The export cache must be per core-worker: a module-level actor
        # class outlives ray_tpu.shutdown()/init() cycles, and a stale
        # descriptor points at a previous cluster's function registry.
        if self._descriptor is None or self._descriptor_owner is not worker:
            self._descriptor = worker.function_manager.export(
                job_id, self._cls)
            self._descriptor_owner = worker
        opts = self._options
        actor_id = ActorID.of(job_id)
        lifetime = opts.get("lifetime")
        detached = lifetime == "detached"
        max_restarts = opts.get("max_restarts",
                                CONFIG.actor_max_restarts_default)
        spec = TaskSpec(
            task_id=TaskID.for_actor_creation(actor_id),
            job_id=job_id,
            task_type=ACTOR_CREATION_TASK,
            function=self._descriptor,
            args=pack_args(args, kwargs),
            num_returns=0,
            resources=resources_from_options(opts, default_num_cpus=1),
            owner_address=worker.rpc_address,
            owner_worker_id=worker.worker_id,
            name=opts.get("name") or self._cls.__name__,
            scheduling_strategy=normalize_strategy(
                opts.get("scheduling_strategy")),
            runtime_env=upload_packages(opts.get("runtime_env"),
                                        worker.gcs),
            label_selector=opts.get("label_selector") or {},
            actor_id=actor_id,
            max_restarts=max_restarts,
            max_task_retries=opts.get("max_task_retries", 0),
            max_concurrency=opts.get("max_concurrency", 1),
            concurrency_groups=opts.get("concurrency_groups") or {},
            is_asyncio=self._is_asyncio(),
            is_detached=detached,
        )
        # Reconnecting + idempotent (the GCS dedupes on actor_id): a GCS
        # restart mid-registration retries onto the new incarnation
        # instead of failing the creation.
        reply = worker.gcs.call_sync_reconnecting(
            "register_actor", spec=spec, name=opts.get("name", "") or "",
            namespace=opts.get("namespace", "") or "",
            is_detached=detached,
            get_if_exists=opts.get("get_if_exists", False),
            timeout=CONFIG.worker_start_timeout_s)
        return ActorHandle(reply["actor_id"], self._cls.__name__,
                           self._method_options(),
                           opts.get("max_task_retries", 0))


def get_actor(name: str, namespace: str = "") -> ActorHandle:
    worker = get_core_worker()
    info = worker.gcs.call_sync("get_actor_info", name=name,
                                namespace=namespace)
    if info is None or info["state"] == "DEAD":
        raise ValueError(f"actor {name!r} not found in namespace "
                         f"{namespace!r}")
    return ActorHandle(info["actor_id"], info.get("class_name", ""), {})

