"""ray_tpu.autoscaler — demand-driven cluster scaling
(reference: python/ray/autoscaler/v2 — Autoscaler autoscaler.py:47,
scheduler.py bin-packing, InstanceManager/Reconciler instance_manager/,
ICloudInstanceProvider node_provider.py:149, fake provider for tests
_private/fake_multi_node/node_provider.py)."""

from .autoscaler import Autoscaler, AutoscalerConfig, NodeTypeConfig
from .cluster_config import (ClusterHandle, load_cluster_config, up,
                             validate_cluster_config)
from .elastic import ElasticAutoscaler, ElasticConfig, ElasticMonitor
from .node_provider import FakeNodeProvider, NodeProvider

__all__ = ["Autoscaler", "AutoscalerConfig", "ElasticAutoscaler",
           "ElasticConfig", "ElasticMonitor", "FakeNodeProvider",
           "NodeProvider", "NodeTypeConfig", "ClusterHandle",
           "load_cluster_config", "validate_cluster_config", "up"]
