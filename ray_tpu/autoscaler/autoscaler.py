"""Autoscaler: declarative reconciliation of cluster size to demand
(reference: autoscaler/v2/autoscaler.py:47 Autoscaler.try_schedule →
scheduler.py ResourceDemandScheduler bin-packing; reconciler.py drives
instances toward the target; idle termination per
idle_timeout_node states).

One reconcile() pass:
 1. read unmet demand from the GCS (queued lease shapes + pending PG
    bundles, shipped up in raylet heartbeats),
 2. subtract capacity already free on live nodes,
 3. bin-pack the remainder onto the cheapest fitting node types
    (bounded by max_workers),
 4. launch via the provider; terminate nodes idle past the timeout
    (bounded by min_workers)."""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class NodeTypeConfig:
    name: str
    resources: Dict[str, float]
    min_workers: int = 0
    max_workers: int = 10
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class AutoscalerConfig:
    node_types: List[NodeTypeConfig]
    idle_timeout_s: float = 30.0
    max_launch_batch: int = 5


class Autoscaler:
    def __init__(self, config: AutoscalerConfig, provider, gcs_client):
        self.config = config
        self.provider = provider
        self.gcs = gcs_client
        self._idle_since: Dict[str, float] = {}  # node_id -> ts
        self.num_launches = 0
        self.num_terminations = 0

    # -- demand/supply snapshot -------------------------------------------

    def _snapshot(self):
        demand_info = self.gcs.call_sync("get_cluster_demand")
        view = self.gcs.call_sync("get_cluster_view")
        instances = self.provider.non_terminated_instances()
        return demand_info, view, instances

    # -- one reconcile pass ------------------------------------------------

    def reconcile(self) -> Dict[str, int]:
        demand_info, view, instances = self._snapshot()
        demands = [dict(d) for d in demand_info["task_demand"]] + \
            [dict(b) for b in demand_info["pg_demand"]]

        # 2. cancel out demand satisfiable by capacity already free.
        free: List[Dict[str, float]] = [
            dict(info.get("available", {})) for info in view.values()]
        unmet = []
        for demand in demands:
            placed = False
            for cap in free:
                if all(cap.get(k, 0.0) >= v for k, v in demand.items()):
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if not placed:
                unmet.append(demand)

        counts = self._count_by_type(instances)
        launched = 0

        # min_workers floor first (reference: scheduler enforces min counts).
        for nt in self.config.node_types:
            while counts.get(nt.name, 0) < nt.min_workers:
                self._launch(nt)
                counts[nt.name] = counts.get(nt.name, 0) + 1
                launched += 1

        # 3. bin-pack unmet demand onto new nodes.
        pending_caps: List[Dict[str, float]] = []
        for demand in unmet:
            placed = False
            for cap in pending_caps:
                if all(cap.get(k, 0.0) >= v for k, v in demand.items()):
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            node_type = self._pick_type(demand, counts)
            if node_type is None:
                logger.warning("autoscaler: demand %s unsatisfiable by any "
                               "node type under max_workers", demand)
                continue
            if launched >= self.config.max_launch_batch:
                break
            self._launch(node_type)
            counts[node_type.name] = counts.get(node_type.name, 0) + 1
            launched += 1
            cap = dict(node_type.resources)
            for k, v in demand.items():
                cap[k] = cap.get(k, 0.0) - v
            pending_caps.append(cap)

        # 4. idle termination.
        terminated = self._terminate_idle(view, instances, counts,
                                          bool(unmet))
        return {"launched": launched, "terminated": terminated,
                "unmet": len(unmet)}

    def _count_by_type(self, instances) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for info in instances.values():
            counts[info["node_type"]] = counts.get(info["node_type"], 0) + 1
        return counts

    def _pick_type(self, demand: Dict[str, float],
                   counts: Dict[str, int]) -> Optional[NodeTypeConfig]:
        """Smallest node type that fits the demand and is under its cap."""
        fitting = [
            nt for nt in self.config.node_types
            if all(nt.resources.get(k, 0.0) >= v for k, v in demand.items())
            and counts.get(nt.name, 0) < nt.max_workers
        ]
        if not fitting:
            return None
        return min(fitting, key=lambda nt: sum(nt.resources.values()))

    def _launch(self, node_type: NodeTypeConfig):
        logger.info("autoscaler: launching %s", node_type.name)
        self.provider.launch(node_type.name, dict(node_type.resources),
                             dict(node_type.labels))
        self.num_launches += 1

    def _terminate_idle(self, view, instances, counts,
                        has_unmet: bool) -> int:
        now = time.monotonic()
        terminated = 0
        node_to_instance = {info["node_id"]: iid
                            for iid, info in instances.items()}
        live_ids = set(view.keys())
        for node_id, info in view.items():
            total = info.get("total", {})
            avail = info.get("available", {})
            busy = any(avail.get(k, 0.0) < v for k, v in total.items())
            if busy or has_unmet:
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            if now - since < self.config.idle_timeout_s:
                continue
            instance_id = node_to_instance.get(node_id)
            if instance_id is None:
                # Cloud providers can't see raylet ids at launch time;
                # their nodes join carrying an rtpu-instance-id label
                # (gke_provider startup script) — match on that.
                labeled = (info.get("labels") or {}).get(
                    "rtpu-instance-id")
                if labeled in instances:
                    instance_id = labeled
            if instance_id is None:
                continue  # not ours (e.g. the head node)
            node_type = instances[instance_id]["node_type"]
            nt = next((t for t in self.config.node_types
                       if t.name == node_type), None)
            if nt is not None and counts.get(node_type, 0) <= nt.min_workers:
                continue
            logger.info("autoscaler: terminating idle node %s (%s)",
                        node_id[:12], node_type)
            self.provider.terminate(instance_id)
            counts[node_type] = counts.get(node_type, 0) - 1
            self._idle_since.pop(node_id, None)
            self.num_terminations += 1
            terminated += 1
        # Forget nodes that disappeared.
        for node_id in list(self._idle_since):
            if node_id not in live_ids:
                self._idle_since.pop(node_id, None)
        return terminated


class Monitor:
    """Background reconcile loop (reference: autoscaler v2 monitor.py)."""

    def __init__(self, autoscaler: Autoscaler, interval_s: float = 1.0):
        import threading
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-autoscaler")
        # Tracking-only: the reconcile loop is owned by the cluster
        # handle (ClusterHandle.down -> Monitor.stop), not node teardown.
        from .._internal.threads import register_daemon_thread
        register_daemon_thread(self._thread, joinable=False)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        from .._internal.backoff import Backoff
        bo = None  # armed while reconciles fail (GCS failover)
        while not self._stop.is_set():
            wait = self.interval_s
            try:
                self.autoscaler.reconcile()
                bo = None
            except Exception:  # noqa: BLE001 — keep reconciling
                logger.exception("autoscaler reconcile failed")
                if bo is None:
                    bo = Backoff(base_s=self.interval_s, max_s=30.0)
                wait = bo.next_delay() or 30.0
            self._stop.wait(wait)
