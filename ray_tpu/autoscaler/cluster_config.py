"""Declarative cluster YAML: `up`/`down` from a config file
(reference: `ray up cluster.yaml` — autoscaler/_private/commands.py
create_or_update_cluster/teardown_cluster; YAML schema
autoscaler/ray-schema.json: cluster_name / provider /
available_node_types{resources,min_workers,max_workers} /
head_node_type / idle_timeout_minutes).

The config resolves to: a head node, a NodeProvider built from
`provider.type`, and an Autoscaler + Monitor reconciling worker counts
between each type's min/max against live GCS demand. `up()` returns a
handle whose `.down()` tears the whole thing back down (reference:
teardown_cluster)."""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Optional

from .autoscaler import (Autoscaler, AutoscalerConfig, Monitor,
                         NodeTypeConfig)


def load_cluster_config(path: str) -> Dict[str, Any]:
    """Parse + validate a cluster YAML; returns the normalized dict."""
    import yaml
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    return validate_cluster_config(raw)


def validate_cluster_config(raw: Dict[str, Any]) -> Dict[str, Any]:
    if not isinstance(raw, dict):
        raise ValueError("cluster config must be a mapping")
    for field in ("cluster_name", "provider", "available_node_types",
                  "head_node_type"):
        if field not in raw:
            raise ValueError(f"cluster config missing {field!r}")
    types = raw["available_node_types"]
    if not isinstance(types, dict) or not types:
        raise ValueError("available_node_types must be a non-empty map")
    head_type = raw["head_node_type"]
    if head_type not in types:
        raise ValueError(
            f"head_node_type {head_type!r} not in available_node_types")
    for name, spec in types.items():
        if "resources" not in spec:
            raise ValueError(f"node type {name!r} missing resources")
        if int(spec.get("min_workers", 0)) > \
                int(spec.get("max_workers", 0)) and name != head_type:
            raise ValueError(
                f"node type {name!r}: min_workers > max_workers")
    provider = raw["provider"]
    if "type" not in provider:
        raise ValueError("provider.type is required")
    if provider["type"] not in ("fake", "gke_tpu"):
        raise ValueError(
            f"unknown provider.type {provider['type']!r} "
            "(supported: fake, gke_tpu)")
    return raw


def _build_provider(config: Dict[str, Any], cluster):
    kind = config["provider"]["type"]
    if kind == "fake":
        from .node_provider import FakeNodeProvider
        return FakeNodeProvider(cluster)
    from .gke_provider import GkeTpuNodeProvider
    opts = {k: v for k, v in config["provider"].items() if k != "type"}
    return GkeTpuNodeProvider(cluster_name=config["cluster_name"],
                              **opts)


def _worker_node_types(config: Dict[str, Any]):
    head_type = config["head_node_type"]
    out = []
    for name, spec in config["available_node_types"].items():
        if name == head_type:
            continue
        out.append(NodeTypeConfig(
            name=name,
            resources={k: float(v)
                       for k, v in spec["resources"].items()},
            min_workers=int(spec.get("min_workers", 0)),
            max_workers=int(spec.get("max_workers", 0)),
            labels=dict(spec.get("labels") or {})))
    return out


@dataclasses.dataclass
class ClusterHandle:
    config: Dict[str, Any]
    cluster: Any
    provider: Any
    autoscaler: Autoscaler
    monitor: Monitor

    def down(self, shutdown_cluster: bool = True):
        """teardown_cluster: stop reconciling, terminate every provider
        instance, then (optionally) the head."""
        self.monitor.stop()
        for instance_id in list(
                self.provider.non_terminated_instances()):
            try:
                self.provider.terminate(instance_id)
            except Exception:  # noqa: BLE001 — best-effort teardown
                logging.getLogger(__name__).debug(
                    "instance terminate failed", exc_info=True)
        if shutdown_cluster:
            self.cluster.shutdown()


def up(config_or_path, *, cluster=None, connect: bool = True,
       monitor_interval_s: float = 1.0) -> ClusterHandle:
    """Bring the described cluster up. With the fake provider a head
    Cluster is created in-process (pass `cluster=` to adopt one);
    min_workers of every type are pre-provisioned, then the Monitor
    keeps counts reconciled against demand."""
    if isinstance(config_or_path, str):
        config = load_cluster_config(config_or_path)
    else:
        config = validate_cluster_config(config_or_path)

    if cluster is None:
        from ..cluster_utils import Cluster
        head_spec = config["available_node_types"][
            config["head_node_type"]]
        cluster = Cluster(head_node_args={
            "resources": {k: float(v)
                          for k, v in head_spec["resources"].items()}})
        if connect:
            cluster.connect()

    provider = _build_provider(config, cluster)
    idle_s = float(config.get("idle_timeout_minutes", 0.5)) * 60.0
    as_config = AutoscalerConfig(
        node_types=_worker_node_types(config),
        idle_timeout_s=idle_s,
        max_launch_batch=int(config.get("max_launch_batch", 5)))

    # The reconciler talks to the HEAD's GCS directly (not the calling
    # process's driver connection): up(connect=False) and adopted
    # clusters must reconcile against the cluster the YAML described,
    # not whatever this process happens to be connected to.
    from .._internal.gcs_client import GcsClient
    gcs = GcsClient(tuple(cluster.gcs_address))
    autoscaler = Autoscaler(as_config, provider, gcs)
    monitor = Monitor(autoscaler, interval_s=monitor_interval_s)
    monitor.start()
    return ClusterHandle(config=config, cluster=cluster,
                         provider=provider, autoscaler=autoscaler,
                         monitor=monitor)
