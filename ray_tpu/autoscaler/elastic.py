"""Elastic autoscaler: a metric-driven closed loop with hysteresis
(reference: autoscaler/v2 reconciler driven by the GCS autoscaler state
manager — gcs_autoscaler_state_manager.h — instead of raw config).

Where the config-driven :class:`~ray_tpu.autoscaler.Autoscaler` bin-packs
the *instantaneous* demand snapshot, this reconciler closes the loop on
flight-recorder signals and refuses to act on transients:

- **scale-up** fires only after unmet demand has persisted AND the
  oldest pending lease is older than ``queue_age_up_s`` for
  ``up_delay_s`` straight (a deep-but-fresh queue is a burst the
  current fleet will absorb; an OLD queue is starvation),
- **scale-in** fires only after a node has been fully idle (all
  resources free, zero queued leases) for ``down_delay_s`` — and it is
  routed through the GCS **drain** path (fence → actor migration →
  in-flight leases finish) before the provider terminates the machine,
  so shrink never kills running work,
- errors back off jittered-exponentially (the shared
  ``backoff.Backoff`` primitive, rtpulint rule L009) instead of
  spinning the failure at tick rate.

Both delays are the hysteresis that keeps an oscillating queue from
flapping the fleet — unit-tested in tests/test_fleet_ops.py against a
synthetic oscillating signal.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Dict, List, Optional

from .autoscaler import NodeTypeConfig
from .._internal.config import CONFIG

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class ElasticConfig:
    node_types: List[NodeTypeConfig]
    # Hysteresis thresholds. None = the CONFIG defaults (overridable
    # per-cluster via RTPU_AUTOSCALE_*).
    queue_age_up_s: Optional[float] = None
    up_delay_s: Optional[float] = None
    down_delay_s: Optional[float] = None
    max_launch_batch: int = 4
    drain_timeout_s: Optional[float] = None

    def resolved(self) -> "ElasticConfig":
        return dataclasses.replace(
            self,
            queue_age_up_s=self.queue_age_up_s
            if self.queue_age_up_s is not None
            else CONFIG.autoscale_queue_age_up_s,
            up_delay_s=self.up_delay_s if self.up_delay_s is not None
            else CONFIG.autoscale_up_delay_s,
            down_delay_s=self.down_delay_s
            if self.down_delay_s is not None
            else CONFIG.autoscale_down_delay_s,
            drain_timeout_s=self.drain_timeout_s
            if self.drain_timeout_s is not None
            else CONFIG.drain_timeout_s)


class ElasticAutoscaler:
    """One reconcile() pass reads the GCS autoscaler state (ONE rpc:
    per-node capacity/queue/drain rows + aggregate unmet demand),
    updates the hysteresis clocks, and acts only on signals that have
    persisted. Scale-in drains before it terminates."""

    def __init__(self, config: ElasticConfig, provider, gcs_client,
                 clock=time.monotonic):
        self.config = config.resolved()
        self.provider = provider
        self.gcs = gcs_client
        self._clock = clock
        # Hysteresis state: when the scale-up signal first turned on,
        # and per-node when full idleness began.
        self._pressure_since: Optional[float] = None
        self._idle_since: Dict[str, float] = {}
        self.num_launches = 0
        self.num_drains = 0
        self.num_terminations = 0

    # -- signals -----------------------------------------------------------

    @staticmethod
    def _unmet_demand(state: Dict[str, Any]) -> List[Dict[str, float]]:
        """Demand not satisfiable by capacity already free on live,
        non-draining nodes (draining capacity is leaving — counting it
        would starve the scale-up exactly when a drain needs cover)."""
        free = [dict(n.get("available", {}))
                for n in state["nodes"].values()
                if not n.get("draining")]
        unmet = []
        for demand in [dict(d) for d in state.get("task_demand", ())] + \
                [dict(b) for b in state.get("pg_demand", ())]:
            placed = False
            for cap in free:
                if all(cap.get(k, 0.0) >= v for k, v in demand.items()):
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if not placed:
                unmet.append(demand)
        return unmet

    # -- one pass ----------------------------------------------------------

    def reconcile(self) -> Dict[str, int]:
        state = self.gcs.call_sync("get_autoscaler_state")
        now = self._clock()
        cfg = self.config
        unmet = self._unmet_demand(state)
        max_age = max((n.get("queue_age_s", 0.0)
                       for n in state["nodes"].values()), default=0.0)
        counts = self._count_by_type()

        # ---- scale-up with hysteresis -------------------------------
        launched = 0
        pressure = bool(unmet) and max_age >= cfg.queue_age_up_s
        if pressure:
            if self._pressure_since is None:
                self._pressure_since = now
            if now - self._pressure_since >= cfg.up_delay_s:
                launched = self._launch_for(unmet, counts)
                if launched:
                    # One action per persisted signal: the clock re-arms
                    # so the NEXT launch again needs a persisted signal
                    # (the new capacity needs time to register).
                    self._pressure_since = None
        else:
            self._pressure_since = None

        # ---- scale-in with hysteresis, via drain --------------------
        drained = self._scale_in(state, counts, has_unmet=bool(unmet),
                                 now=now)
        return {"launched": launched, "drained": drained,
                "unmet": len(unmet), "max_queue_age_s": max_age}

    def _count_by_type(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for info in self.provider.non_terminated_instances().values():
            counts[info["node_type"]] = counts.get(info["node_type"], 0) + 1
        return counts

    def _launch_for(self, unmet: List[Dict[str, float]],
                    counts: Dict[str, int]) -> int:
        from .._internal.runtime_metrics import runtime_metrics
        launched = 0
        pending_caps: List[Dict[str, float]] = []
        for demand in unmet:
            placed = False
            for cap in pending_caps:
                if all(cap.get(k, 0.0) >= v for k, v in demand.items()):
                    for k, v in demand.items():
                        cap[k] = cap.get(k, 0.0) - v
                    placed = True
                    break
            if placed:
                continue
            fitting = [
                nt for nt in self.config.node_types
                if all(nt.resources.get(k, 0.0) >= v
                       for k, v in demand.items())
                and counts.get(nt.name, 0) < nt.max_workers]
            if not fitting:
                logger.warning("elastic autoscaler: demand %s "
                               "unsatisfiable under max_workers", demand)
                continue
            if launched >= self.config.max_launch_batch:
                break
            nt = min(fitting, key=lambda t: sum(t.resources.values()))
            logger.info("elastic autoscaler: launching %s (unmet=%s)",
                        nt.name, demand)
            self.provider.launch(nt.name, dict(nt.resources),
                                 dict(nt.labels))
            runtime_metrics().autoscale_decisions.inc(
                tags={"action": "launch"})
            counts[nt.name] = counts.get(nt.name, 0) + 1
            self.num_launches += 1
            launched += 1
            cap = dict(nt.resources)
            for k, v in demand.items():
                cap[k] = cap.get(k, 0.0) - v
            pending_caps.append(cap)
        return launched

    def _scale_in(self, state: Dict[str, Any], counts: Dict[str, int],
                  has_unmet: bool, now: float) -> int:
        from .._internal.runtime_metrics import runtime_metrics
        cfg = self.config
        instances = self.provider.non_terminated_instances()
        node_to_instance = {info.get("node_id"): iid
                            for iid, info in instances.items()}
        drained = 0
        live = set()
        for node_id, info in state["nodes"].items():
            live.add(node_id)
            if info.get("is_head") or info.get("draining"):
                continue
            total = info.get("total", {})
            avail = info.get("available", {})
            busy = any(avail.get(k, 0.0) < v for k, v in total.items()) \
                or info.get("queue_depth", 0) > 0
            if busy or has_unmet:
                # Pending demand anywhere holds ALL idle nodes: tearing
                # down capacity the queue is about to need just trades
                # a queue wait for a cold boot.
                self._idle_since.pop(node_id, None)
                continue
            since = self._idle_since.setdefault(node_id, now)
            if now - since < cfg.down_delay_s:
                continue
            instance_id = node_to_instance.get(node_id)
            if instance_id is None:
                labeled = (info.get("labels") or {}).get(
                    "rtpu-instance-id")
                if labeled in instances:
                    instance_id = labeled
            if instance_id is None:
                continue  # not ours (e.g. a manually added node)
            node_type = instances[instance_id]["node_type"]
            nt = next((t for t in self.config.node_types
                       if t.name == node_type), None)
            if nt is not None and \
                    counts.get(node_type, 0) <= nt.min_workers:
                continue
            logger.info("elastic autoscaler: draining idle node %s "
                        "(%s) before terminate", node_id[:12], node_type)
            report = self.gcs.call_sync(
                "drain_node", node_id=node_id,
                timeout_s=cfg.drain_timeout_s, exit_process=False,
                timeout=cfg.drain_timeout_s + 60)
            runtime_metrics().autoscale_decisions.inc(
                tags={"action": "drain_in"})
            self.num_drains += 1
            if isinstance(report, dict) and report.get("error"):
                # Failed drain must not strand a FENCED node that is
                # never terminated, never retried (the draining flag
                # excludes it from every future reconcile), and never
                # takes work again: lower the fence so the node returns
                # to service, and keep the idle clock so a later pass
                # retries the scale-in.
                logger.warning("drain of %s failed (%s); canceling the "
                               "fence and keeping the node",
                               node_id[:12], report["error"])
                try:
                    self.gcs.call_sync("drain_node", node_id=node_id,
                                       cancel=True, timeout=30)
                except Exception:  # noqa: BLE001 — best-effort unfence
                    logger.warning("drain cancel of %s failed too",
                                   node_id[:12], exc_info=True)
                continue
            self.provider.terminate(instance_id)
            runtime_metrics().autoscale_decisions.inc(
                tags={"action": "terminate"})
            counts[node_type] = counts.get(node_type, 0) - 1
            self._idle_since.pop(node_id, None)
            self.num_terminations += 1
            drained += 1
        for node_id in list(self._idle_since):
            if node_id not in live:
                self._idle_since.pop(node_id, None)
        return drained


class ElasticMonitor:
    """Background reconcile loop for the elastic autoscaler (the
    metric-driven sibling of autoscaler.Monitor). Failing ticks back
    off jittered-exponentially; healthy ticks run at ``interval_s``."""

    def __init__(self, autoscaler: ElasticAutoscaler,
                 interval_s: float = 1.0):
        import threading
        self.autoscaler = autoscaler
        self.interval_s = interval_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="rtpu-elastic-autoscaler")
        from .._internal.threads import register_daemon_thread
        register_daemon_thread(self._thread, joinable=False)

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()

    def _loop(self):
        from .._internal.backoff import Backoff
        bo = None  # armed while reconciles fail (GCS failover window)
        while not self._stop.is_set():
            wait = self.interval_s
            try:
                self.autoscaler.reconcile()
                bo = None
            except Exception:  # noqa: BLE001 — keep reconciling
                logger.exception("elastic reconcile failed")
                if bo is None:
                    bo = Backoff(base_s=self.interval_s, max_s=30.0)
                wait = bo.next_delay() or 30.0
            self._stop.wait(wait)
