"""GKE / Cloud-TPU node provider
(reference: autoscaler/_private/gcp/node_provider.py — GCPNodeProvider
speaking the GCE + TPU REST APIs; kuberay/ for the GKE path. This
provider speaks the Cloud TPU v2 REST shapes —
tpu.googleapis.com/v2/projects/{p}/locations/{z}/nodes — through an
injectable transport so CI exercises the full request/response cycle
against a recorded mock without cloud credentials or egress).

A "node" here is one TPU slice (the scheduler's atomic unit on TPU —
SURVEY §7 step 4): create provisions a slice whose hosts each start a
raylet; terminate deletes the slice. The autoscaler drives it exactly
like any other provider (launch/terminate/list)."""

from __future__ import annotations

import json
import logging
import threading
import uuid
from typing import Any, Callable, Dict, List, Optional

from .node_provider import NodeProvider

logger = logging.getLogger(__name__)

TPU_API = "https://tpu.googleapis.com/v2"

#: node_type name -> (acceleratorType, hosts-per-slice) for common slices
KNOWN_SLICES = {
    "v5p-8": ("v5p-8", 1),
    "v5p-16": ("v5p-16", 2),
    "v5p-32": ("v5p-32", 4),
    "v5p-64": ("v5p-64", 8),
    "v5e-4": ("v5litepod-4", 1),
    "v5e-8": ("v5litepod-8", 2),
}


def _http_transport(method: str, url: str,
                    body: Optional[dict] = None) -> dict:
    """Default transport: urllib against the real API (requires ADC
    metadata credentials on a GCE/GKE host). Tests inject a mock."""
    import urllib.request
    data = json.dumps(body).encode() if body is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    req.add_header("Content-Type", "application/json")
    token = _metadata_token()
    if token:
        req.add_header("Authorization", f"Bearer {token}")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read() or b"{}")


def _metadata_token() -> Optional[str]:
    """Access token from the GCE metadata server (reference:
    gcp/node_provider.py uses google-auth; the metadata endpoint is the
    dependency-free equivalent on-cluster)."""
    import urllib.request
    try:
        req = urllib.request.Request(
            "http://metadata.google.internal/computeMetadata/v1/instance/"
            "service-accounts/default/token",
            headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=2) as resp:
            return json.loads(resp.read())["access_token"]
    except Exception:  # noqa: BLE001 — not on GCP
        return None


class GkeTpuNodeProvider(NodeProvider):
    """TPU-slice lifecycle over the Cloud TPU v2 REST shapes.

    `transport(method, url, body) -> dict` is injectable; the default
    hits the real API. Each launch creates one slice node named
    rtpu-<cluster>-<uuid>; the node's metadata.startup-script joins the
    slice's hosts to the cluster (head address baked in)."""

    def __init__(self, project: str, zone: str, *,
                 cluster_name: str = "rtpu",
                 head_address: str = "",
                 runtime_version: str = "tpu-ubuntu2204-base",
                 transport: Callable[..., dict] = _http_transport):
        self.project = project
        self.zone = zone
        self.cluster_name = cluster_name
        self.head_address = head_address
        self.runtime_version = runtime_version
        self._transport = transport
        self._lock = threading.Lock()
        # instance_id -> {"node_type", "name", "node_id"}
        self._instances: Dict[str, Dict[str, Any]] = {}

    # -- REST plumbing -----------------------------------------------------

    @property
    def _parent(self) -> str:
        return f"projects/{self.project}/locations/{self.zone}"

    def _node_url(self, name: str = "") -> str:
        base = f"{TPU_API}/{self._parent}/nodes"
        return f"{base}/{name}" if name else base

    # -- NodeProvider ------------------------------------------------------

    def launch(self, node_type: str, resources: Dict[str, float],
               labels: Dict[str, str]) -> str:
        accel, _hosts = KNOWN_SLICES.get(node_type, (node_type, 1))
        name = f"rtpu-{self.cluster_name}-{uuid.uuid4().hex[:8]}"
        body = {
            "acceleratorType": accel,
            "runtimeVersion": self.runtime_version,
            "labels": dict(labels, **{
                "rtpu-cluster": self.cluster_name,
                "rtpu-node-type": node_type.replace("_", "-"),
            }),
            "metadata": {
                "startup-script": self._startup_script(name),
            },
            "networkConfig": {"enableExternalIps": False},
        }
        reply = self._transport(
            "POST", f"{self._node_url()}?nodeId={name}", body)
        # The API returns a long-running operation; the slice shows up in
        # list() as CREATING then READY (reference: the GCP provider polls
        # the operation the same way).
        logger.info("TPU slice create %s -> %s", name,
                    reply.get("name", "operation"))
        instance_id = name
        with self._lock:
            self._instances[instance_id] = {
                "node_type": node_type, "name": name, "node_id": None}
        return instance_id

    def terminate(self, instance_id: str) -> bool:
        with self._lock:
            info = self._instances.pop(instance_id, None)
        if info is None:
            return False
        try:
            self._transport("DELETE", self._node_url(info["name"]))
        except Exception as e:  # noqa: BLE001
            logger.warning("TPU slice delete %s failed: %s",
                           info["name"], e)
            with self._lock:
                self._instances[instance_id] = info
            return False
        return True

    def non_terminated_instances(self) -> Dict[str, Dict[str, Any]]:
        """Reconciles the local table against nodes.list — slices that
        vanished server-side (preempted, deleted out-of-band) drop out,
        matching the reference provider's non_terminated_nodes."""
        try:
            reply = self._transport("GET", self._node_url())
        except Exception as e:  # noqa: BLE001
            logger.warning("TPU nodes.list failed: %s", e)
            with self._lock:
                return {iid: {"node_type": i["node_type"],
                              "node_id": i["node_id"]}
                        for iid, i in self._instances.items()}
        live = {}
        for node in reply.get("nodes", []):
            name = node.get("name", "").rsplit("/", 1)[-1]
            state = node.get("state", "")
            if state in ("DELETING", "TERMINATED"):
                continue
            live[name] = node
        with self._lock:
            gone = [iid for iid, i in self._instances.items()
                    if i["name"] not in live]
            for iid in gone:
                logger.info("TPU slice %s vanished server-side", iid)
                self._instances.pop(iid, None)
            return {iid: {"node_type": i["node_type"],
                          "node_id": i["node_id"],
                          "state": live[i["name"]].get("state")}
                    for iid, i in self._instances.items()}

    # -- helpers -----------------------------------------------------------

    def _startup_script(self, instance_name: str = "") -> str:
        # the rtpu-instance-id label lets the autoscaler map the joined
        # raylet back to this slice for idle termination
        label = f" --labels rtpu-instance-id={instance_name}" \
            if instance_name else ""
        return (
            "#!/bin/bash\n"
            "python -m ray_tpu.cli start "
            f"--address {self.head_address} --num-tpus auto{label}\n")


class RecordedTpuApi:
    """Recorded mock of the Cloud TPU v2 REST surface for tests
    (reference pattern: tests/accelerators mock the GCE metadata the
    same way). Use `provider = GkeTpuNodeProvider(..., transport=mock)`.
    Nodes move CREATING -> READY after `ready_after` list calls."""

    def __init__(self, ready_after: int = 1):
        self.nodes: Dict[str, dict] = {}
        self.calls: List[tuple] = []
        self._ready_after = ready_after
        self._list_count = 0

    def __call__(self, method: str, url: str,
                 body: Optional[dict] = None) -> dict:
        self.calls.append((method, url, body))
        if method == "POST":
            name = url.rsplit("nodeId=", 1)[-1]
            self.nodes[name] = dict(body or {}, name=name,
                                    state="CREATING", _lists=0)
            return {"name": f"operations/create-{name}"}
        if method == "DELETE":
            name = url.rsplit("/", 1)[-1]
            if name not in self.nodes:
                raise RuntimeError(f"404 node {name}")
            self.nodes[name]["state"] = "DELETING"
            del self.nodes[name]
            return {"name": f"operations/delete-{name}"}
        if method == "GET":
            self._list_count += 1
            out = []
            for node in self.nodes.values():
                node["_lists"] += 1
                if node["state"] == "CREATING" and \
                        node["_lists"] > self._ready_after:
                    node["state"] = "READY"
                out.append({k: v for k, v in node.items()
                            if k != "_lists"})
            return {"nodes": out}
        raise ValueError(f"unsupported {method}")
