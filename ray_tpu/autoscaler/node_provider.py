"""Node providers
(reference: autoscaler/v2/instance_manager/node_provider.py:149
ICloudInstanceProvider ABC + the v1-adapter; test double:
_private/fake_multi_node/node_provider.py FakeMultiNodeProvider).

A provider owns machine lifecycle only — launch/terminate/list. The
autoscaler decides WHAT to launch; the raylet on the new machine registers
itself with the GCS. The fake provider backs "machines" with extra raylet
subprocesses on this host (cluster_utils), which is also how the TPU
provider maps: one "node" = one TPU host joining the slice."""

from __future__ import annotations

import abc
import threading
import uuid
from typing import Any, Dict, List, Optional


class NodeProvider(abc.ABC):
    @abc.abstractmethod
    def launch(self, node_type: str, resources: Dict[str, float],
               labels: Dict[str, str]) -> str:
        """Start one node of `node_type`; returns a provider instance id."""

    @abc.abstractmethod
    def terminate(self, instance_id: str) -> bool:
        ...

    @abc.abstractmethod
    def non_terminated_instances(self) -> Dict[str, Dict[str, Any]]:
        """instance_id -> {"node_type": ..., "node_id": <raylet id or None>}"""


class FakeNodeProvider(NodeProvider):
    """Launches extra raylet subprocesses on this host (reference:
    FakeMultiNodeProvider — the autoscaler test substrate)."""

    def __init__(self, cluster):
        """cluster: a ray_tpu.cluster_utils.Cluster (already connected)."""
        self._cluster = cluster
        self._lock = threading.Lock()
        self._instances: Dict[str, Dict[str, Any]] = {}

    def launch(self, node_type: str, resources: Dict[str, float],
               labels: Dict[str, str]) -> str:
        instance_id = f"fake-{uuid.uuid4().hex[:8]}"
        num_cpus = int(resources.get("CPU", 1))
        extra = {k: v for k, v in resources.items() if k != "CPU"} or None
        node = self._cluster.add_node(
            num_cpus=num_cpus, resources=extra,
            labels=dict(labels, **{"ray.io/node-type": node_type}))
        with self._lock:
            self._instances[instance_id] = {
                "node_type": node_type, "node": node,
                "node_id": node.node_id,
            }
        return instance_id

    def terminate(self, instance_id: str) -> bool:
        with self._lock:
            info = self._instances.pop(instance_id, None)
        if info is None:
            return False
        self._cluster.remove_node(info["node"], allow_graceful=True)
        return True

    def non_terminated_instances(self) -> Dict[str, Dict[str, Any]]:
        with self._lock:
            return {iid: {"node_type": i["node_type"],
                          "node_id": i["node_id"]}
                    for iid, i in self._instances.items()}
