"""Command-line interface
(reference: python/ray/scripts/scripts.py — `ray start` :679, stop,
status, job submit/logs/stop, `ray list ...` via util/state/state_cli.py,
`ray timeline`).

Usage: python -m ray_tpu.cli <command> ...

  start --head [--num-cpus N] [--port P] [--dashboard]   run a head node
  start --address HOST:PORT [--num-cpus N]               join as a worker
  stop                                                   stop local nodes
  status   [--address ...]                               cluster resources
  list     {nodes,actors,tasks,placement_groups,objects,workers,jobs}
  memory   [--json] [--limit N]                          cluster memory report
  events   [--type T] [--json] [--limit N]               cluster event log
  timeline [--output FILE] [--train|--serve]             chrome trace
  requests [--by-tenant|--by-route] [--why ID] [--json]  serve request folds
  stragglers [--json] [--limit N]                        skew/straggler view
  alerts   [--rule R] [--severity S] [--json]            SLO alert table
  trace    [TRACE_ID] [--json] [--logs]                  span tree / list
  logs     [--task|--actor|--job|--node|--level|--grep]  cluster log search
           [--tail N] [--follow] [--json]                (worker ring query)
  profile  [--duration S] [--hz N] [--format F]          cluster CPU profile
           [--node ID] [--pid P] [--task T] [-o FILE]    (merged flamegraph)
  stack    [--node ID] [--json]                          fleet stack dump
  devices  [--json]                                      per-device HBM /
                                                         compile / step+MFU
  dashboard                                              start + print URL
  submit   [--wait] -- ENTRYPOINT...                     submit a job
  job      {logs,stop,list} [ID]
  chaos    {show,set,clear,kill-gcs,kill-worker}         fault injection
           [--spec S] [--seed N] [--pid P]               drills / failover
  perf     [--quick]                                     microbenchmarks

The head address is written to /tmp/rtpu/head_address; commands default
to it so `--address` is rarely needed (reference: ray's address file in
the session dir)."""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time

ADDRESS_FILE = "/tmp/rtpu/head_address"


def _write_address(address: str):
    os.makedirs(os.path.dirname(ADDRESS_FILE), exist_ok=True)
    with open(ADDRESS_FILE, "w") as f:
        f.write(address)


def _resolve_address(arg) -> str:
    if arg:
        return arg
    try:
        with open(ADDRESS_FILE) as f:
            return f.read().strip()
    except FileNotFoundError:
        raise SystemExit(
            "no --address given and no head found "
            f"({ADDRESS_FILE} missing); run `python -m ray_tpu.cli "
            "start --head` first")


def _connect(args):
    import ray_tpu
    if ray_tpu.is_initialized():
        return
    ray_tpu.init(address=_resolve_address(getattr(args, "address", None)),
                 ignore_reinit_error=True)


# -- commands ---------------------------------------------------------------

def cmd_start(args):
    from ray_tpu._internal.node import Node, default_resources

    resources = default_resources(args.num_cpus, None)
    if args.head:
        node = Node(head=True, resources=resources)
        node.start()
        address = f"{node.gcs_address[0]}:{node.gcs_address[1]}"
        _write_address(address)
        print(f"head started; GCS at {address}", flush=True)
        if args.dashboard:
            import ray_tpu
            from ray_tpu.dashboard import start_dashboard
            ray_tpu.init(address=address, ignore_reinit_error=True)
            print(f"dashboard at {start_dashboard()}", flush=True)
        print("press Ctrl-C to stop", flush=True)
        _block_until_signal()
        node.stop()
        return
    address = _resolve_address(args.address)
    host, port = address.rsplit(":", 1)
    from ray_tpu._internal.gcs_client import GcsClient
    probe = GcsClient((host, int(port)))
    nodes = probe.call_sync("get_all_nodes")
    session = next((n.get("session_name") for n in nodes
                    if n.get("is_head")), "connected")
    index = max((n.get("node_index", 0) for n in nodes), default=0) + 1
    from ray_tpu._internal import raylet_main
    sys.argv = ["raylet"]
    raylet_main.main([
        "--gcs-address", address, "--session", session or "connected",
        "--node-index", str(index),
        "--resources", json.dumps(resources),
    ])


def _block_until_signal():
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    while not stop:
        time.sleep(0.5)


def cmd_up(args):
    """`ray up cluster.yaml` analog (reference:
    autoscaler/_private/commands.py create_or_update_cluster): start a
    head + autoscaler from a declarative YAML and reconcile until
    interrupted."""
    from ray_tpu.autoscaler.cluster_config import load_cluster_config, up

    config = load_cluster_config(args.config)
    workers = {name: (spec.get("min_workers", 0),
                      spec.get("max_workers", 0))
               for name, spec in config["available_node_types"].items()
               if name != config["head_node_type"]}
    print(f"cluster {config['cluster_name']!r}: provider "
          f"{config['provider']['type']}, head "
          f"{config['head_node_type']}, workers {workers}", flush=True)
    if args.validate_only:
        print("config valid", flush=True)
        return
    handle = up(config)
    address = handle.cluster.address
    _write_address(address)
    print(f"head started; GCS at {address}; autoscaler reconciling "
          "(Ctrl-C to tear down)", flush=True)
    _block_until_signal()
    handle.down()


def cmd_down(args):
    """`ray down` analog (reference: commands.py teardown_cluster):
    terminate every provider instance named by the YAML."""
    from ray_tpu.autoscaler.cluster_config import (_build_provider,
                                                   load_cluster_config)

    config = load_cluster_config(args.config)
    if config["provider"]["type"] == "fake":
        print("fake provider is in-process; nothing to tear down "
              "(Ctrl-C the `up` process instead)", flush=True)
        return
    provider = _build_provider(config, cluster=None)
    instances = provider.non_terminated_instances()
    for instance_id in instances:
        provider.terminate(instance_id)
    print(f"terminated {len(instances)} instances of cluster "
          f"{config['cluster_name']!r}", flush=True)


def cmd_stop(_args):
    import subprocess
    patterns = ["ray_tpu._internal.raylet_main",
                "ray_tpu._internal.worker_main",
                "ray_tpu.cli start"]
    for pattern in patterns:
        subprocess.run(["pkill", "-f", pattern], check=False)
    try:
        os.unlink(ADDRESS_FILE)
    except FileNotFoundError:
        pass
    print("stopped")


def cmd_status(args):
    _connect(args)
    from ray_tpu.util import state as st
    from ray_tpu._internal.core_worker import get_core_worker
    nodes = st.list_nodes()
    demand = get_core_worker().gcs.call_sync("get_cluster_demand")
    print(f"nodes: {len(nodes)}")
    total, avail = {}, {}
    for node in nodes:
        mark = " (head)" if node["is_head"] else ""
        print(f"  {node['node_id'][:12]}{mark}  "
              f"{node['resources_available']} / "
              f"{node['resources_total']}")
        for k, v in node["resources_total"].items():
            total[k] = total.get(k, 0) + v
        for k, v in node["resources_available"].items():
            avail[k] = avail.get(k, 0) + v
    print(f"resources: {avail} available of {total}")
    # Owner shards of THIS driver (the submit fan-in side): queue depth
    # and loop lag per shard make imbalance visible from the terminal.
    cw = get_core_worker()
    if len(cw.shards) > 1:
        print(f"owner shards (driver pid {os.getpid()}): "
              f"{len(cw.shards)}")
        for row in cw.shards.stats():
            lag = row["loop_lag_s"]
            lag_txt = f"{lag * 1000:.2f}ms" if lag is not None else "-"
            print(f"  shard {row['shard']}: queue_depth="
                  f"{row['queue_depth']} submits={row['submits']} "
                  f"loop_lag={lag_txt}")
    # Per-node accelerator rows from the device plane (chip count, HBM
    # used/limit, compile seconds since start) — best-effort: a cluster
    # with no accel reports (or the plane killed) just omits the block.
    try:
        accel = st.accel_summary(force_local_jax=False, node_timeout_s=3)
        accel_nodes = [n for n in accel["nodes"]
                       if n["num_devices"] or n["compiles"]]
        if accel_nodes:
            print("accelerators:")
            for row in accel_nodes:
                limit = _fmt_bytes(row["hbm_limit_bytes"]) \
                    if row["hbm_limit_bytes"] else "?"
                print(f"  {row['node_id'][:12]}  "
                      f"{row['num_devices']} chips  HBM "
                      f"{_fmt_bytes(row['hbm_used_bytes'])} / {limit}  "
                      f"compile {row['compile_seconds']:.2f}s "
                      f"({row['compiles']} compiles)")
    except Exception as e:  # noqa: BLE001 — status must render anyway
        print(f"accelerators: unavailable ({e})")
    # Transport plane: per-process rpc error/retry/slow totals from the
    # observatory fan-out — best-effort like the accel block (and empty
    # under RTPU_NO_RPC_METRICS, where the counters don't exist).
    try:
        rows = [p for p in st.rpc_summary()["processes"]
                if "error" not in p]
        if any(p.get("transport_errors") or p.get("retries")
               or p.get("slow_total") for p in rows):
            print("rpc transport:")
            for p in rows:
                node = (p.get("node_id") or "")[:12] or "-"
                print(f"  {p.get('mode', '?'):8s} pid={p.get('pid')} "
                      f"node={node}  "
                      f"errors={p.get('transport_errors', 0):g}  "
                      f"retries={p.get('retries', 0):g}  "
                      f"slow={p.get('slow_total', 0)}")
    except Exception as e:  # noqa: BLE001 — status must render anyway
        print(f"rpc transport: unavailable ({e})")
    # Per-shape pending demand with a feasibility check, so "why is my
    # task pending" is answerable from here: a shape no amount of
    # waiting can satisfy is flagged INFEASIBLE. A shape must fit on
    # ONE node (tasks/bundles don't split), so the test is whether any
    # single node's totals satisfy every resource at once — not the
    # cluster-wide sum ({CPU: 12} pends forever on 2x8-CPU nodes).
    shapes = {}
    for kind, shape_list in (("task", demand["task_demand"]),
                             ("pg bundle", demand["pg_demand"])):
        for shape in shape_list:
            key = (kind, tuple(sorted(shape.items())))
            shapes[key] = shapes.get(key, 0) + 1
    if not shapes:
        print("pending demand: none")
        return
    print(f"pending demand: {sum(shapes.values())} requests, "
          f"{len(shapes)} shapes")
    node_totals = [n["resources_total"] for n in nodes]
    for (kind, shape), count in sorted(shapes.items(),
                                       key=lambda kv: -kv[1]):
        demand_dict = dict(shape)
        line = f"  {count}x {kind} {demand_dict}"
        fits_somewhere = any(
            all(nt.get(k, 0) >= v for k, v in shape)
            for nt in node_totals)
        if not fits_somewhere:
            best = {k: max((nt.get(k, 0) for nt in node_totals),
                           default=0) for k, _v in shape}
            why = [f"{k} {v:g} > best node {best[k]:g}"
                   for k, v in shape if v > best[k]]
            line += (f"  [INFEASIBLE: no single node fits: "
                     f"{'; '.join(why) or 'combined shape'}]")
        print(line)


def cmd_list(args):
    _connect(args)
    from ray_tpu.util import state as st
    listing = {
        "nodes": st.list_nodes, "actors": st.list_actors,
        "tasks": st.list_tasks,
        "placement_groups": st.list_placement_groups,
        "objects": st.list_objects, "workers": st.list_workers,
    }
    if args.what == "jobs":
        from ray_tpu.job_submission import JobManager
        rows = JobManager().list_jobs()
    else:
        rows = listing[args.what](limit=args.limit)
    print(json.dumps(rows, indent=1, default=str))


def _fmt_bytes(n) -> str:
    n = float(n or 0)
    for unit in ("B", "KiB", "MiB", "GiB", "TiB"):
        if n < 1024 or unit == "TiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}TiB"


def cmd_memory(args):
    """Cluster memory report (reference: `ray memory` — per-object rows
    with owner, reference kind, and callsite, plus store accounting and
    the pinned-but-unreferenced leak heuristic)."""
    _connect(args)
    from ray_tpu.util import state as st
    summary = st.memory_summary(limit=args.limit)
    if args.json:
        print(json.dumps(summary, indent=1, default=str))
        return
    for node in summary["nodes"]:
        store = node["store"]
        pressure = "  [MEMORY PRESSURE]" if node.get("mem_pressure") else ""
        print(f"node {node['node_id'][:12]}  store "
              f"{_fmt_bytes(store.get('used_bytes'))} / "
              f"{_fmt_bytes(store.get('capacity'))} used, "
              f"{_fmt_bytes(store.get('pinned_bytes'))} pinned, "
              f"{_fmt_bytes(store.get('spilled_bytes'))} spilled "
              f"({store.get('spill_count', 0)} spills, "
              f"{store.get('restore_count', 0)} restores)"
              f"{pressure}")
    print(f"\n{len(summary['objects'])} object refs, "
          f"{_fmt_bytes(summary['total_owned_bytes'])} owned")
    header = (f"{'OBJECT ID':<18} {'NODE':<14} {'PID':<7} {'SIZE':>10} "
              f"{'KIND':<24} {'BORROWERS':>9}  CALLSITE")
    print(header)
    print("-" * len(header))
    for obj in summary["objects"][:args.limit]:
        site = obj.get("callsite") or "-"
        if len(site) > 60:
            site = "..." + site[-57:]
        print(f"{obj['object_id'][:16]:<18} "
              f"{(obj.get('node_id') or '?')[:12]:<14} "
              f"{obj.get('pid') or '?':<7} "
              f"{_fmt_bytes(obj.get('size')):>10} "
              f"{obj.get('kind', '?'):<24} "
              f"{obj.get('borrowers', 0):>9}  {site}")
    if summary["by_callsite"]:
        print("\ntop owner callsites by bytes:")
        for agg in summary["by_callsite"]:
            print(f"  {_fmt_bytes(agg['total_bytes']):>10}  "
                  f"x{agg['count']:<5} {agg['callsite']}")
    if summary.get("leak_heuristic_skipped"):
        print("\nleak heuristic skipped: some owner reports were "
              "unreachable or truncated")
    if summary["leaked"]:
        print(f"\nPOSSIBLE LEAKS ({len(summary['leaked'])} store objects "
              "with no owner reference):")
        for obj in summary["leaked"][:20]:
            print(f"  {obj['object_id'][:16]}  "
                  f"{_fmt_bytes(obj.get('size'))}  "
                  f"node {(obj.get('node_id') or '?')[:12]}"
                  f"{'  (spilled)' if obj.get('spilled') else ''}")
    if summary["errors"]:
        errs = json.dumps(summary["errors"], default=str)
        print(f"\nunreachable: {errs}")


def cmd_events(args):
    """Render the GCS cluster event log (node/actor/job transitions,
    SPILL/RESTORE, MEMORY_PRESSURE)."""
    _connect(args)
    from ray_tpu.util import state as st
    events = st.list_events(event_type=args.type, limit=args.limit)
    if args.json:
        print(json.dumps(events, indent=1, default=str))
        return
    if not events:
        print("no events recorded")
        return
    for ev in events:
        stamp = time.strftime("%H:%M:%S", time.localtime(ev["ts"]))
        print(f"{stamp}  {ev['severity']:<7} {ev['type']:<18} "
              f"{ev.get('message', '')}")


def cmd_timeline(args):
    _connect(args)
    from ray_tpu.util import state as st
    if getattr(args, "train", False):
        trace = st.train_timeline(args.output)
        tracks = sorted({row["pid"] for row in trace})
        print(f"wrote {len(trace)} train spans across "
              f"{len(tracks)} tracks ({', '.join(map(str, tracks))}) "
              f"to {args.output}")
        return
    if getattr(args, "serve", False):
        trace = st.serve_timeline(args.output)
        tracks = sorted({row["tid"] for row in trace if "tid" in row})
        print(f"wrote {len(trace)} serve spans across "
              f"{len(tracks)} requests to {args.output}")
        return
    trace = st.timeline(args.output)
    print(f"wrote {len(trace)} spans to {args.output}")


def cmd_requests(args):
    """Render the serve-plane request observatory: percentile folds over
    every traced request (optionally grouped by tenant/route), or one
    request's `why_slow` latency-attribution report with --why."""
    _connect(args)
    from ray_tpu.util import state as st
    if args.why:
        report = st.why_slow(args.why)
        if args.json:
            print(json.dumps(report, indent=1, default=str))
            return
        if "error" in report:
            print(report["error"])
            return
        print(f"request {report['request_id']}  "
              f"outcome={report.get('outcome') or 'in-flight'}"
              + (f"  tenant={report['tenant']}"
                 if report.get("tenant") else "")
              + (f"  route={report['route']}"
                 if report.get("route") else ""))
        for horizon in ("ttft", "e2e"):
            total = report.get(f"{horizon}_s")
            buckets = report.get(f"{horizon}_buckets")
            if total is None or not buckets:
                continue
            print(f"  {horizon}: {total:.4f}s")
            for name, sec in sorted(buckets.items(),
                                    key=lambda kv: -kv[1]):
                if sec <= 0:
                    continue
                print(f"    {name:<16} {sec:>9.4f}s "
                      f"({100.0 * sec / total if total else 0:5.1f}%)")
        if report.get("preemptions"):
            print(f"  preemptions: {report['preemptions']}")
        for ev in report.get("events", []):
            args_s = " ".join(
                f"{k}={v}" for k, v in sorted(ev.items())
                if k not in ("event", "t_s"))
            print(f"  +{ev['t_s']:>8.4f}s  {ev['event']:<14} {args_s}")
        return
    by = "tenant" if args.by_tenant else (
        "route" if args.by_route else None)
    fold = st.serve_requests(by=by)
    if args.json:
        print(json.dumps(fold, indent=1, default=str))
        return
    groups = fold["groups"]
    if not groups:
        print("no requests traced")
        return
    label = fold.get("by") or "all"
    print(f"{label:<18} reqs  done fail  preempt   park_s "
          f"ttft_p50  ttft_p95   e2e_p50   e2e_p95")
    for key in sorted(groups):
        g = groups[key]

        def _f(v):
            return f"{v:>8.4f}" if v is not None else "       -"
        print(f"{key:<18} {g['requests']:>4} {g['finished']:>5} "
              f"{g['failed']:>4} {g['preemptions']:>8} "
              f"{g['park_s_total']:>8.3f} "
              f"{_f(g['ttft_p50_s'])}  {_f(g['ttft_p95_s'])}  "
              f"{_f(g['e2e_p50_s'])}  {_f(g['e2e_p95_s'])}")


def cmd_stragglers(args):
    """Render the straggler/skew view: STRAGGLER_DETECTED events plus
    the per-track (rank/stage) rolling step-time fold."""
    _connect(args)
    from ray_tpu.util import state as st
    view = st.stragglers(limit=args.limit)
    if args.json:
        print(json.dumps(view, indent=1, default=str))
        return
    stats = view["step_stats"]
    if stats:
        print("track       steps  mean_step_s  last_step_s")
        for track in sorted(stats):
            row = stats[track]
            print(f"{track:<10} {row['steps']:>6} "
                  f"{row['mean_step_s']:>12.4f} {row['last_s']:>12.4f}")
    if not view["events"]:
        print("no stragglers detected")
        return
    print()
    for ev in view["events"]:
        stamp = time.strftime("%H:%M:%S", time.localtime(ev["ts"]))
        print(f"{stamp}  rank {ev.get('rank')}  "
              f"phase={ev.get('phase', '?')}  "
              f"wait={ev.get('wait_s', 0):.3f}s "
              f"(median of peers {ev.get('median_others_s', 0):.3f}s, "
              f"seen by rank {ev.get('observer_rank')} "
              f"x{ev.get('consecutive_ops')} ops)")


def cmd_alerts(args):
    """Render the GCS SLO alert table (what the alert engine fired)."""
    _connect(args)
    from ray_tpu.util import state as st
    rows = st.alerts(rule=args.rule, severity=args.severity,
                     limit=args.limit)
    if args.json:
        print(json.dumps(rows, indent=1, default=str))
        return
    if not rows:
        print("no alerts fired")
        return
    for row in rows:
        stamp = time.strftime("%H:%M:%S", time.localtime(row["ts"]))
        print(f"{stamp}  {row['severity']:<8} {row['rule']:<22} "
              f"{row.get('message', '')}")


def cmd_trace(args):
    """Print one trace's span tree (or list recent traces with no id).
    --logs interleaves each execution span's captured log lines (by the
    task id the span carries) under its node."""
    _connect(args)
    from ray_tpu.util import state as st
    if not args.trace_id:
        rows = st.list_traces(limit=args.limit)
        if args.json:
            print(json.dumps(rows, indent=1, default=str))
            return
        for row in rows:
            print(f"{row['trace_id']}  {row['name'] or '?':24s} "
                  f"spans={row['num_spans']} "
                  f"procs={row['num_processes']} "
                  f"dur={row['duration_s']:.3f}s")
        if not rows:
            print("no traces recorded")
        return
    tree = st.get_trace(args.trace_id)
    if args.json:
        print(json.dumps(tree, indent=1, default=str))
        return
    if not tree["num_spans"]:
        print(f"no spans recorded for trace {args.trace_id}")
        raise SystemExit(1)
    print(f"trace {tree['trace_id']}: {tree['num_spans']} spans across "
          f"{tree['num_processes']} processes")

    lines_by_task = {}
    if getattr(args, "logs", False):
        # ONE cluster sweep serves the whole tree; lines group by the
        # task id each execution span carries.
        for line in st.get_logs(limit=10_000)["lines"]:
            if line.get("task"):
                lines_by_task.setdefault(line["task"], []).append(line)

    def _render(node, depth):
        print(f"{'  ' * depth}- {node['name']}  "
              f"[{node['duration_s'] * 1e3:.1f}ms pid={node['pid']} "
              f"span={node['span_id'][:8]}]")
        for line in lines_by_task.get(node.get("task_id") or "", ()):
            stamp = time.strftime("%H:%M:%S",
                                  time.localtime(line["ts"]))
            print(f"{'  ' * (depth + 1)}| {stamp} "
                  f"[{line.get('level') or '?'}] {line['line']}")
        for child in node["children"]:
            _render(child, depth + 1)
    for root in tree["roots"]:
        _render(root, 0)


def cmd_logs(args):
    """Cluster log search/tail over the per-worker rings (reference:
    `ray logs` + the dashboard log view): works with log_to_driver OFF
    — retention lives at the raylets, not in driver stdout."""
    _connect(args)
    from ray_tpu.util import state as st

    def _print_batch(batch):
        for line in batch["lines"]:
            stamp = time.strftime("%H:%M:%S",
                                  time.localtime(line["ts"]))
            who = f"node{line.get('node_index', '?')} " \
                  f"pid={line.get('pid', '?')}"
            task = f" task={line['task'][:12]}" if line.get("task") else ""
            actor = f" actor={line['actor'][:12]}" \
                if line.get("actor") else ""
            print(f"{stamp} [{who}{task}{actor} "
                  f"{line.get('level') or '?'}] {line['line']}")

    if args.follow:
        try:
            for batch in st.tail_logs(task=args.task, actor=args.actor,
                                      job=args.job, node_id=args.node,
                                      level=args.level, grep=args.grep):
                _print_batch(batch)
        except KeyboardInterrupt:
            return
        return
    result = st.get_logs(task=args.task, actor=args.actor, job=args.job,
                         node_id=args.node, level=args.level,
                         grep=args.grep, tail=args.tail,
                         limit=args.limit)
    if args.json:
        print(json.dumps(result, indent=1, default=str))
        return
    if result.get("disabled"):
        print("log plane disabled (RTPU_NO_LOG_PLANE) on some nodes")
    _print_batch(result)
    extras = []
    if result["dropped"]:
        extras.append(f"{result['dropped']} lines dropped (ring "
                      "overflow)")
    if result["errors"]:
        extras.append(f"unreachable: "
                      f"{json.dumps(result['errors'], default=str)}")
    if extras:
        print("-- " + "; ".join(extras))


def cmd_profile(args):
    """Cluster-wide CPU profile (reference: the reporter agent's py-spy
    routing, fleet-merged): sample every process for --duration at
    --hz, print top-N task/actor/frame attribution, and emit the merged
    flamegraph as collapsed stacks or speedscope JSON."""
    _connect(args)
    from ray_tpu.util import state as st
    report = st.profile_cluster(
        duration_s=args.duration, hz=args.hz, node_id=args.node,
        pid=args.pid, task=args.task, top=args.top)

    def _emit(text: str):
        if args.output:
            with open(args.output, "w") as f:
                f.write(text)
            print(f"wrote {len(text)} bytes to {args.output}")
        else:
            print(text)

    if args.format == "json":
        _emit(json.dumps(report, indent=1, default=str))
        return
    if args.format == "speedscope":
        _emit(json.dumps(report["speedscope"], default=str))
        return
    if args.format == "collapsed":
        _emit(report["collapsed"])
        return
    # table (default): capture summary + attribution tables
    print(f"sampled {report['num_samples']} stacks across "
          f"{report['num_processes']} processes "
          f"({report['duration_s']:g}s @ {report['hz']:g}Hz)")
    ex = report["executor"]
    if ex["running"] or ex["idle"]:
        busy = ex["running"] / (ex["running"] + ex["idle"]) * 100
        print(f"executor threads: {ex['running']} running / "
              f"{ex['idle']} idle samples ({busy:.0f}% busy)")
    for title, key, label in (("top tasks by sampled CPU", "by_task",
                               "name"),
                              ("top actor classes", "by_actor", "actor"),
                              ("top frames (self)", "by_frame", "frame")):
        rows = report["top"][key]
        if not rows:
            continue
        print(f"\n{title}:")
        for agg in rows:
            extra = f"  task={agg['task'][:12]}" if key == "by_task" \
                else ""
            print(f"  {agg['cpu_s']:>8.3f}s  x{agg['samples']:<6} "
                  f"{agg.get(label) or '?'}{extra}")
    if report["errors"]:
        print(f"\nunreachable/refused: "
              f"{json.dumps(report['errors'], default=str)}")
    if args.output:
        with open(args.output, "w") as f:
            f.write(report["collapsed"])
        print(f"\ncollapsed flamegraph written to {args.output}")


def cmd_stack(args):
    """One-shot stack dump of every worker/raylet/GCS/driver in the
    fleet (reference: `ray stack`, fleet-scoped)."""
    _connect(args)
    from ray_tpu.util import state as st
    rows = st.stack_cluster(node_id=args.node)
    if args.json:
        print(json.dumps(rows, indent=1, default=str))
        return
    dumped = 0
    for row in rows:
        where = f"node {(row.get('node_id') or '?')[:12]} " \
            f"pid {row.get('pid') or '?'} ({row.get('component', '?')})"
        if row.get("error"):
            print(f"==== {where}: UNREACHABLE: {row['error']}")
            continue
        dumped += 1
        print(f"==== {where} " + "=" * 20)
        print(row.get("text", ""))
    print(f"dumped {dumped} processes "
          f"({sum(1 for r in rows if r.get('error'))} unreachable)")


def cmd_devices(args):
    """Cluster accelerator report (the device leg of memory/profile):
    per-device HBM used/peak/limit, XLA compile totals + top compiled
    functions, and step/MFU telemetry per process."""
    _connect(args)
    from ray_tpu.util import state as st
    summary = st.accel_summary()
    if args.json:
        print(json.dumps(summary, indent=1, default=str))
        return
    comp = summary["compile"]
    print(f"devices: {len(summary['devices'])} across "
          f"{len(summary['nodes'])} nodes · compiles {comp['compiles']} "
          f"({comp['compile_seconds']:.2f}s, "
          f"cache {comp['cache_hits']} hit / "
          f"{comp['cache_misses']} miss)")
    header = (f"{'NODE':<14} {'PID':<7} {'DEV':<4} {'KIND':<14} "
              f"{'HBM USED':>10} {'PEAK':>10} {'LIMIT':>10}  SOURCE")
    print(header)
    print("-" * len(header))
    for dev in summary["devices"]:
        print(f"{(dev.get('node_id') or '?')[:12]:<14} "
              f"{dev.get('pid') or '?':<7} "
              f"{dev['index']:<4} {dev['device_kind'][:14]:<14} "
              f"{_fmt_bytes(dev['hbm_used_bytes']):>10} "
              f"{_fmt_bytes(dev['hbm_peak_bytes']):>10} "
              f"{_fmt_bytes(dev['hbm_limit_bytes']):>10}  "
              f"{dev['source']}")
    if summary["steps"]:
        print("\nstep telemetry (per process, per kind):")
        for row in summary["steps"]:
            print(f"  {row['kind']:<14} pid {row.get('pid') or '?':<7} "
                  f"steps={int(row['steps'])} "
                  f"mean={row['mean_step_s'] * 1e3:.2f}ms "
                  f"tok/s={row['tokens_per_s']:.1f} "
                  f"mfu={row['mfu'] * 100:.1f}% "
                  f"goodput compile/device/host="
                  f"{row['compile_s']:.2f}/{row['device_s']:.2f}/"
                  f"{row['host_s']:.2f}s")
    top_fns = []
    for proc in summary["processes"]:
        top_fns.extend((proc.get("compile") or {}).get("per_function", ()))
    top_fns.sort(key=lambda r: -r["seconds"])
    if top_fns:
        print("\ntop compiled functions by backend-compile seconds:")
        for fn in top_fns[:10]:
            print(f"  {fn['seconds']:>8.3f}s  x{fn['count']:<4} "
                  f"{fn['function']}")
    if summary["errors"]:
        print(f"\nunreachable: "
              f"{json.dumps(summary['errors'], default=str)}")


def cmd_dashboard(args):
    _connect(args)
    from ray_tpu.dashboard import start_dashboard
    print(start_dashboard())


def cmd_submit(args):
    _connect(args)
    from ray_tpu.job_submission import JobManager, JobStatus
    import shlex
    manager = JobManager()
    entrypoint = shlex.join(args.entrypoint)
    submission_id = manager.submit_job(entrypoint=entrypoint)
    print(f"submitted {submission_id}")
    if args.wait:
        status = manager.wait_until_finished(submission_id,
                                             timeout_s=args.timeout)
        print(manager.get_job_logs(submission_id), end="")
        print(f"job {submission_id}: {status}")
        if status != JobStatus.SUCCEEDED:
            raise SystemExit(1)


def cmd_job(args):
    _connect(args)
    from ray_tpu.job_submission import JobManager
    manager = JobManager()
    if args.action == "list":
        print(json.dumps(manager.list_jobs(), indent=1, default=str))
    elif args.action == "logs":
        print(manager.get_job_logs(args.id), end="")
    elif args.action == "stop":
        print("stopped" if manager.stop_job(args.id) else "not running")


def cmd_drain(args):
    """Graceful node drain (`cli drain <node-prefix>`): fence new lease
    grants, migrate actors, wait for in-flight work up to the deadline
    — the rolling-upgrade / scale-in primitive."""
    _connect(args)
    from ray_tpu.util.state import api as state_api
    report = state_api.drain_node(
        args.node, timeout_s=args.timeout, exit_process=args.exit,
        cancel=args.cancel)
    print(json.dumps(report, indent=1, default=str))
    if report.get("error"):
        raise SystemExit(1)


def cmd_rollout(args):
    """Rolling restart (`cli rollout`): drain every non-head node one
    by one (each with exit_process so a supervised raylet restarts
    clean) and wait for a replacement to register before moving on —
    the cluster keeps serving throughout. The head restart itself rides
    the PR-10 incarnation reconnect-and-replay path (restart the GCS
    process out-of-band; clients re-register automatically)."""
    _connect(args)
    import time as _time
    from ray_tpu.util.state import api as state_api
    targets = [n for n in state_api.list_nodes()
               if n["state"] == "ALIVE" and not n["is_head"]]
    if not targets:
        print("no non-head nodes to roll")
        return
    for i, node in enumerate(targets):
        nid = node["node_id"]
        print(f"[{i + 1}/{len(targets)}] draining node {nid[:12]} "
              f"(index {node['node_index']})...")
        report = state_api.drain_node(nid, timeout_s=args.timeout,
                                      exit_process=True)
        print(f"  drained in {report.get('elapsed_s', 0):.2f}s, "
              f"migrated {len(report.get('migrated_actors', ()))} "
              f"actor(s), "
              f"{len(report.get('stragglers_killed', ()))} straggler(s)"
              + (f"; ERROR {report['error']}"
                 if report.get("error") else ""))
        if report.get("error"):
            raise SystemExit(1)
        if args.no_wait:
            continue
        # Wait for the replacement (a supervisor restarting the raylet)
        # to re-register before rolling the next node, so capacity never
        # dips by more than one node.
        before = {n["node_id"] for n in targets} | \
            {n["node_id"] for n in state_api.list_nodes()}
        deadline = _time.monotonic() + args.rejoin_timeout
        while _time.monotonic() < deadline:
            fresh = [n for n in state_api.list_nodes()
                     if n["state"] == "ALIVE"
                     and n["node_id"] not in before]
            if fresh:
                print(f"  replacement node {fresh[0]['node_id'][:12]} "
                      "registered")
                break
            _time.sleep(0.5)
        else:
            print("  (no replacement registered within "
                  f"{args.rejoin_timeout:.0f}s — is a supervisor "
                  "restarting the raylet? continuing)")
    print("rollout complete")


def cmd_chaos(args):
    """Fault-injection drills (the deterministic chaos harness,
    _internal/chaos.py): arm/disarm RPC fault rules cluster-wide, show
    the GCS's failover status, and kill processes for failover tests."""
    _connect(args)
    from ray_tpu.util.state import api as state_api
    if args.action == "show":
        info = state_api.gcs_info()
        from ray_tpu._internal.chaos import REGISTRY
        out = {"gcs": info, "local_rules": [vars(r) for r in
                                           REGISTRY.active_rules()],
               "local_schedule": REGISTRY.schedule_status(),
               "local_hits": REGISTRY.hit_counts()}
        if args.json:
            print(json.dumps(out, indent=2, default=str))
        else:
            print(f"gcs incarnation {info['incarnation']} "
                  f"(pid {info['pid']}, persist={info['persist_mode']}, "
                  f"wal={info['wal_bytes']}B, "
                  f"failovers={info['failovers']})")
            for r in out["local_rules"]:
                print(f"  rule {r['pattern']}:{r['action']}"
                      f":{r['prob']}" + (f":{r['param']}"
                                         if r["param"] else ""))
            for s in out["local_schedule"]:
                state = "ACTIVE" if s["active"] else "armed"
                print(f"  sched t+{s['at_s']:g}s {s['pattern']}:"
                      f"{s['action']}:{s['prob']:g}"
                      + (f":{s['param']:g}" if s["param"] else "")
                      + f"  [{state}, t={s['elapsed_s']:g}s]")
            for site, n in out["local_hits"].items():
                print(f"  hits {site}: {n}")
    elif args.action == "set":
        if not args.spec and not args.schedule:
            raise SystemExit("chaos set requires --spec "
                             "(method:action:prob[:param],...) and/or "
                             "--schedule (at_s:method:action:prob"
                             "[:param],...)")
        rows = state_api.set_chaos(spec=args.spec, seed=args.seed,
                                   schedule=args.schedule or None)
        for row in rows:
            print(row)
    elif args.action == "clear":
        for row in state_api.set_chaos(spec="", seed=0, schedule=""):
            print(row)
    elif args.action == "kill-gcs":
        info = state_api.gcs_info()
        print(f"SIGKILLing gcs incarnation {info['incarnation']} "
              f"(pid {info['pid']})...")
        try:
            state_api._gcs().call_sync("chaos_kill_self", timeout=10)
        except Exception as e:  # noqa: BLE001 — death races the reply
            print(f"(kill call returned {e!r})")
    elif args.action == "kill-worker":
        import ray_tpu
        from ray_tpu._internal.core_worker import get_core_worker
        cw = get_core_worker()
        for node in ray_tpu.nodes():
            if args.node and not node["node_id"].startswith(args.node):
                continue
            ok = cw.run_sync(cw.clients.get(tuple(node["address"])).call(
                "chaos_kill_worker", worker_hex=args.worker or "",
                pid=args.pid, timeout=10), timeout=15)
            print(f"node {node['node_id'][:12]}: {ok}")
            if ok:
                break
    else:
        raise SystemExit(f"unknown chaos action {args.action!r}")


def cmd_rpc(args):
    """Transport observatory (`state.rpc_summary()`): per-method client
    latency percentiles + error rates, retry/chaos counters, per-ring
    native stats, and every process's slow-RPC ring."""
    _connect(args)
    from ray_tpu.util import state as st
    summary = st.rpc_summary()
    if args.json:
        print(json.dumps(summary, indent=1, default=str))
        return

    def _ms(v):
        return f"{v * 1000:.2f}ms" if v is not None else "-"

    methods = summary["methods"]
    if args.method:
        methods = [m for m in methods if args.method in m["method"]]
    print(f"methods: {len(methods)} "
          f"(client latency, 1/64-sampled + every slow call)")
    for m in methods:
        print(f"  {m['method']:<24s} n={m['sampled']:<6d} "
              f"p50={_ms(m['p50_s'])} p95={_ms(m['p95_s'])} "
              f"p99={_ms(m['p99_s'])} errors={m['transport_errors']:g}")
    if summary["retries_by_site"]:
        print("retries:")
        for site, n in sorted(summary["retries_by_site"].items()):
            print(f"  {site}: {n:g}")
    if summary["chaos_hits"]:
        print("chaos hits:")
        for pattern, n in sorted(summary["chaos_hits"].items()):
            print(f"  {pattern}: {n:g}")
    if summary["rings"]:
        print("native rings:")
        for r in summary["rings"]:
            print(f"  pid={r['pid']} ring={r['ring']}  "
                  f"depth={r.get('queue_depth', 0):g} "
                  f"hwm={r.get('depth_hwm', 0):g}  "
                  f"frames in/out={r.get('frames_in', 0):g}/"
                  f"{r.get('frames_out', 0):g}")
    processes = summary["processes"]
    if args.node:
        processes = [p for p in processes
                     if (p.get("node_id") or "").startswith(args.node)]
    for p in processes:
        if "error" in p:
            print(f"process {p.get('node_id') or p.get('job_id')}: "
                  f"unreachable ({p['error']})")
            continue
        print(f"process pid={p.get('pid')} mode={p.get('mode', '?')} "
              f"errors={p.get('transport_errors', 0):g} "
              f"retries={p.get('retries', 0):g} "
              f"slow={p.get('slow_total', 0)}")
        if args.slow:
            for row in p.get("slow", ()):
                print(f"    {row['method']:<20s} "
                      f"{row['duration_s'] * 1000:8.1f}ms  "
                      f"peer={row['peer']}  site={row['site']}")


def cmd_perf(args):
    from ray_tpu import perf
    perf.main(quick=args.quick)


def cmd_lint(args):
    """rtpulint: project-specific static analysis (per-file rules
    L001-L010 plus cross-module A001-A003/J001-J003, burn-down
    allowlist). Exit codes: 0 clean, 1 violations or a stale/malformed
    allowlist entry, 2 usage/environment error (--changed without a
    usable git checkout)."""
    from ray_tpu._internal import lint
    raise SystemExit(lint.main(
        (["--json"] if args.json else [])
        + (["--no-allowlist"] if args.no_allowlist else [])
        + (["--changed"] if args.changed else [])))


def cmd_serve(args):
    """`serve deploy/status/shutdown` (reference: serve/scripts.py —
    the config-file production deploy path)."""
    import ray_tpu
    ray_tpu.init(address=_resolve_address(getattr(args, "address", None)))
    from ray_tpu import serve as serve_api
    if args.action == "deploy":
        if not args.config:
            raise SystemExit("serve deploy requires a config file path")
        from ray_tpu.serve.config_file import deploy_config
        names = deploy_config(args.config)
        print(f"deployed applications: {', '.join(names)}")
        print(f"http: {serve_api.get_http_address()}")
    elif args.action == "status":
        import json as _json
        print(_json.dumps(serve_api.status(), indent=2, default=str))
    elif args.action == "shutdown":
        serve_api.shutdown()
        print("serve shut down")


def main(argv=None):
    parser = argparse.ArgumentParser(prog="ray_tpu")
    sub = parser.add_subparsers(dest="command", required=True)

    p = sub.add_parser("start")
    p.add_argument("--head", action="store_true")
    p.add_argument("--address")
    p.add_argument("--num-cpus", type=float, default=None)
    p.add_argument("--dashboard", action="store_true")
    p.set_defaults(fn=cmd_start)

    p = sub.add_parser("up")
    p.add_argument("config")
    p.add_argument("--validate-only", action="store_true")
    p.set_defaults(fn=cmd_up)

    p = sub.add_parser("down")
    p.add_argument("config")
    p.set_defaults(fn=cmd_down)

    p = sub.add_parser("stop")
    p.set_defaults(fn=cmd_stop)

    p = sub.add_parser("status")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_status)

    p = sub.add_parser("list")
    p.add_argument("what", choices=["nodes", "actors", "tasks",
                                    "placement_groups", "objects",
                                    "workers", "jobs"])
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_list)

    p = sub.add_parser("memory")
    p.add_argument("--json", action="store_true")
    p.add_argument("--limit", type=int, default=50)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_memory)

    p = sub.add_parser("events")
    p.add_argument("--type", default=None)
    p.add_argument("--json", action="store_true")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_events)

    p = sub.add_parser("timeline")
    p.add_argument("--output", default="timeline.json")
    p.add_argument("--train", action="store_true",
                   help="cross-rank train-step timeline (steptrace) "
                        "instead of the task timeline")
    p.add_argument("--serve", action="store_true",
                   help="serve-plane per-request lifecycle timeline "
                        "(reqtrace) instead of the task timeline")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_timeline)

    p = sub.add_parser("requests")
    p.add_argument("--by-tenant", action="store_true",
                   help="group percentile folds by tenant label")
    p.add_argument("--by-route", action="store_true",
                   help="group percentile folds by serve route")
    p.add_argument("--why", default=None, metavar="REQUEST_ID",
                   help="latency-attribution report for one request "
                        "(unique id prefix ok)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_requests)

    p = sub.add_parser("stragglers")
    p.add_argument("--json", action="store_true")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_stragglers)

    p = sub.add_parser("alerts")
    p.add_argument("--rule", default=None)
    p.add_argument("--severity", default=None)
    p.add_argument("--json", action="store_true")
    p.add_argument("--limit", type=int, default=100)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_alerts)

    p = sub.add_parser("trace")
    p.add_argument("trace_id", nargs="?")
    p.add_argument("--json", action="store_true")
    p.add_argument("--logs", action="store_true",
                   help="interleave captured log lines under each "
                        "execution span (by task id)")
    p.add_argument("--limit", type=int, default=20)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_trace)

    p = sub.add_parser("logs")
    p.add_argument("--task", default=None,
                   help="task id (hex prefix)")
    p.add_argument("--actor", default=None,
                   help="actor id (hex prefix)")
    p.add_argument("--job", default=None, help="job id (hex)")
    p.add_argument("--node", default=None,
                   help="restrict to one node (id prefix)")
    p.add_argument("--level", default=None,
                   help="minimum level (DEBUG/INFO/WARNING/ERROR)")
    p.add_argument("--grep", default=None, help="regex over messages")
    p.add_argument("--tail", type=int, default=None,
                   help="last N lines after the merge")
    p.add_argument("--follow", "-f", action="store_true",
                   help="poll for new lines (cursor-based)")
    p.add_argument("--limit", type=int, default=1000)
    p.add_argument("--json", action="store_true")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_logs)

    p = sub.add_parser("profile")
    p.add_argument("--duration", type=float, default=5.0)
    p.add_argument("--hz", type=float, default=None,
                   help="sampling rate (default: CONFIG.profiler_hz)")
    p.add_argument("--format", choices=["table", "collapsed",
                                        "speedscope", "json"],
                   default="table")
    p.add_argument("--output", "-o", default=None)
    p.add_argument("--node", default=None,
                   help="restrict to one node (id prefix)")
    p.add_argument("--pid", type=int, default=None,
                   help="restrict to one process")
    p.add_argument("--task", default=None,
                   help="restrict to one task (id prefix or exact name)")
    p.add_argument("--top", type=int, default=15)
    p.add_argument("--address")
    p.set_defaults(fn=cmd_profile)

    p = sub.add_parser("stack")
    p.add_argument("--node", default=None,
                   help="restrict to one node (id prefix)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_stack)

    p = sub.add_parser("devices")
    p.add_argument("--json", action="store_true")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_devices)

    p = sub.add_parser("dashboard")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_dashboard)

    p = sub.add_parser("submit")
    p.add_argument("--wait", action="store_true")
    p.add_argument("--timeout", type=float, default=600.0)
    p.add_argument("--address")
    p.add_argument("entrypoint", nargs="+")
    p.set_defaults(fn=cmd_submit)

    p = sub.add_parser("job")
    p.add_argument("action", choices=["list", "logs", "stop"])
    p.add_argument("id", nargs="?")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_job)

    p = sub.add_parser(
        "drain",
        help="gracefully drain one node: fence leases, migrate actors, "
             "wait for in-flight work")
    p.add_argument("node", help="node id (hex prefix)")
    p.add_argument("--timeout", type=float, default=None,
                   help="drain deadline seconds (default: "
                        "CONFIG.drain_timeout_s); stragglers past it "
                        "are postmortem-tag killed")
    p.add_argument("--exit", action="store_true",
                   help="ask a standalone raylet to exit clean after "
                        "the drain (rolling-restart primitive)")
    p.add_argument("--cancel", action="store_true",
                   help="lower the fence instead (abort a drain)")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_drain)

    p = sub.add_parser(
        "rollout",
        help="rolling restart: drain+exit every non-head node one by "
             "one, waiting for replacements between nodes")
    p.add_argument("--timeout", type=float, default=None,
                   help="per-node drain deadline seconds")
    p.add_argument("--rejoin-timeout", type=float, default=60.0,
                   help="how long to wait for a replacement node "
                        "before rolling the next one")
    p.add_argument("--no-wait", action="store_true",
                   help="do not wait for replacements (drain-only "
                        "sweep)")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_rollout)

    p = sub.add_parser(
        "chaos",
        help="fault-injection drills: arm/disarm rpc chaos rules, "
             "show failover status, kill the GCS or a worker")
    p.add_argument("action",
                   choices=["show", "set", "clear", "kill-gcs",
                            "kill-worker"])
    p.add_argument("--address")
    p.add_argument("--spec", default="",
                   help="method:action:prob[:param],... with actions "
                        "drop_req|drop_resp|delay|dup")
    p.add_argument("--schedule", default="",
                   help="time-scheduled script at_s:method:action:prob"
                        "[:param],... — each entry arms at_s seconds "
                        "after set; a later entry for the same "
                        "method:action replaces the earlier one")
    p.add_argument("--seed", type=int, default=0,
                   help="chaos RNG seed (0 = process-random)")
    p.add_argument("--pid", type=int, default=0,
                   help="kill-worker: worker pid")
    p.add_argument("--worker", default="",
                   help="kill-worker: worker id hex prefix")
    p.add_argument("--node", default="",
                   help="kill-worker: restrict to one node id prefix")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_chaos)

    p = sub.add_parser(
        "rpc",
        help="transport observatory: per-method latency percentiles, "
             "retry/error rates, native-ring stats, slow-RPC ring")
    p.add_argument("--address")
    p.add_argument("--method", default="",
                   help="filter the method table by substring")
    p.add_argument("--node", default="",
                   help="restrict process rows to one node id prefix")
    p.add_argument("--slow", action="store_true",
                   help="print each process's slow-RPC ring (method, "
                        "duration, peer, creation site)")
    p.add_argument("--json", action="store_true")
    p.set_defaults(fn=cmd_rpc)

    p = sub.add_parser("perf")
    p.add_argument("--quick", action="store_true")
    p.set_defaults(fn=cmd_perf)

    p = sub.add_parser("lint")
    p.add_argument("--json", action="store_true")
    p.add_argument("--no-allowlist", action="store_true")
    p.add_argument("--changed", action="store_true",
                   help="only report violations in files changed vs HEAD")
    p.set_defaults(fn=cmd_lint)

    p = sub.add_parser("serve")
    p.add_argument("action", choices=["deploy", "status", "shutdown"])
    p.add_argument("config", nargs="?")
    p.add_argument("--address")
    p.set_defaults(fn=cmd_serve)

    args = parser.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
