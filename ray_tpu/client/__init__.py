"""Thin remote-driver client (reference: python/ray/util/client/ —
`ray.init("ray://...")`; architecture doc util/client/ARCHITECTURE.md).

`ray_tpu.client.connect("host:port")` attaches to a ClientServer running
inside the cluster: no local raylet/GCS, every API call proxied over one
RPC connection. Refs here are stubs; the server holds the real ones and
releases them when the stub is garbage-collected or the session ends.

    ctx = ray_tpu.client.connect("127.0.0.1:10001")

    @ctx.remote
    def f(x):
        return x + 1

    ref = f.remote(41)
    assert ctx.get(ref) == 42
    ctx.disconnect()
"""

from __future__ import annotations

import hashlib
import logging
import threading
import weakref
from typing import Any, Dict, List, Optional, Tuple

from .._internal import serialization

logger = logging.getLogger(__name__)

__all__ = ["connect", "ClientContext"]


class ClientObjectRef:
    __slots__ = ("_stub", "_ctx_ref", "__weakref__")

    def __init__(self, stub: str, ctx: "ClientContext"):
        self._stub = stub
        self._ctx_ref = weakref.ref(ctx)

    def hex(self) -> str:
        return self._stub

    def __repr__(self):
        return f"ClientObjectRef({self._stub[:16]})"

    def __del__(self):
        ctx = self._ctx_ref()
        if ctx is not None:
            ctx._release(self._stub)


class ClientActorHandle:
    def __init__(self, stub: str, ctx: "ClientContext"):
        self._stub = stub
        self._ctx = ctx

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _ClientMethod(self, name)


class _ClientMethod:
    def __init__(self, handle: ClientActorHandle, name: str):
        self._handle = handle
        self._name = name

    def remote(self, *args, **kwargs):
        ctx = self._handle._ctx
        reply = ctx._call("actor_call", actor=self._handle._stub,
                          method_name=self._name,
                          data=ctx._pack_args(args, kwargs))
        return ClientObjectRef(reply["ref"], ctx)


class ClientRemoteFunction:
    def __init__(self, ctx: "ClientContext", fn_id: str, num_returns: int):
        self._ctx = ctx
        self._fn_id = fn_id
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        ctx = self._ctx
        reply = ctx._call("call", fn_id=self._fn_id,
                          data=ctx._pack_args(args, kwargs),
                          num_returns=self._num_returns)
        refs = [ClientObjectRef(r, ctx) for r in reply["refs"]]
        return refs[0] if reply["single"] else refs


class ClientActorClass:
    def __init__(self, ctx: "ClientContext", fn_id: str):
        self._ctx = ctx
        self._fn_id = fn_id

    def remote(self, *args, **kwargs):
        ctx = self._ctx
        reply = ctx._call("create_actor", fn_id=self._fn_id,
                          data=ctx._pack_args(args, kwargs))
        return ClientActorHandle(reply["actor"], ctx)


class ClientContext:
    def __init__(self, address: str):
        from .._internal.rpc import ClientPool

        host, port = address.rsplit(":", 1)
        self._pool = ClientPool()
        self._client = self._pool.get((host, int(port)))
        self._session_id = self._rpc("connect")["session_id"]
        self._registered: set = set()
        self._pending_release: List[str] = []
        self._release_lock = threading.Lock()
        # Keepalive: the server reaps sessions idle > its TTL (120 s), and
        # an interactive driver routinely sits idle longer than that — ping
        # in the background so its refs/actors survive (reference: the Ray
        # client maintains a heartbeat for exactly this reason).
        self._ping_stop = threading.Event()
        # Tracking-only registration: the keepalive belongs to this
        # REMOTE connection, not to any local node — a Node.stop() in
        # this process must not silence it (the server would reap the
        # still-live session). disconnect() stops it.
        from .._internal.threads import register_daemon_thread
        self._ping_thread = threading.Thread(
            target=self._keepalive, daemon=True, name="rtpu-client-ping")
        register_daemon_thread(self._ping_thread, joinable=False)
        self._ping_thread.start()

    def _keepalive(self):
        from .._internal.backoff import Backoff
        bo = None  # armed while pings fail: retry on the shared schedule
        wait = 30.0
        while not self._ping_stop.wait(wait):
            try:
                self._rpc("ping", session_id=self._session_id)
                bo, wait = None, 30.0
            except Exception:
                logger.debug("client keepalive ping failed", exc_info=True)
                if bo is None:
                    bo = Backoff(base_s=1.0, max_s=30.0)
                wait = bo.next_delay() or 30.0

    # -- plumbing --------------------------------------------------------

    def _rpc(self, method: str, **kwargs):
        reply = self._client.call_sync(f"client_{method}", timeout=120,
                                       **kwargs)
        return reply

    def _call(self, method: str, **kwargs):
        self._flush_releases()
        return self._rpc(method, session_id=self._session_id, **kwargs)

    def _release(self, stub: str):
        with self._release_lock:
            self._pending_release.append(stub)

    def _flush_releases(self):
        with self._release_lock:
            if not self._pending_release:
                return
            refs, self._pending_release = self._pending_release, []
        try:
            self._rpc("release", session_id=self._session_id, refs=refs)
        except Exception:
            logger.debug("ref release batch to client server failed",
                         exc_info=True)

    def _pack_args(self, args: Tuple, kwargs: Dict) -> bytes:
        """Hoist top-level ClientObjectRefs so the server substitutes the
        real refs (matching the framework's own arg semantics)."""
        ref_slots = []
        plain_args = []
        for i, a in enumerate(args):
            if isinstance(a, ClientObjectRef):
                ref_slots.append((("a", i), a.hex()))
                plain_args.append(None)
            else:
                plain_args.append(a)
        plain_kwargs = {}
        for k, v in kwargs.items():
            if isinstance(v, ClientObjectRef):
                ref_slots.append((("k", k), v.hex()))
                plain_kwargs[k] = None
            else:
                plain_kwargs[k] = v
        return serialization.dumps(
            (tuple(plain_args), plain_kwargs, ref_slots))

    # -- public api ------------------------------------------------------

    def remote(self, _target=None, **options):
        def wrap(target):
            payload = serialization.dumps({
                "fn": target, "options": options or None,
                "is_actor": isinstance(target, type)})
            fn_id = hashlib.sha1(payload).hexdigest()
            if fn_id not in self._registered:
                self._call("register_function", fn_id=fn_id, data=payload)
                self._registered.add(fn_id)
            if isinstance(target, type):
                return ClientActorClass(self, fn_id)
            return ClientRemoteFunction(
                self, fn_id, options.get("num_returns", 1))
        return wrap if _target is None else wrap(_target)

    def put(self, value: Any) -> ClientObjectRef:
        reply = self._call("put", data=serialization.dumps(value))
        return ClientObjectRef(reply["ref"], self)

    def get(self, refs, timeout: Optional[float] = None):
        single = isinstance(refs, ClientObjectRef)
        stub_list = [refs.hex()] if single else [r.hex() for r in refs]
        reply = self._call("get", refs=stub_list, timeout_s=timeout)
        if "error" in reply:
            raise serialization.loads(reply["error"])
        values = serialization.loads(reply["values"])
        return values[0] if single else values

    def wait(self, refs: List[ClientObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        by_stub = {r.hex(): r for r in refs}
        reply = self._call("wait", refs=list(by_stub),
                           num_returns=num_returns, timeout_s=timeout)
        return ([by_stub[s] for s in reply["ready"]],
                [by_stub[s] for s in reply["not_ready"]])

    def kill(self, actor: ClientActorHandle):
        self._call("kill_actor", actor=actor._stub)

    def disconnect(self):
        self._ping_stop.set()
        try:
            self._flush_releases()
            self._rpc("disconnect", session_id=self._session_id)
        except Exception:
            logger.debug("client disconnect RPC failed", exc_info=True)


def connect(address: str) -> ClientContext:
    return ClientContext(address)
