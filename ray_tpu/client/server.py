"""Client server: the cluster-side half of the remote-driver mode
(reference: python/ray/util/client/ARCHITECTURE.md + server/ — a thin
client proxies every API call to a server that owns the real refs).

The server is a driver attached to the cluster; each connected client
gets a session holding the REAL ObjectRefs/actor handles its stub ids
map to, so client-side garbage collection translates into server-side
releases, and a vanished client's refs are dropped with its session.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

SESSION_TTL_S = 120.0


class _Session:
    def __init__(self, session_id: str):
        self.session_id = session_id
        self.refs: Dict[str, Any] = {}        # stub id -> ObjectRef
        self.actors: Dict[str, Any] = {}      # stub id -> actor handle
        self.functions: Dict[str, Any] = {}   # fn id -> RemoteFunction
        self.actor_classes: Dict[str, Any] = {}
        self.last_seen = time.monotonic()


class ClientServer:
    """Serves thin clients over the framework RPC plane."""

    def __init__(self):
        self._sessions: Dict[str, _Session] = {}
        self._lock = threading.Lock()
        self._server = None
        self._reap_stop = threading.Event()
        self.address: Optional[Tuple[str, int]] = None

    # -- lifecycle -------------------------------------------------------

    def start(self, host: str = "127.0.0.1", port: int = 0):
        from .._internal.rpc import EventLoopThread, RpcServer

        self._server = RpcServer("client-server")
        self._server.register_instance(self)  # methods: handle_client_*
        loop = EventLoopThread.get()
        self.address = loop.run_sync(self._server.start(host, port))
        from .._internal.threads import spawn_daemon
        # Fresh event per start(), bound to the thread via args: a
        # stop()/start() pair can never leave an old reaper waiting on a
        # cleared event (clear() after set() loses the wakeup).
        self._reap_stop = threading.Event()
        spawn_daemon(self._reaper, args=(self._reap_stop,),
                     name="rtpu-client-reaper", stop=self._reap_stop.set)
        return self.address

    def stop(self):
        from .._internal.rpc import EventLoopThread
        self._reap_stop.set()
        if self._server is not None:
            EventLoopThread.get().run_sync(self._server.stop(), 5)

    def _reaper(self, stop: threading.Event):
        while not stop.wait(10.0):
            now = time.monotonic()
            with self._lock:
                dead = [sid for sid, s in self._sessions.items()
                        if now - s.last_seen > SESSION_TTL_S]
                for sid in dead:
                    logger.info("client session %s expired", sid[:8])
                    self._sessions.pop(sid, None)

    def _session(self, session_id: str) -> _Session:
        with self._lock:
            session = self._sessions.get(session_id)
            if session is None:
                raise RuntimeError(f"unknown client session {session_id!r}")
            session.last_seen = time.monotonic()
            return session

    # -- rpc handlers (all named handle_client_*) ------------------------

    async def handle_client_connect(self):
        session_id = uuid.uuid4().hex
        with self._lock:
            self._sessions[session_id] = _Session(session_id)
        return {"session_id": session_id}

    async def handle_client_ping(self, session_id: str):
        self._session(session_id)
        return True

    async def handle_client_disconnect(self, session_id: str):
        with self._lock:
            self._sessions.pop(session_id, None)
        return True

    def _sync_put(self, session_id: str, data: bytes):
        import ray_tpu
        from .._internal import serialization

        session = self._session(session_id)
        ref = ray_tpu.put(serialization.loads(data))
        stub = ref.hex()
        session.refs[stub] = ref
        return {"ref": stub}

    def _sync_get(self, session_id: str, refs: List[str],
                  timeout_s: Optional[float] = None):
        import ray_tpu
        from .._internal import serialization

        session = self._session(session_id)
        real = [session.refs[r] for r in refs]
        try:
            values = ray_tpu.get(real, timeout=timeout_s)
        except Exception as e:  # noqa: BLE001 — ship the real error
            return {"error": serialization.dumps(e)}
        return {"values": serialization.dumps(values)}

    def _sync_wait(self, session_id: str, refs: List[str],
                   num_returns: int,
                   timeout_s: Optional[float] = None):
        import ray_tpu

        session = self._session(session_id)
        real = {r: session.refs[r] for r in refs}
        ready, not_ready = ray_tpu.wait(
            list(real.values()), num_returns=num_returns,
            timeout=timeout_s)
        inv = {ref.hex(): stub for stub, ref in real.items()}
        return {"ready": [inv[r.hex()] for r in ready],
                "not_ready": [inv[r.hex()] for r in not_ready]}

    async def handle_client_release(self, session_id: str,
                                    refs: List[str]):
        try:
            session = self._session(session_id)
        except RuntimeError:
            return True
        for r in refs:
            session.refs.pop(r, None)
        return True

    def _sync_register_function(self, session_id: str,
                                              fn_id: str, data: bytes):
        import ray_tpu
        from .._internal import serialization

        session = self._session(session_id)
        if fn_id not in session.functions:
            payload = serialization.loads(data)
            target = payload["fn"]
            options = payload.get("options") or {}
            if payload.get("is_actor"):
                session.actor_classes[fn_id] = ray_tpu.remote(
                    **options)(target) if options \
                    else ray_tpu.remote(target)
            else:
                session.functions[fn_id] = ray_tpu.remote(
                    **options)(target) if options \
                    else ray_tpu.remote(target)
        return True

    def _resolve_args(self, session: _Session, data: bytes):
        from .._internal import serialization

        args, kwargs, ref_slots = serialization.loads(data)
        args = list(args)
        for path, stub in ref_slots:
            kind, index = path
            real = session.refs[stub]
            if kind == "a":
                args[index] = real
            else:
                kwargs[index] = real
        return tuple(args), kwargs

    def _sync_call(self, session_id: str, fn_id: str,
                                 data: bytes, num_returns: int = 1):
        session = self._session(session_id)
        fn = session.functions[fn_id]
        args, kwargs = self._resolve_args(session, data)
        out = fn.remote(*args, **kwargs)
        refs = out if isinstance(out, list) else [out]
        stubs = []
        for ref in refs:
            session.refs[ref.hex()] = ref
            stubs.append(ref.hex())
        return {"refs": stubs, "single": not isinstance(out, list)}

    def _sync_create_actor(self, session_id: str,
                                         fn_id: str, data: bytes):
        session = self._session(session_id)
        cls = session.actor_classes[fn_id]
        args, kwargs = self._resolve_args(session, data)
        handle = cls.remote(*args, **kwargs)
        actor_stub = uuid.uuid4().hex
        session.actors[actor_stub] = handle
        return {"actor": actor_stub}

    def _sync_actor_call(self, session_id: str, actor: str,
                         method_name: str, data: bytes):
        session = self._session(session_id)
        handle = session.actors[actor]
        args, kwargs = self._resolve_args(session, data)
        ref = getattr(handle, method_name).remote(*args, **kwargs)
        session.refs[ref.hex()] = ref
        return {"ref": ref.hex()}

    def _sync_kill_actor(self, session_id: str, actor: str):
        import ray_tpu

        session = self._session(session_id)
        handle = session.actors.pop(actor, None)
        if handle is not None:
            ray_tpu.kill(handle)
        return True



    # -- async wrappers: the blocking driver API must run off the io loop

    async def handle_client_put(self, **kwargs):
        import asyncio
        import functools
        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self._sync_put, **kwargs))

    async def handle_client_get(self, **kwargs):
        import asyncio
        import functools
        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self._sync_get, **kwargs))

    async def handle_client_wait(self, **kwargs):
        import asyncio
        import functools
        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self._sync_wait, **kwargs))

    async def handle_client_register_function(self, **kwargs):
        import asyncio
        import functools
        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self._sync_register_function, **kwargs))

    async def handle_client_call(self, **kwargs):
        import asyncio
        import functools
        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self._sync_call, **kwargs))

    async def handle_client_create_actor(self, **kwargs):
        import asyncio
        import functools
        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self._sync_create_actor, **kwargs))

    async def handle_client_actor_call(self, **kwargs):
        import asyncio
        import functools
        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self._sync_actor_call, **kwargs))

    async def handle_client_kill_actor(self, **kwargs):
        import asyncio
        import functools
        return await asyncio.get_running_loop().run_in_executor(
            None, functools.partial(self._sync_kill_actor, **kwargs))

def serve_forever(gcs_address: str, host: str = "127.0.0.1",
                  port: int = 10001):
    """Entry for `ray_tpu client-server`: attach to the cluster and serve
    thin clients until killed."""
    import ray_tpu

    ray_tpu.init(address=gcs_address)
    server = ClientServer()
    addr = server.start(host, port)
    print(f"client server listening on {addr[0]}:{addr[1]}", flush=True)
    while True:
        time.sleep(3600)
