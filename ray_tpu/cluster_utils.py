"""Multi-node test cluster on one machine
(reference: python/ray/cluster_utils.py — Cluster, add_node).

The head node (GCS + raylet) runs in-process; `add_node` launches additional
raylets as real subprocesses, giving genuine multi-node semantics — separate
object stores, cross-node object transfer, node kill/failure tests — without
containers. This fixture carries most of the reference's distributed test
coverage (SURVEY §4.2).

Fleet-operations extensions (rolling upgrades / chaos soak substrate):
``external_gcs=True`` runs the GCS as a real subprocess (killable with
SIGKILL and restartable at the same port — the PR-10 incarnation
reconnect-and-replay drill), ``restart_node`` performs one rolling-
restart step (GCS-coordinated drain → clean exit → fresh raylet at the
same index), and ``kill_gcs``/``restart_gcs`` are the head-failover
primitives the soak bench schedules."""

from __future__ import annotations

import json
import logging
import os
import socket
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ._internal.node import Node, new_session_name
from ._internal.rpc import Address

logger = logging.getLogger(__name__)


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_ready_line(proc: subprocess.Popen, marker: str,
                     what: str, timeout_s: float = 60.0) -> str:
    """Wait for a subprocess's readiness protocol line WITHOUT a
    blocking readline — a wedged child that prints nothing must trip
    the deadline, not hang the caller forever."""
    import select
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"{what} subprocess exited rc={proc.returncode}")
        ready, _, _ = select.select([proc.stdout], [], [], 0.5)
        if not ready:
            continue
        line = proc.stdout.readline()
        if line.startswith(marker):
            return line
    raise TimeoutError(f"{what} did not come up in {timeout_s:.0f}s")


def spawn_gcs(port: int, session: str, persist: Optional[str] = None,
              env: Optional[Dict[str, str]] = None) -> subprocess.Popen:
    """Run a GCS as a real subprocess (gcs_main) and wait for its
    readiness line — the killable head for failover drills."""
    proc_env = dict(os.environ)
    proc_env.setdefault("JAX_PLATFORMS", "cpu")
    proc_env.update(env or {})
    cmd = [sys.executable, "-m", "ray_tpu._internal.gcs_main",
           "--host", "127.0.0.1", "--port", str(port),
           "--session", session]
    if persist:
        cmd += ["--persist-path", persist]
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, stderr=None,
                            env=proc_env, text=True)
    _wait_ready_line(proc, "RTPU_GCS_READY", "gcs")
    return proc


class RemoteNodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id: str, address: Address,
                 node_index: int, resources: Optional[Dict] = None,
                 labels: Optional[Dict] = None,
                 object_store_memory: int = 0,
                 env: Optional[Dict[str, str]] = None):
        self.proc = proc
        self.node_id = node_id
        self.address = address
        self.node_index = node_index
        # Spawn spec retained so restart_node can relaunch an identical
        # raylet (fresh node id) after a drain.
        self.resources = dict(resources or {})
        self.labels = dict(labels or {})
        self.object_store_memory = object_store_memory
        self.env = dict(env or {})


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None,
                 external_gcs: bool = False,
                 gcs_persist_path: Optional[str] = None,
                 gcs_env: Optional[Dict[str, str]] = None):
        self.session_name = new_session_name()
        self.head_node: Optional[Node] = None
        self.remote_nodes: List[RemoteNodeHandle] = []
        self._next_index = 1
        self._connected = False
        self.gcs_proc: Optional[subprocess.Popen] = None
        self._gcs_port: Optional[int] = None
        self._gcs_persist = gcs_persist_path
        self._gcs_env = dict(gcs_env or {})
        if initialize_head:
            args = dict(head_node_args or {})
            system_config = args.pop("_system_config", None)
            if system_config:
                from ._internal.config import CONFIG
                CONFIG.apply_system_config(system_config)
            gcs_address = None
            if external_gcs:
                # Killable control plane: the GCS lives in its own
                # process at a FIXED port (restarts keep the address, so
                # reconnecting clients need no rediscovery).
                self._gcs_port = free_port()
                self.gcs_proc = spawn_gcs(
                    self._gcs_port, self.session_name,
                    persist=self._gcs_persist, env=self._gcs_env)
                gcs_address = ("127.0.0.1", self._gcs_port)
            self.head_node = Node(
                head=not external_gcs, is_head=True,
                session_name=self.session_name,
                gcs_address=gcs_address,
                resources=args.get("resources",
                                   {"CPU": args.get("num_cpus", 2)}),
                labels=args.get("labels"),
                object_store_memory=args.get("object_store_memory"))
            self.head_node.start()

    @property
    def gcs_address(self) -> Address:
        return self.head_node.gcs_address

    @property
    def address(self) -> str:
        host, port = self.gcs_address
        return f"{host}:{port}"

    def connect(self, namespace: str = ""):
        """Attach the current process as the driver."""
        import ray_tpu
        worker = ray_tpu.init(_node=self.head_node, namespace=namespace)
        self._connected = True
        return worker

    def add_node(self, num_cpus: float = 2, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: int = 0,
                 env: Optional[Dict[str, str]] = None,
                 wait: bool = True,
                 node_index: Optional[int] = None) -> RemoteNodeHandle:
        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", num_cpus)
        if num_tpus:
            node_resources["TPU"] = num_tpus
        if node_index is None:
            index = self._next_index
            self._next_index += 1
        else:
            index = node_index
        cmd = [
            sys.executable, "-m", "ray_tpu._internal.raylet_main",
            "--gcs-address", self.address,
            "--session", self.session_name,
            "--node-index", str(index),
            "--resources", json.dumps(node_resources),
            "--labels", json.dumps(labels or {}),
        ]
        if object_store_memory:
            cmd += ["--object-store-memory", str(object_store_memory)]
        proc_env = dict(os.environ)
        proc_env.update(env or {})
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=None, env=proc_env, text=True)
        node_id, address = None, None
        if wait:
            line = _wait_ready_line(proc, "RTPU_RAYLET_READY", "raylet")
            _, node_id, addr = line.split()
            host, port = addr.rsplit(":", 1)
            address = (host, int(port))
        handle = RemoteNodeHandle(proc, node_id, address, index,
                                  resources=node_resources,
                                  labels=labels,
                                  object_store_memory=object_store_memory,
                                  env=env)
        self.remote_nodes.append(handle)
        return handle

    def remove_node(self, handle: RemoteNodeHandle,
                    allow_graceful: bool = False):
        """Kill a node (SIGKILL unless graceful) — failure-injection
        primitive for fault-tolerance tests."""
        if allow_graceful:
            handle.proc.terminate()
        else:
            handle.proc.kill()
        handle.proc.wait(timeout=30)
        self.remote_nodes.remove(handle)

    # -- fleet operations (rolling upgrades / head failover) -----------

    def drain_node(self, handle: RemoteNodeHandle,
                   timeout_s: Optional[float] = None,
                   exit_process: bool = False) -> Dict:
        """GCS-coordinated graceful drain of one subprocess raylet
        (requires a connected driver for the state API)."""
        from ray_tpu.util.state import api as state_api
        return state_api.drain_node(handle.node_id, timeout_s=timeout_s,
                                    exit_process=exit_process)

    def restart_node(self, handle: RemoteNodeHandle,
                     drain: bool = True,
                     timeout_s: Optional[float] = None,
                     wait: bool = True) -> RemoteNodeHandle:
        """One rolling-restart step: gracefully drain the raylet (fence
        → actor migration → in-flight leases → clean exit), then launch
        a replacement at the same index (fresh node id) and wait for it
        to register. With ``drain=False`` it is a crash-restart
        (SIGKILL) instead."""
        report: Dict = {}
        if drain:
            report = self.drain_node(handle, timeout_s=timeout_s,
                                     exit_process=True)
            if report.get("error"):
                raise RuntimeError(f"drain failed: {report['error']}")
            try:
                handle.proc.wait(timeout=(timeout_s or 60) + 30)
            except subprocess.TimeoutExpired:
                logger.warning("drained raylet %s did not exit; killing",
                               handle.node_id[:12])
                handle.proc.kill()
                handle.proc.wait(timeout=30)
            self.remote_nodes.remove(handle)
        else:
            self.remove_node(handle)
        replacement = self.add_node(
            resources=handle.resources, labels=handle.labels,
            object_store_memory=handle.object_store_memory,
            env=handle.env, wait=wait, node_index=handle.node_index)
        replacement.drain_report = report
        return replacement

    def rolling_restart(self, timeout_s: Optional[float] = None,
                        between=None) -> List[RemoteNodeHandle]:
        """Drain-and-replace every subprocess raylet one by one (the
        `cli rollout` flow against an in-test cluster). ``between`` is
        an optional callback run after each node (the soak bench
        injects its mid-rollout GCS kill there)."""
        replaced = []
        for handle in list(self.remote_nodes):
            replaced.append(self.restart_node(handle,
                                              timeout_s=timeout_s))
            if between is not None:
                between(replaced[-1])
        return replaced

    def kill_gcs(self):
        """SIGKILL the external GCS subprocess (head-failover drill)."""
        if self.gcs_proc is None:
            raise RuntimeError("kill_gcs requires external_gcs=True")
        self.gcs_proc.kill()
        self.gcs_proc.wait(timeout=30)

    def restart_gcs(self):
        """Respawn the external GCS at the SAME port (clients reconnect
        with no rediscovery; with a persist path the state recovers via
        WAL replay and the incarnation bumps)."""
        if self._gcs_port is None:
            raise RuntimeError("restart_gcs requires external_gcs=True")
        if self.gcs_proc is not None and self.gcs_proc.poll() is None:
            self.kill_gcs()
        self.gcs_proc = spawn_gcs(
            self._gcs_port, self.session_name,
            persist=self._gcs_persist, env=self._gcs_env)
        return self.gcs_proc

    def wait_for_nodes(self, count: Optional[int] = None,
                       timeout: float = 60.0):
        """Wait until the GCS sees `count` alive nodes (default: all)."""
        import ray_tpu
        expected = count if count is not None \
            else 1 + len(self.remote_nodes)
        deadline = time.time() + timeout
        while time.time() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["state"] == "ALIVE"]
            if len(alive) >= expected:
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"expected {expected} alive nodes within {timeout}s")

    def shutdown(self):
        import ray_tpu
        if self._connected:
            ray_tpu.shutdown()
        for handle in list(self.remote_nodes):
            try:
                handle.proc.kill()
                handle.proc.wait(timeout=10)
            except Exception:
                logger.debug("kill of remote node pid %s failed",
                             handle.proc.pid, exc_info=True)
        self.remote_nodes.clear()
        if self.head_node is not None:
            self.head_node.stop()
            self.head_node = None
        if self.gcs_proc is not None:
            try:
                if self.gcs_proc.poll() is None:
                    self.gcs_proc.terminate()
                    self.gcs_proc.wait(timeout=10)
            except Exception:
                logger.debug("gcs subprocess teardown failed",
                             exc_info=True)
            self.gcs_proc = None
