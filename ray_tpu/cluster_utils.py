"""Multi-node test cluster on one machine
(reference: python/ray/cluster_utils.py — Cluster, add_node).

The head node (GCS + raylet) runs in-process; `add_node` launches additional
raylets as real subprocesses, giving genuine multi-node semantics — separate
object stores, cross-node object transfer, node kill/failure tests — without
containers. This fixture carries most of the reference's distributed test
coverage (SURVEY §4.2)."""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ._internal.node import Node, new_session_name
from ._internal.rpc import Address

logger = logging.getLogger(__name__)


class RemoteNodeHandle:
    def __init__(self, proc: subprocess.Popen, node_id: str, address: Address,
                 node_index: int):
        self.proc = proc
        self.node_id = node_id
        self.address = address
        self.node_index = node_index


class Cluster:
    def __init__(self, initialize_head: bool = True,
                 head_node_args: Optional[Dict] = None):
        self.session_name = new_session_name()
        self.head_node: Optional[Node] = None
        self.remote_nodes: List[RemoteNodeHandle] = []
        self._next_index = 1
        self._connected = False
        if initialize_head:
            args = dict(head_node_args or {})
            system_config = args.pop("_system_config", None)
            if system_config:
                from ._internal.config import CONFIG
                CONFIG.apply_system_config(system_config)
            self.head_node = Node(
                head=True, session_name=self.session_name,
                resources=args.get("resources", {"CPU": args.get("num_cpus", 2)}),
                labels=args.get("labels"),
                object_store_memory=args.get("object_store_memory"))
            self.head_node.start()

    @property
    def gcs_address(self) -> Address:
        return self.head_node.gcs_address

    @property
    def address(self) -> str:
        host, port = self.gcs_address
        return f"{host}:{port}"

    def connect(self, namespace: str = ""):
        """Attach the current process as the driver."""
        import ray_tpu
        worker = ray_tpu.init(_node=self.head_node, namespace=namespace)
        self._connected = True
        return worker

    def add_node(self, num_cpus: float = 2, num_tpus: float = 0,
                 resources: Optional[Dict[str, float]] = None,
                 labels: Optional[Dict[str, str]] = None,
                 object_store_memory: int = 0,
                 env: Optional[Dict[str, str]] = None,
                 wait: bool = True) -> RemoteNodeHandle:
        node_resources = dict(resources or {})
        node_resources.setdefault("CPU", num_cpus)
        if num_tpus:
            node_resources["TPU"] = num_tpus
        index = self._next_index
        self._next_index += 1
        cmd = [
            sys.executable, "-m", "ray_tpu._internal.raylet_main",
            "--gcs-address", self.address,
            "--session", self.session_name,
            "--node-index", str(index),
            "--resources", json.dumps(node_resources),
            "--labels", json.dumps(labels or {}),
        ]
        if object_store_memory:
            cmd += ["--object-store-memory", str(object_store_memory)]
        proc_env = dict(os.environ)
        proc_env.update(env or {})
        proc = subprocess.Popen(cmd, stdout=subprocess.PIPE,
                                stderr=None, env=proc_env, text=True)
        node_id, address = None, None
        if wait:
            deadline = time.time() + 60
            while time.time() < deadline:
                line = proc.stdout.readline()
                if line.startswith("RTPU_RAYLET_READY"):
                    _, node_id, addr = line.split()
                    host, port = addr.rsplit(":", 1)
                    address = (host, int(port))
                    break
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"raylet subprocess exited rc={proc.returncode}")
            else:
                raise TimeoutError("raylet did not come up in 60s")
        handle = RemoteNodeHandle(proc, node_id, address, index)
        self.remote_nodes.append(handle)
        return handle

    def remove_node(self, handle: RemoteNodeHandle,
                    allow_graceful: bool = False):
        """Kill a node (SIGKILL unless graceful) — failure-injection
        primitive for fault-tolerance tests."""
        if allow_graceful:
            handle.proc.terminate()
        else:
            handle.proc.kill()
        handle.proc.wait(timeout=30)
        self.remote_nodes.remove(handle)

    def wait_for_nodes(self, count: Optional[int] = None,
                       timeout: float = 60.0):
        """Wait until the GCS sees `count` alive nodes (default: all)."""
        import ray_tpu
        expected = count if count is not None \
            else 1 + len(self.remote_nodes)
        deadline = time.time() + timeout
        while time.time() < deadline:
            alive = [n for n in ray_tpu.nodes() if n["state"] == "ALIVE"]
            if len(alive) >= expected:
                return
            time.sleep(0.1)
        raise TimeoutError(
            f"expected {expected} alive nodes within {timeout}s")

    def shutdown(self):
        import ray_tpu
        if self._connected:
            ray_tpu.shutdown()
        for handle in list(self.remote_nodes):
            try:
                handle.proc.kill()
                handle.proc.wait(timeout=10)
            except Exception:
                logger.debug("kill of remote node pid %s failed",
                             handle.proc.pid, exc_info=True)
        self.remote_nodes.clear()
        if self.head_node is not None:
            self.head_node.stop()
            self.head_node = None
