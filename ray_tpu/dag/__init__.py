"""ray_tpu.dag — compiled actor dataflow graphs
(reference: python/ray/dag/ — DAGNode bind API, CompiledDAG
compiled_dag_node.py:805, per-actor exec loops :186/:1863, driver
execute :2546)."""

from .compiled_dag import CompiledDAG
from .nodes import InputNode, MultiOutputNode

__all__ = ["CompiledDAG", "InputNode", "MultiOutputNode"]
