"""CompiledDAG: freeze a bound graph into channel-connected exec loops
(reference: dag/compiled_dag_node.py:805 CompiledDAG — channel allocation,
per-actor schedules, exec-loop installation :1863, driver execute :2546 /
teardown).

Why compiled graphs exist: per-call actor RPC costs ~1ms through the
control plane. A fixed dataflow topology (e.g. a pipelined inference
graph between device-holding actors) pays channel hops instead —
microseconds over shared memory, no per-step scheduling. This is the
control-plane analog of the reference's accelerator channels: device data
stays put inside each actor; only (small) values cross."""

from __future__ import annotations

import logging
import os
import time
import uuid
from typing import Any, Dict, List, Tuple

from .nodes import ClassMethodNode, DAGNode, InputNode, MultiOutputNode

logger = logging.getLogger(__name__)


class CompiledDAG:
    def __init__(self, output_node: DAGNode,
                 channel_capacity: int = 8 * 1024 * 1024,
                 timeout_s: float = 60.0):
        self._dag_id = uuid.uuid4().hex[:10]
        self._capacity = channel_capacity
        self._timeout = timeout_s
        self._torn_down = False

        if isinstance(output_node, MultiOutputNode):
            self._final_nodes = list(output_node.outputs)
        else:
            self._final_nodes = [output_node]
        for node in self._final_nodes:
            if not isinstance(node, ClassMethodNode):
                raise TypeError("DAG outputs must be bound actor methods")

        self._order = self._toposort()
        self._compile()

    # -- graph analysis ----------------------------------------------------

    def _toposort(self) -> List[ClassMethodNode]:
        order: List[ClassMethodNode] = []
        seen = set()

        def visit(node: DAGNode):
            if id(node) in seen or not isinstance(node, ClassMethodNode):
                return
            seen.add(id(node))
            for up in node.upstream_nodes():
                visit(up)
            order.append(node)

        for node in self._final_nodes:
            visit(node)
        return order

    def _chan_path(self, edge: str) -> str:
        root = "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"
        return os.path.join(root, f"rtpu-dag-{self._dag_id}-{edge}")

    def _compile(self):
        from ..experimental.channel import SharedMemoryChannel
        import ray_tpu
        from .._internal.core_worker import get_core_worker

        # Channels are files in this node's /dev/shm: every participating
        # actor must be co-located with the driver (cross-node compiled
        # graphs would need an RPC/DCN channel type — not yet built).
        worker = get_core_worker()
        gcs = worker.gcs
        for node in self._order:
            deadline = time.monotonic() + 60
            while True:
                info = gcs.call_sync("get_actor_info",
                                     actor_id=node.actor.actor_id)
                if info is not None and info["state"] == "ALIVE":
                    break
                if info is not None and info["state"] == "DEAD" or \
                        time.monotonic() > deadline:
                    raise RuntimeError(
                        f"actor for {node.method_name} is not alive")
                time.sleep(0.05)
            actor_host = (info.get("address") or (None, None))[0]
            if actor_host and actor_host != worker.rpc_address[0]:
                raise NotImplementedError(
                    "compiled DAGs currently require all actors on the "
                    "driver's host (shared-memory channels); actor "
                    f"{node.method_name} is on {actor_host}")

        node_index = {id(n): i for i, n in enumerate(self._order)}
        # Edges: producer node -> consumer arg slots; input -> consumers.
        self._input_paths: List[str] = []       # driver writes these
        self._output_paths: List[str] = []      # driver reads these
        out_edges: Dict[int, List[str]] = {i: [] for i in
                                           range(len(self._order))}
        arg_sources: Dict[int, List[Tuple[str, Any]]] = {}
        kwarg_sources: Dict[int, Dict[str, Tuple[str, Any]]] = {}
        created: List[SharedMemoryChannel] = []

        def make_channel(edge: str) -> str:
            path = self._chan_path(edge)
            created.append(SharedMemoryChannel(
                path, capacity=self._capacity, create=True))
            return path

        for i, node in enumerate(self._order):
            sources = []
            for j, arg in enumerate(node.args):
                sources.append(self._source_for(arg, i, j, node_index,
                                                out_edges, make_channel))
            arg_sources[i] = sources
            ksources = {}
            for name, value in node.kwargs.items():
                ksources[name] = self._source_for(
                    value, i, f"k{name}", node_index, out_edges,
                    make_channel)
            kwarg_sources[i] = ksources

        for node in self._final_nodes:
            i = node_index[id(node)]
            path = make_channel(f"out-{i}")
            out_edges[i].append(path)
            self._output_paths.append(path)

        self._channels = created

        # Group steps per actor, preserving topological order.
        per_actor: Dict[bytes, Tuple[Any, List[Dict[str, Any]]]] = {}
        actor_local_index: Dict[Tuple[bytes, int], int] = {}
        for i, node in enumerate(self._order):
            key = node.actor.actor_id
            if key not in per_actor:
                per_actor[key] = (node.actor, [])
            _, steps = per_actor[key]
            # Rewrite ("node", producer_idx) into local/channel sources.
            def resolve(src):
                kind, value = src
                if kind != "node":
                    return src
                producer = value
                if self._order[producer].actor.actor_id == key:
                    return ("local", actor_local_index[(key, producer)])
                # Cross-actor edge: a dedicated channel.
                path = make_channel(f"e{producer}-{i}-{len(created)}")
                out_edges[producer].append(path)
                return ("chan", path)
            steps.append({
                "method": node.method_name,
                "args": [resolve(s) for s in arg_sources[i]],
                "kwargs": {k: resolve(s)
                           for k, s in kwarg_sources[i].items()},
                "outs": out_edges[i],  # shared list: filled as edges added
                "_index": i,
            })
            actor_local_index[(key, i)] = len(steps) - 1

        # Out-edge lists were mutated after step construction; materialize.
        for _actor, steps in per_actor.values():
            for step in steps:
                step["outs"] = list(out_edges[step.pop("_index")])

        self._loop_refs = []
        self._actors = []
        for actor, steps in per_actor.values():
            self._actors.append(actor)
            ref = actor._submit_method("__rtpu_dag_exec__",
                                       (steps, self._timeout), {}, {})
            self._loop_refs.append(ref)

    def _source_for(self, arg, consumer_idx, slot, node_index, out_edges,
                    make_channel):
        if isinstance(arg, InputNode):
            path = make_channel(f"in-{consumer_idx}-{slot}")
            self._input_paths.append(path)
            return ("chan", path)
        if isinstance(arg, ClassMethodNode):
            return ("node", node_index[id(arg)])
        if isinstance(arg, DAGNode):
            raise TypeError(f"unsupported DAG node {type(arg).__name__}")
        return ("const", arg)

    # -- driver API --------------------------------------------------------

    def execute(self, *input_value) -> Any:
        """One synchronous step: feed the input, return the output(s)."""
        self.feed(*input_value)
        return self.drain()

    def feed(self, *input_value) -> None:
        """Write one input WITHOUT waiting for its output — the
        pipelined half of execute(). Keeping several feeds in flight
        lets chained actors overlap (stage s works on item t while
        stage s+1 works on item t-1 — the MPMD microbatch schedule).
        Channels are single-slot, so feed blocks once the graph and the
        slots are full: callers must drain() concurrently past a depth
        of ~2x the chain length or the feed/drain pair deadlocks."""
        if self._torn_down:
            raise RuntimeError("DAG has been torn down")
        value = input_value[0] if len(input_value) == 1 else input_value
        for path in self._input_paths:
            self._chan_by_path(path).put(value, timeout=self._timeout)

    def drain(self) -> Any:
        """Read one output (FIFO order of the feeds)."""
        if self._torn_down:
            raise RuntimeError("DAG has been torn down")
        outs = [self._chan_by_path(p).get(timeout=self._timeout)
                for p in self._output_paths]
        from ..experimental.channel import DagTaskError
        for out in outs:
            if isinstance(out, DagTaskError):
                raise out
        return outs if len(outs) > 1 else outs[0]

    def _chan_by_path(self, path: str):
        for ch in self._channels:
            if ch.path == path:
                return ch
        raise KeyError(path)

    def teardown(self):
        if self._torn_down:
            return
        self._torn_down = True
        import ray_tpu
        for ch in self._channels:
            ch.close()
        # Loops observe the close sentinel and return their iteration count.
        try:
            ray_tpu.get(self._loop_refs, timeout=30)
        except Exception:  # noqa: BLE001 — teardown is best-effort
            logger.debug("dag loop join at teardown failed", exc_info=True)
        for ch in self._channels:
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:  # noqa: BLE001
            pass
