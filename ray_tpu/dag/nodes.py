"""DAG node API: `.bind()` builds the graph, `experimental_compile()`
freezes it (reference: dag/dag_node.py DAGNode + class_node/method
binding; InputNode input_node.py; MultiOutputNode output_node.py)."""

from __future__ import annotations

from typing import Any, List, Optional


class DAGNode:
    def __init__(self):
        self._downstream: List["DAGNode"] = []

    def experimental_compile(self, **kwargs):
        from .compiled_dag import CompiledDAG
        return CompiledDAG(self, **kwargs)


class InputNode(DAGNode):
    """The driver-provided input (context-manager form mirrors the
    reference: `with InputNode() as inp: ...`)."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class ClassMethodNode(DAGNode):
    """One bound actor-method invocation in the graph."""

    def __init__(self, actor, method_name: str, args: tuple,
                 kwargs: dict):
        super().__init__()
        self.actor = actor
        self.method_name = method_name
        self.args = args
        self.kwargs = kwargs

    def upstream_nodes(self) -> List[DAGNode]:
        return [a for a in self.args if isinstance(a, DAGNode)] + \
            [v for v in self.kwargs.values() if isinstance(v, DAGNode)]


class MultiOutputNode(DAGNode):
    def __init__(self, outputs: List[DAGNode]):
        super().__init__()
        self.outputs = list(outputs)


def bind(actor_method, *args, **kwargs) -> ClassMethodNode:
    """actor.method.bind(...) — attached to ActorMethod."""
    handle = actor_method._handle
    return ClassMethodNode(handle, actor_method._method_name, args, kwargs)
