"""Actor-side compiled-DAG execution loop
(reference: dag/compiled_dag_node.py do_exec_tasks :186 — the actor is
pinned into a loop that reads input channels, runs its bound methods, and
writes output channels until torn down)."""

from __future__ import annotations

import logging
from typing import Any, Dict, List

from ..experimental.channel import (ChannelClosedError, DagTaskError,
                                    SharedMemoryChannel)

logger = logging.getLogger(__name__)


def exec_loop(instance: Any, plan: List[Dict[str, Any]],
              timeout_s: float) -> int:
    """Run this actor's steps until any channel closes.

    plan: topologically ordered steps:
      {"method": str,
       "args": [("const", value) | ("chan", path) | ("local", step_idx)],
       "kwargs": {name: same-source-tuples},
       "outs": [channel paths]}
    Channels are opened lazily here (the compiler creates the files).
    """
    channels: Dict[str, SharedMemoryChannel] = {}

    def chan(path: str) -> SharedMemoryChannel:
        ch = channels.get(path)
        if ch is None:
            ch = SharedMemoryChannel(path, create=False)
            channels[path] = ch
        return ch

    iterations = 0
    try:
        while True:
            local_results: List[Any] = []
            for step in plan:
                args = []
                for kind, value in step["args"]:
                    if kind == "const":
                        args.append(value)
                    elif kind == "chan":
                        args.append(chan(value).get(timeout=timeout_s))
                    else:
                        args.append(local_results[value])
                kwargs = {}
                for name, (kind, value) in step["kwargs"].items():
                    if kind == "const":
                        kwargs[name] = value
                    elif kind == "chan":
                        kwargs[name] = chan(value).get(timeout=timeout_s)
                    else:
                        kwargs[name] = local_results[value]
                poison = next(
                    (a for a in [*args, *kwargs.values()]
                     if isinstance(a, DagTaskError)), None)
                if poison is not None:
                    out = poison  # forward upstream failure unexecuted
                else:
                    try:
                        out = getattr(instance, step["method"])(
                            *args, **kwargs)
                    except Exception:  # noqa: BLE001 — to the driver
                        import traceback
                        out = DagTaskError(step["method"],
                                           traceback.format_exc())
                local_results.append(out)
                for path in step["outs"]:
                    chan(path).put(out, timeout=timeout_s)
            iterations += 1
    except ChannelClosedError:
        return iterations
    finally:
        for ch in channels.values():
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                logger.debug("channel close in exec loop failed",
                             exc_info=True)
