"""ray_tpu.dashboard — REST observability + job submission endpoint
(reference: python/ray/dashboard — DashboardHead head.py:49, job REST
modules/job/, state aggregation state_aggregator.py, Prometheus metrics
modules/metrics/)."""

from .head import DashboardHead, start_dashboard

__all__ = ["DashboardHead", "start_dashboard"]
