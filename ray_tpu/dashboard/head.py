"""DashboardHead: the cluster's REST surface
(reference: dashboard/head.py:49 DashboardHead — aiohttp app serving the
state API, job routes, and Prometheus metrics; here a dependency-free
asyncio HTTP server inside a detached actor).

Routes:
  GET  /api/cluster_status            nodes + aggregate resources
  GET  /api/nodes|actors|tasks|placement_groups|objects|workers
  GET  /api/rpc                       transport observatory (per-method
                                      latency, rings, slow-RPC ring)
  GET  /api/jobs/                     submitted jobs
  POST /api/jobs/                     {entrypoint, ...} -> submission_id
  GET  /api/jobs/<id>                 job info
  GET  /api/jobs/<id>/logs            {"logs": ...}
  POST /api/jobs/<id>/stop
  GET  /api/timeline                  chrome-trace JSON of task spans
  GET  /api/train_timeline            cross-rank train-step timeline
  GET  /api/serve_timeline            per-request serve lifecycle trace
  GET  /api/requests                  serve request folds (?by=tenant|
                                      route) / ?why=<id> attribution
  GET  /api/stragglers                straggler events + step-time skew
  GET  /api/alerts                    SLO alert table (alert engine)
                                      (?since= for incremental polls)
  GET  /api/memory                    cluster memory summary (stores,
                                      per-object refs, leak heuristic)
  GET  /api/events                    GCS cluster event log
  GET  /api/gcs                       GCS failover status (incarnation,
                                      persist mode, WAL bytes, failovers)
  GET  /api/traces                    recorded trace summaries
  GET  /api/traces/<trace_id>         one trace's span tree
  GET  /api/profile                   cluster CPU profile (no ?pid=) or
                                      one-shot worker capture (?pid=)
  GET  /api/profile/status            fleet sampler status
  GET  /api/stacks                    fleet-wide stack dumps
  GET  /api/devices                   cluster accelerator summary
                                      (per-device HBM, XLA compile,
                                      step/MFU telemetry)
  GET  /api/logs                      attributed worker log lines from
                                      the raylet rings (?task=&actor=&
                                      job=&level=&grep=&tail=&since=)
  GET  /api/logs/rings                per-worker ring inventory
  GET  /metrics                       Prometheus exposition
  GET  /-/healthz
  GET  /                              web frontend (single-page app,
                                      client/index.html — the analog of
                                      the reference's React frontend in
                                      dashboard/client/src/, rebuilt
                                      dependency-free over these routes)
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import urllib.parse
from typing import Any, Dict, Optional, Tuple

logger = logging.getLogger(__name__)

DASHBOARD_NAME = "DASHBOARD_HEAD"
DASHBOARD_NAMESPACE = "_dashboard"


class DashboardHead:
    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self._host = host
        self._port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self._job_manager = None

    async def ready(self) -> Tuple[str, int]:
        if self._server is None:
            self._server = await asyncio.start_server(
                self._handle_conn, self._host, self._port)
            self._port = self._server.sockets[0].getsockname()[1]
            # The SLO alert engine rides with the dashboard head: one
            # registry-registered daemon evaluating the cluster's
            # flushed metrics every alert_eval_interval_s.
            from .._internal.alerts import ensure_engine
            ensure_engine()
        return (self._host, self._port)

    # -- HTTP plumbing (same shape as serve's proxy) ----------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:
                line = await reader.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
                method, target, _v = line.decode("latin1").strip().split(
                    " ", 2)
                headers = {}
                while True:
                    hline = await reader.readline()
                    if not hline or hline in (b"\r\n", b"\n"):
                        break
                    name, _, value = hline.decode("latin1").partition(":")
                    headers[name.strip().lower()] = value.strip()
                length = int(headers.get("content-length", 0) or 0)
                body = await reader.readexactly(length) if length else b""
                parsed = urllib.parse.urlsplit(target)
                query = dict(urllib.parse.parse_qsl(parsed.query))
                status, payload, ctype = await self._route(
                    method.upper(), parsed.path, query, body)
                reason = {200: "OK", 404: "Not Found",
                          400: "Bad Request",
                          500: "Internal Server Error"}.get(status, "")
                writer.write(
                    f"HTTP/1.1 {status} {reason}\r\n"
                    f"Content-Type: {ctype}\r\n"
                    f"Content-Length: {len(payload)}\r\n\r\n"
                    .encode("latin1") + payload)
                await writer.drain()
                if headers.get("connection", "").lower() == "close":
                    break
        except (ConnectionError, asyncio.IncompleteReadError, ValueError):
            pass
        except Exception:  # noqa: BLE001
            logger.exception("dashboard connection failed")
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                logger.debug("dashboard conn close failed", exc_info=True)

    async def _route(self, method: str, path: str, query: Dict[str, str],
                     body: bytes) -> Tuple[int, bytes, str]:
        loop = asyncio.get_running_loop()
        try:
            # Blocking state/GCS lookups run off-loop.
            return await loop.run_in_executor(
                None, self._route_sync, method, path, query, body)
        except Exception as e:  # noqa: BLE001
            logger.exception("route %s %s failed", method, path)
            return (500, json.dumps({"error": str(e)}).encode(),
                    "application/json")

    def _json(self, obj, status: int = 200) -> Tuple[int, bytes, str]:
        return (status, json.dumps(obj, default=str).encode(),
                "application/json")

    def _route_sync(self, method: str, path: str, query: Dict[str, str],
                    body: bytes) -> Tuple[int, bytes, str]:
        from ..util import state as st

        if path == "/-/healthz":
            return (200, b"ok", "text/plain")
        if path in ("/", "/index.html"):
            import os
            page = os.path.join(os.path.dirname(
                os.path.abspath(__file__)), "client", "index.html")
            with open(page, "rb") as f:
                return (200, f.read(), "text/html; charset=utf-8")
        if path == "/metrics":
            from .._internal.core_worker import get_core_worker
            from ..util.metrics import (collect_cluster_metrics,
                                        prometheus_text)
            text = prometheus_text(
                collect_cluster_metrics(get_core_worker().gcs))
            return (200, text.encode(), "text/plain; version=0.0.4")
        if path == "/api/cluster_status":
            nodes = st.list_nodes()
            total: Dict[str, float] = {}
            available: Dict[str, float] = {}
            for node in nodes:
                for k, v in node["resources_total"].items():
                    total[k] = total.get(k, 0) + v
                for k, v in node["resources_available"].items():
                    available[k] = available.get(k, 0) + v
            return self._json({"nodes": nodes, "resources_total": total,
                               "resources_available": available})
        if path == "/api/nodes":
            return self._json(st.list_nodes())
        node_match = re.fullmatch(r"/api/nodes/([0-9a-f]+)/stats", path)
        if node_match:
            # per-node agent stats, proxied to that node's raylet
            # (reference: dashboard/agent.py + reporter_agent.py — the
            # raylet serves the agent surface here)
            from .._internal.core_worker import get_core_worker
            node_hex = node_match.group(1)
            node = next((n for n in st.list_nodes()
                         if n["node_id"].startswith(node_hex)), None)
            if node is None:
                return (404, b"unknown node", "text/plain")
            client = get_core_worker().clients.get(
                tuple(node["address"]))
            return self._json(client.call_sync("agent_stats",
                                               timeout=30))
        if path == "/api/actors":
            return self._json(st.list_actors())
        if path == "/api/tasks":
            since = query.get("since")
            return self._json(st.list_tasks(
                job_id=query.get("job_id"),
                limit=int(query.get("limit", 1000)),
                since=float(since) if since else None))
        if path == "/api/placement_groups":
            return self._json(st.list_placement_groups())
        if path == "/api/objects":
            return self._json(st.list_objects())
        if path == "/api/workers":
            return self._json(st.list_workers())
        if path == "/api/shards":
            # owner-shard rows per fan-in process (drivers + self):
            # queue depth / submits / loop lag per shard
            return self._json(st.shard_summary())
        if path == "/api/rpc":
            # transport observatory: per-method latency percentiles,
            # retry/error/chaos counters, native-ring stats, slow ring
            return self._json(st.rpc_summary())
        if path == "/api/timeline":
            since = query.get("since")
            return self._json(st.timeline(
                job_id=query.get("job_id"),
                since=float(since) if since else None))
        if path == "/api/train_timeline":
            # cross-rank train-step timeline (steptrace fold) — the
            # Timeline tab's train view
            return self._json(st.train_timeline())
        if path == "/api/serve_timeline":
            # per-request serve lifecycle timeline (reqtrace fold) —
            # the Serve tab's chrome-trace view
            return self._json(st.serve_timeline())
        if path == "/api/requests":
            # serve request observatory: percentile folds (optionally
            # ?by=tenant|route) or one request's ?why=<id> attribution
            why = query.get("why")
            if why:
                return self._json(st.why_slow(why))
            return self._json(st.serve_requests(by=query.get("by")))
        if path == "/api/stragglers":
            return self._json(st.stragglers(
                limit=int(query.get("limit", 100))))
        if path == "/api/alerts":
            since = query.get("since")
            return self._json(st.alerts(
                rule=query.get("rule"),
                severity=query.get("severity"),
                since=float(since) if since else None,
                limit=int(query.get("limit", 100))))
        if path == "/api/memory":
            return self._json(st.memory_summary(
                limit=int(query.get("limit", 1000))))
        if path == "/api/events":
            since = query.get("since")
            return self._json(st.list_events(
                event_type=query.get("type"),
                since=float(since) if since else None,
                limit=int(query.get("limit", 500))))
        if path == "/api/gcs":
            # Failover surface: incarnation, persist mode, WAL bytes,
            # failover count, persist-failure streak.
            return self._json(st.gcs_info())
        if path == "/api/autoscaler":
            # Autoscaler state manager view: per-node capacity /
            # pending-lease queue depth + age / drain flag and the
            # aggregate unmet demand the elastic reconciler acts on.
            return self._json(st.autoscaler_state())
        if path == "/api/traces":
            return self._json(st.list_traces(
                limit=int(query.get("limit", 100))))
        trace_match = re.fullmatch(r"/api/traces/([0-9a-f]+)", path)
        if trace_match:
            tree = st.get_trace(trace_match.group(1))
            if not tree["num_spans"]:
                return self._json({"error": "no such trace"}, 404)
            return self._json(tree)
        if path == "/api/profile/status":
            return self._json(st.profiling_status())
        if path == "/api/profile":
            return self._route_profile(query)
        if path == "/api/stacks":
            return self._json(st.stack_cluster(
                node_id=query.get("node_id")))
        if path == "/api/devices":
            # the dashboard actor's own process stays jax-free — only
            # workers/drivers that already run jax contribute devices;
            # short per-node timeout so a hung raylet can't wedge the tab
            return self._json(st.accel_summary(force_local_jax=False,
                                               node_timeout_s=10))
        if path == "/api/logs":
            # cluster log search over the per-worker raylet rings
            # (?task=&actor=&job=&node_id=&level=&grep=&tail=&limit=
            # &since=<cursor json> — since is the "cursors" object a
            # previous reply returned, for follow-style polling)
            since = query.get("since")
            tail = query.get("tail")
            return self._json(st.get_logs(
                task=query.get("task"), actor=query.get("actor"),
                job=query.get("job"), node_id=query.get("node_id"),
                level=query.get("level"), grep=query.get("grep"),
                tail=int(tail) if tail else None,
                limit=int(query.get("limit", 1000)),
                since=json.loads(since) if since else None))
        if path == "/api/logs/rings":
            return self._json(st.list_logs(
                node_id=query.get("node_id")))

        job_match = re.fullmatch(r"/api/jobs/([^/]*)(/logs|/stop)?", path)
        if path == "/api/jobs/" or job_match:
            return self._route_jobs(method, job_match, body, query)
        return (404, b"not found", "text/plain")

    def _route_profile(self, query: Dict[str, str]):
        """GET /api/profile — two scopes:

        Cluster (no ``pid``): ?duration=2&hz=100&format=json|collapsed|
        speedscope[&node_id=&task=&top=] — samples the whole fleet via
        profile_cluster and returns the merged report (collapsed text
        for format=collapsed, the speedscope document for
        format=speedscope, the full report otherwise).

        Single worker (``pid`` given): ?pid=&node_id=&kind=pystack|jax&
        duration=1 — the original one-shot capture proxied through that
        node's raylet (reference: dashboard/modules/reporter/
        profile_manager.py:82; TPU analog = jax xplane capture)."""
        from ..util import state as st

        pid = query.get("pid")
        if not pid:
            report = st.profile_cluster(
                duration_s=min(float(query.get("duration", 2.0)), 30.0),
                hz=float(query["hz"]) if query.get("hz") else None,
                node_id=query.get("node_id"),
                task=query.get("task"),
                top=int(query.get("top", 20)))
            fmt = query.get("format", "json")
            if fmt == "collapsed":
                return (200, report["collapsed"].encode(), "text/plain")
            if fmt == "speedscope":
                return self._json(report["speedscope"])
            return self._json(report)
        from .._internal.core_worker import get_core_worker
        worker = get_core_worker()
        node_id = query.get("node_id") or worker.node_id
        nodes = worker.gcs.call_sync("get_all_nodes", timeout=10)
        addr = next((tuple(n["address"]) for n in nodes
                     if n["node_id"] == node_id), None)
        if addr is None:
            return self._json({"error": f"unknown node {node_id}"}, 404)
        raylet = worker.clients.get(addr)
        reply = raylet.call_sync(
            "profile_worker", pid=int(pid),
            kind=query.get("kind", "pystack"),
            duration_s=float(query.get("duration", 1.0)),
            timeout=float(query.get("duration", 1.0)) + 90)
        if reply.get("error"):
            return self._json(reply, 404)
        ctype = "application/zip" if reply.get("kind") == "jax" \
            else "text/plain"
        return (200, reply["data"], ctype)

    def _route_jobs(self, method: str, match, body: bytes,
                    query: Optional[Dict[str, str]] = None):
        from ..job_submission import JobManager
        if self._job_manager is None:
            self._job_manager = JobManager()
        manager = self._job_manager
        query = query or {}
        sub_id = match.group(1) if match else ""
        action = match.group(2) if match else None

        if method == "POST" and not sub_id:
            payload = json.loads(body or b"{}")
            submission_id = manager.submit_job(
                entrypoint=payload["entrypoint"],
                submission_id=payload.get("submission_id"),
                runtime_env=payload.get("runtime_env"),
                metadata=payload.get("metadata"))
            return self._json({"submission_id": submission_id})
        if method == "GET" and not sub_id:
            return self._json(manager.list_jobs())
        if method == "GET" and action == "/logs":
            # Cursor pagination (?limit=&since=) — the /api/tasks
            # pattern; without params the legacy {"logs": <str>} shape
            # survives for small outputs, while big logs page instead
            # of shipping one unbounded concatenated string.
            if "limit" in query or "since" in query:
                return self._json(manager.get_job_logs_paged(
                    sub_id, limit=int(query.get("limit", 1000)),
                    since=int(query.get("since", 0))))
            info = manager.get_job_info(sub_id)
            try:
                import os as _os
                size = _os.path.getsize(info["log_path"]) if info else 0
            except OSError:
                size = 0
            if size <= 1_000_000:  # legacy shape for small outputs
                return self._json({"logs": manager.get_job_logs(sub_id)})
            return self._json(dict(
                manager.get_job_logs_paged(sub_id, limit=10_000),
                paged=True))
        if method == "POST" and action == "/stop":
            return self._json({"stopped": manager.stop_job(sub_id)})
        if method == "GET" and sub_id:
            info = manager.get_job_info(sub_id)
            if info is None:
                return self._json({"error": "no such job"}, 404)
            return self._json(info)
        return (400, b"bad job request", "text/plain")

    def ping(self):
        return True


def start_dashboard(host: str = "127.0.0.1", port: int = 0) -> str:
    """Start (or find) the dashboard head actor; returns its http address."""
    import ray_tpu
    try:
        head = ray_tpu.get_actor(DASHBOARD_NAME,
                                 namespace=DASHBOARD_NAMESPACE)
    except ValueError:
        head_cls = ray_tpu.remote(DashboardHead)
        head = head_cls.options(
            name=DASHBOARD_NAME, namespace=DASHBOARD_NAMESPACE,
            lifetime="detached", num_cpus=0, max_concurrency=100,
            get_if_exists=True).remote(host, port)
    bound_host, bound_port = ray_tpu.get(head.ready.remote(), timeout=60)
    return f"http://{bound_host}:{bound_port}"
