from .block import Block, BlockAccessor
from .context import DataContext
from .dataset import Dataset
from .iterator import DataIterator
from .read_api import (from_arrow, from_items, from_numpy, from_pandas,
                       from_torch, range, read_binary_files, read_csv,
                       read_images, read_json, read_numpy, read_parquet,
                       read_sql, read_text, read_tfrecords,
                       read_webdataset)

__all__ = [
    "Dataset", "DataIterator", "DataContext", "Block", "BlockAccessor",
    "range", "from_items", "from_pandas", "from_numpy", "from_arrow",
    "read_parquet", "read_csv", "read_json", "read_text",
    "read_binary_files", "read_images", "read_sql", "read_tfrecords",
    "read_numpy", "read_webdataset", "from_torch",
]
