"""Blocks: the unit of data movement
(reference: python/ray/data/block.py — blocks are Arrow tables in the object
store; operators exchange ObjectRefs to blocks).

A Block here is a pyarrow.Table (columnar path) or a Python list (simple/
object path). BlockAccessor normalizes both. Batches cross into JAX/numpy as
dicts of numpy arrays — zero-copy from shared memory whenever Arrow's layout
allows it."""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np
import pyarrow as pa

Block = Union[pa.Table, List[Any]]


class BlockAccessor:
    def __init__(self, block: Block):
        self.block = block

    @staticmethod
    def for_block(block: Block) -> "BlockAccessor":
        return BlockAccessor(block)

    @staticmethod
    def batch_to_block(batch: Any) -> Block:
        """Normalize a user-returned batch into a Block."""
        if isinstance(batch, pa.Table):
            return batch
        if isinstance(batch, dict):
            return pa.table({k: _to_arrow_array(v) for k, v in batch.items()})
        if isinstance(batch, list):
            return batch
        try:
            import pandas as pd
            if isinstance(batch, pd.DataFrame):
                return pa.Table.from_pandas(batch, preserve_index=False)
        except ImportError:
            pass
        raise TypeError(f"cannot convert batch of type {type(batch)} "
                        "to a block (use dict of arrays, pyarrow.Table, "
                        "pandas.DataFrame, or list)")

    # -- introspection ---------------------------------------------------

    def num_rows(self) -> int:
        if isinstance(self.block, pa.Table):
            return self.block.num_rows
        return len(self.block)

    def size_bytes(self) -> int:
        if isinstance(self.block, pa.Table):
            return self.block.nbytes
        return sum(len(repr(r)) for r in self.block[:10]) * \
            max(1, len(self.block) // 10)

    def schema(self):
        if isinstance(self.block, pa.Table):
            return self.block.schema
        if self.block:
            first = self.block[0]
            if isinstance(first, dict):
                return {k: type(v).__name__ for k, v in first.items()}
            return type(first).__name__
        return None

    # -- conversions -----------------------------------------------------

    def to_pylist(self) -> List[Any]:
        if isinstance(self.block, pa.Table):
            return self.block.to_pylist()
        return list(self.block)

    def to_pandas(self):
        if isinstance(self.block, pa.Table):
            return self.block.to_pandas()
        import pandas as pd
        return pd.DataFrame(self.block)

    def to_numpy_batch(self) -> Dict[str, np.ndarray]:
        if isinstance(self.block, pa.Table):
            out = {}
            for name in self.block.column_names:
                col = self.block.column(name)
                try:
                    out[name] = col.to_numpy(zero_copy_only=False)
                except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
                    out[name] = np.asarray(col.to_pylist(), dtype=object)
            return out
        if self.block and isinstance(self.block[0], dict):
            keys = self.block[0].keys()
            return {k: np.asarray([r[k] for r in self.block]) for k in keys}
        return {"item": np.asarray(self.block)}

    def to_batch(self, batch_format: str):
        if batch_format == "numpy":
            return self.to_numpy_batch()
        if batch_format == "pandas":
            return self.to_pandas()
        if batch_format in ("pyarrow", "arrow"):
            return self.block if isinstance(self.block, pa.Table) \
                else pa.table(self.to_numpy_batch())
        if batch_format == "default":
            return self.to_numpy_batch()
        raise ValueError(f"unknown batch_format {batch_format!r}")

    # -- slicing ---------------------------------------------------------

    def slice(self, start: int, end: int) -> Block:
        if isinstance(self.block, pa.Table):
            return self.block.slice(start, end - start)
        return self.block[start:end]

    def take_columns_row(self, index: int) -> Any:
        if isinstance(self.block, pa.Table):
            return {name: self.block.column(name)[index].as_py()
                    for name in self.block.column_names}
        return self.block[index]

    def iter_rows(self) -> Iterator[Any]:
        if isinstance(self.block, pa.Table):
            for batch in self.block.to_batches():
                yield from batch.to_pylist()
        else:
            yield from self.block

    @staticmethod
    def concat(blocks: List[Block]) -> Block:
        if not blocks:
            return []
        tables = [b for b in blocks if isinstance(b, pa.Table)]
        if tables and len(tables) == len(blocks):
            return pa.concat_tables(tables, promote_options="default")
        out: List[Any] = []
        for block in blocks:
            out.extend(BlockAccessor(block).to_pylist())
        return out

    @staticmethod
    def empty() -> Block:
        return []

    @staticmethod
    def from_rows(rows: List[Any]) -> Block:
        """Rows (dicts of scalars/arrays, or plain values) to a block —
        arrow table when the shape allows, else a list block."""
        if rows and isinstance(rows[0], dict) and all(
                np.isscalar(v) or isinstance(v, (np.ndarray, list, str))
                for v in rows[0].values()):
            try:
                keys = rows[0].keys()
                return pa.table({k: [r[k] for r in rows] for k in keys})
            except Exception:
                return rows
        return rows

    def sort_by(self, key, descending: bool = False) -> Block:
        if isinstance(self.block, pa.Table):
            order = "descending" if descending else "ascending"
            return self.block.sort_by([(key, order)])
        return sorted(self.block,
                      key=(key if callable(key) else
                           (lambda r: r[key] if isinstance(r, dict) else r)),
                      reverse=descending)


def _to_arrow_array(values):
    arr = np.asarray(values)
    if arr.ndim > 1:
        # Tensors: store as fixed-size lists.
        flat = arr.reshape(arr.shape[0], -1)
        return pa.FixedSizeListArray.from_arrays(
            pa.array(flat.ravel()), flat.shape[1])
    return pa.array(arr)
