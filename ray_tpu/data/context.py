"""DataContext (reference: python/ray/data/context.py:304)."""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    max_tasks_in_flight: int = 16
    read_parallelism: int = 8
    # Pipeline-wide CPU budget for the streaming executor's resource
    # manager (None = the cluster's CPU total). Map operators share it
    # fairly instead of each claiming a fixed in-flight window
    # (reference: execution/resource_manager.py).
    execution_cpu_budget: Optional[int] = None
    # Pipeline-wide object-store byte budget: when the bytes buffered in
    # operator queues (+ the consumer queue) exceed it, map operators
    # stop launching tasks until the consumer drains — a wide-row
    # pipeline cannot OOM the store while CPU-idle (reference:
    # execution/resource_manager.py object-store budgets +
    # backpressure_policy/). None = unlimited.
    execution_object_store_byte_budget: Optional[int] = None
    # "push": all-to-all exchanges consume map outputs in rounds of
    # push_shuffle_merge_factor, folding each round into one partial per
    # output partition as soon as it lands (merges pipeline with the next
    # round's maps; reduce fan-in is ceil(M/factor) instead of M).
    # "pull": one-shot plan — every reduce takes all M map parts directly
    # (reference: push_based_shuffle_task_scheduler.py:460).
    shuffle_strategy: str = "push"
    push_shuffle_merge_factor: int = 8
    # Streaming executor buffers (in blocks): per-operator edge buffer and
    # the consumer-facing output queue — both bound memory and carry the
    # backpressure signal upstream.
    op_output_buffer_blocks: int = 8
    streaming_output_buffer_blocks: int = 8

    _current = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = DataContext()
            return cls._current
