"""DataContext (reference: python/ray/data/context.py:304)."""

from __future__ import annotations

import dataclasses
import threading
from typing import Optional


@dataclasses.dataclass
class DataContext:
    target_max_block_size: int = 128 * 1024 * 1024
    target_min_block_size: int = 1 * 1024 * 1024
    max_tasks_in_flight: int = 16
    read_parallelism: int = 8
    # Pipeline-wide CPU budget for the streaming executor's resource
    # manager (None = the cluster's CPU total). Map operators share it
    # fairly instead of each claiming a fixed in-flight window
    # (reference: execution/resource_manager.py).
    execution_cpu_budget: Optional[int] = None
    # Pipeline-wide object-store byte budget: when the bytes buffered in
    # operator queues (+ the consumer queue) exceed it, map operators
    # stop launching tasks until the consumer drains — a wide-row
    # pipeline cannot OOM the store while CPU-idle (reference:
    # execution/resource_manager.py object-store budgets +
    # backpressure_policy/). None = unlimited.
    execution_object_store_byte_budget: Optional[int] = None
    shuffle_strategy: str = "push"
    # Streaming executor buffers (in blocks): per-operator edge buffer and
    # the consumer-facing output queue — both bound memory and carry the
    # backpressure signal upstream.
    op_output_buffer_blocks: int = 8
    streaming_output_buffer_blocks: int = 8

    _current = None
    _lock = threading.Lock()

    @classmethod
    def get_current(cls) -> "DataContext":
        with cls._lock:
            if cls._current is None:
                cls._current = DataContext()
            return cls._current
