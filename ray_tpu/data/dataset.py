"""Dataset: lazy, distributed, streaming data
(reference: python/ray/data/dataset.py + _internal/plan.py +
_internal/execution/streaming_executor.py).

A Dataset is a logical plan over blocks held in the shared-memory object
store. Transformations are lazy; consumption (iter_batches / take /
materialize / aggregates) triggers execution: map-like stages are fused and
run as one remote task per block with bounded in-flight windows
(backpressure); all-to-all stages (shuffle / sort / repartition / groupby)
materialize boundaries.

TPU-first notes: batches come out as dicts of numpy arrays ready for
device put; `streaming_split`/`shard` feed Train workers per-rank.
"""

from __future__ import annotations

import itertools
import random as _random
from typing import (Any, Callable, Dict, Iterable, Iterator, List, Optional,
                    Tuple, Union)

import numpy as np

from .block import Block, BlockAccessor
from .context import DataContext
from .logical import ALL_TO_ALL, MAP, LogicalOp, Optimizer

# Legacy stage shape ("map", block_fn[, opts]) / ("allToAll", plan_fn[,
# name]) still accepted by _with_stage; internally stages are LogicalOp
# nodes (see logical.py) so the optimizer rules can reason about them.
Stage = Tuple[str, Callable]


def _coerce_stage(stage) -> LogicalOp:
    if isinstance(stage, LogicalOp):
        return stage
    kind = stage[0]
    if kind == MAP:
        opts = stage[2] if len(stage) > 2 else {}
        return LogicalOp(MAP, stage[1], name="map", opts=opts or {})
    name = stage[2] if len(stage) > 2 else "exchange"
    return LogicalOp(ALL_TO_ALL, stage[1], name=name,
                     meta={"exchange": name})


class Dataset:
    def __init__(self, source_fn: Callable[[], List],
                 stages: Optional[List] = None,
                 name: str = "dataset", source=None):
        # source_fn: () -> list of ObjectRef[Block]; `source` optionally
        # carries a rule-rewritable datasource descriptor (read_api)
        self._source_fn = source_fn
        self._stages: List[LogicalOp] = \
            [_coerce_stage(s) for s in (stages or [])]
        self._name = name
        self._source = source
        self._materialized: Optional[List] = None

    # ------------------------------------------------------------------
    # transformations (lazy)
    # ------------------------------------------------------------------

    def _with_stage(self, stage, name: str) -> "Dataset":
        ds = Dataset(self._source_fn,
                     self._stages + [_coerce_stage(stage)],
                     name=f"{self._name}->{name}", source=self._source)
        ds._materialized = self._materialized
        return ds

    def map_batches(self, fn: Callable, *, batch_size: Optional[int] = None,
                    batch_format: str = "numpy",
                    fn_kwargs: Optional[Dict] = None,
                    compute: Optional[str] = None,
                    concurrency: Optional[int] = None) -> "Dataset":
        """compute="actors" runs this stage on a pool of `concurrency`
        actors (reference: ActorPoolMapOperator) instead of per-block
        tasks — for fns with expensive setup (models, tokenizers).
        `fn` may be a CLASS (reference: stateful map_batches UDFs):
        instantiated once per pool worker, then called per batch —
        requires compute="actors"."""
        fn_kwargs = fn_kwargs or {}
        if isinstance(fn, type):
            if compute != "actors":
                raise ValueError(
                    "map_batches with a class UDF requires "
                    "compute='actors' (one instance per pool worker)")
            holder: Dict[str, Any] = {}
            cls = fn

            def fn(batch, _holder=holder, **kw):  # noqa: F811
                inst = _holder.get("inst")
                if inst is None:
                    inst = cls()
                    _holder["inst"] = inst
                return inst(batch, **kw)

        def stage(block: Block) -> Block:
            acc = BlockAccessor(block)
            n = acc.num_rows()
            size = batch_size or max(n, 1)
            outs = []
            for start in range(0, max(n, 1), size):
                piece = BlockAccessor(acc.slice(start, min(start + size, n)))
                batch = piece.to_batch(batch_format)
                result = fn(batch, **fn_kwargs)
                outs.append(BlockAccessor.batch_to_block(result))
            if not outs:
                return block
            return BlockAccessor.concat(outs)

        opts = {"compute": compute, "concurrency": concurrency} \
            if compute or concurrency else {}
        return self._with_stage(
            LogicalOp(MAP, stage, name="map_batches", opts=opts),
            "map_batches")

    def map(self, fn: Callable) -> "Dataset":
        def stage(block: Block) -> Block:
            rows = [fn(r) for r in BlockAccessor(block).iter_rows()]
            return _rows_to_block(rows)
        return self._with_stage(
            LogicalOp(MAP, stage, name="map", preserves_rows=True), "map")

    def flat_map(self, fn: Callable) -> "Dataset":
        def stage(block: Block) -> Block:
            rows = [o for r in BlockAccessor(block).iter_rows()
                    for o in fn(r)]
            return _rows_to_block(rows)
        return self._with_stage(
            LogicalOp(MAP, stage, name="flat_map"), "flat_map")

    def filter(self, fn: Callable) -> "Dataset":
        def stage(block: Block) -> Block:
            rows = [r for r in BlockAccessor(block).iter_rows() if fn(r)]
            return _rows_to_block(rows)
        return self._with_stage(
            LogicalOp(MAP, stage, name="filter"), "filter")

    def add_column(self, name: str, fn: Callable) -> "Dataset":
        def add(batch):
            batch[name] = fn(batch)
            return batch

        def stage(block: Block) -> Block:
            acc = BlockAccessor(block)
            return BlockAccessor.batch_to_block(add(acc.to_batch("numpy")))
        return self._with_stage(
            LogicalOp(MAP, stage, name=f"add_column[{name}]",
                      preserves_rows=True), "add_column")

    def drop_columns(self, cols: List[str]) -> "Dataset":
        def stage(block: Block) -> Block:
            batch = BlockAccessor(block).to_batch("numpy")
            return BlockAccessor.batch_to_block(
                {k: v for k, v in batch.items() if k not in cols})
        return self._with_stage(
            LogicalOp(MAP, stage, name=f"drop_columns{cols}",
                      preserves_rows=True), "drop_columns")

    def select_columns(self, cols: List[str]) -> "Dataset":
        def stage(block: Block) -> Block:
            batch = BlockAccessor(block).to_batch("numpy")
            return BlockAccessor.batch_to_block(
                {k: batch[k] for k in cols})
        return self._with_stage(
            LogicalOp(MAP, stage, name=f"select_columns{cols}",
                      preserves_rows=True, meta={"columns": list(cols)}),
            "select_columns")

    def limit(self, n: int) -> "Dataset":
        def plan_fn(block_refs: List) -> List:
            import ray_tpu
            taken, out = 0, []
            for ref in block_refs:
                if taken >= n:
                    break
                block = ray_tpu.get(ref)
                rows = BlockAccessor(block).num_rows()
                if taken + rows <= n:
                    out.append(ref)
                    taken += rows
                else:
                    sliced = BlockAccessor(block).slice(0, n - taken)
                    out.append(ray_tpu.put(sliced))
                    taken = n
            return out
        return self._with_stage(
            LogicalOp(ALL_TO_ALL, plan_fn, name=f"limit[{n}]",
                      meta={"limit": n}), f"limit[{n}]")

    def repartition(self, num_blocks: int) -> "Dataset":
        from .exchange import repartition_exchange

        def plan_fn(block_refs: List) -> List:
            return repartition_exchange(block_refs, num_blocks)
        return self._with_stage(("allToAll", plan_fn, "repartition"),
                                f"repartition[{num_blocks}]")

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        from .exchange import shuffle_exchange

        def plan_fn(block_refs: List) -> List:
            return shuffle_exchange(block_refs, seed)
        return self._with_stage(("allToAll", plan_fn, "shuffle"),
                                "random_shuffle")

    def sort(self, key: Union[str, Callable], descending: bool = False
             ) -> "Dataset":
        from .exchange import sort_exchange

        def plan_fn(block_refs: List) -> List:
            return sort_exchange(block_refs, key, descending)
        return self._with_stage(("allToAll", plan_fn, "sort"), "sort")

    def union(self, *others: "Dataset") -> "Dataset":
        parents = [self, *others]

        def source():
            refs = []
            for parent in parents:
                refs.extend(parent._execute())
            return refs
        return Dataset(source, [], name="union")

    def zip(self, other: "Dataset") -> "Dataset":
        left, right = self, other

        def source():
            import ray_tpu
            l_rows = left.take_all()
            r_rows = right.take_all()
            rows = []
            for a, b in zip(l_rows, r_rows):
                da, db = _as_dict(a), _as_dict(b)
                merged = dict(da)
                for key, value in db.items():
                    # Suffix only on conflict (reference zip semantics).
                    merged[key if key not in merged else f"{key}_1"] = value
                rows.append(merged)
            return [ray_tpu.put(_rows_to_block(rows))]
        return Dataset(source, [], name="zip")

    def groupby(self, key: str) -> "GroupedData":
        from .grouped import GroupedData
        return GroupedData(self, key)

    def join(self, other: "Dataset", on: str, *, how: str = "inner",
             num_partitions: Optional[int] = None,
             right_suffix: str = "_right") -> "Dataset":
        """Distributed hash join (reference: Dataset.join backed by
        execution/operators/hash_shuffle.py:392). `how` is one of
        inner/left/right/outer; overlapping non-key columns from `other`
        get `right_suffix`."""
        if how not in ("inner", "left", "right", "outer"):
            raise ValueError(f"unsupported join type {how!r}")
        from .exchange import hash_join_exchange
        left, right = self, other

        def source():
            return hash_join_exchange(
                left._execute(), right._execute(), on, how=how,
                num_partitions=num_partitions,
                right_suffix=right_suffix)
        return Dataset(source, [], name=f"join[{how}]")

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------

    def _optimized(self):
        """Run the rule-based optimizer over the logical plan."""
        return Optimizer().optimize(list(self._stages), self._source)

    def explain(self) -> List[str]:
        """Names of the physical stages after optimization (tests assert
        rule effects — fusion by stage count, pushdowns by order)."""
        ops, source = self._optimized()
        out = [f"source[{getattr(source, 'describe', lambda: 'fn')()}]"
               if source is not None else "source[fn]"]
        out.extend(f"{op.kind}:{op.name}" for op in ops)
        return out

    def _make_executor(self):
        """Lower the optimized logical plan into a streaming topology."""
        from .streaming import StreamingExecutor, build_ops
        logical_ops, source = self._optimized()
        source_fn = source.fn if source is not None else self._source_fn
        ops = build_ops(logical_ops)
        return StreamingExecutor(source_fn, ops, name=self._name)

    def iter_block_refs(self) -> Iterator:
        """Stream block refs as the plan produces them (backpressured);
        training can consume while upstream stages still run."""
        if self._materialized is not None and not self._stages:
            yield from self._materialized
            return
        executor = self._make_executor().run_async()
        try:
            yield from executor.iter_output()
        finally:
            executor.stop()

    def _execute(self) -> List:
        """Run the plan to completion; returns all block refs."""
        if self._materialized is not None and not self._stages:
            return self._materialized
        return list(self.iter_block_refs())

    def materialize(self) -> "Dataset":
        refs = self._execute()
        ds = Dataset(lambda: refs, [], name=self._name)
        ds._materialized = refs
        return ds

    def num_blocks(self) -> int:
        return len(self._execute())

    def count(self) -> int:
        import ray_tpu
        refs = self._execute()
        counts = _run_map_tasks(
            refs, [lambda b: [BlockAccessor(b).num_rows()]])
        return sum(BlockAccessor(ray_tpu.get(r)).to_pylist()[0]
                   for r in counts)

    def schema(self):
        import ray_tpu
        refs = self._execute()
        if not refs:
            return None
        return BlockAccessor(ray_tpu.get(refs[0])).schema()

    def take(self, n: int = 20) -> List[Any]:
        import ray_tpu
        out: List[Any] = []
        for ref in self.iter_block_refs():  # stops the stream early
            for row in BlockAccessor(ray_tpu.get(ref)).iter_rows():
                out.append(row)
                if len(out) >= n:
                    return out
        return out

    def take_all(self) -> List[Any]:
        import ray_tpu
        out: List[Any] = []
        for ref in self.iter_block_refs():
            out.extend(BlockAccessor(ray_tpu.get(ref)).iter_rows())
        return out

    def show(self, n: int = 20):
        for row in self.take(n):
            print(row)

    def to_pandas(self):
        import pandas as pd
        import ray_tpu
        frames = [BlockAccessor(ray_tpu.get(r)).to_pandas()
                  for r in self._execute()]
        return pd.concat(frames, ignore_index=True) if frames \
            else pd.DataFrame()

    # -- aggregates ------------------------------------------------------

    def sum(self, on: Optional[str] = None):
        return self._simple_agg(np.sum, on)

    def min(self, on: Optional[str] = None):
        return self._simple_agg(np.min, on)

    def max(self, on: Optional[str] = None):
        return self._simple_agg(np.max, on)

    def mean(self, on: Optional[str] = None):
        rows = self._column_values(on)
        return float(np.mean(rows)) if len(rows) else None

    def std(self, on: Optional[str] = None):
        rows = self._column_values(on)
        return float(np.std(rows, ddof=1)) if len(rows) > 1 else None

    def _column_values(self, on: Optional[str]) -> np.ndarray:
        rows = self.take_all()
        if not rows:
            return np.asarray([])
        if isinstance(rows[0], dict):
            if on is None:
                raise ValueError("specify on= for record datasets")
            return np.asarray([r[on] for r in rows])
        return np.asarray(rows)

    def _simple_agg(self, fn, on):
        values = self._column_values(on)
        if not len(values):
            return None
        result = fn(values)
        return result.item() if hasattr(result, "item") else result

    # -- iteration / train integration ----------------------------------

    def iter_rows(self) -> Iterator[Any]:
        import ray_tpu
        for ref in self.iter_block_refs():
            yield from BlockAccessor(ray_tpu.get(ref)).iter_rows()

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     drop_last: bool = False) -> Iterator[Any]:
        import ray_tpu

        def blocks():
            for ref in self.iter_block_refs():
                yield ray_tpu.get(ref)
        yield from _batches_from_blocks(blocks(), batch_size, batch_format,
                                        drop_last)

    def take_batch(self, batch_size: int = 20,
                   *, batch_format: str = "numpy"):
        """First `batch_size` rows as one batch (reference:
        Dataset.take_batch)."""
        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format=batch_format):
            return batch
        raise ValueError("dataset is empty")

    def iter_torch_batches(self, *, batch_size: int = 256,
                           dtypes=None,
                           drop_last: bool = False) -> Iterator[Any]:
        """numpy batches converted to torch tensors (reference:
        Dataset.iter_torch_batches / iterator.py torch conversion);
        dict batches convert per-column, `dtypes` optionally maps
        column -> torch dtype (or one dtype for all)."""
        import torch

        def to_tensor(arr, key=None):
            t = torch.as_tensor(arr)
            if dtypes is None:
                return t
            want = dtypes.get(key) if isinstance(dtypes, dict) else dtypes
            return t.to(want) if want is not None else t

        for batch in self.iter_batches(batch_size=batch_size,
                                       batch_format="numpy",
                                       drop_last=drop_last):
            if isinstance(batch, dict):
                yield {k: to_tensor(v, k) for k, v in batch.items()}
            else:
                yield to_tensor(batch)

    def split_at_indices(self, indices: List[int]) -> List["Dataset"]:
        """Split by global row indices into len(indices)+1 datasets
        (reference: Dataset.split_at_indices). Materializes rows once;
        splits are in-memory datasets."""
        if any(b < a for a, b in zip(indices, indices[1:])):
            raise ValueError("indices must be sorted")
        if indices and indices[0] < 0:
            raise ValueError("indices must be non-negative")
        rows = self.take_all()
        bounds = [0] + list(indices) + [len(rows)]
        out = []
        for i, (lo, hi) in enumerate(zip(bounds, bounds[1:])):
            part = rows[lo:max(lo, hi)]
            ds = from_items_rows(part, name=f"{self._name}-splitidx{i}")
            out.append(ds)
        return out

    def split(self, n: int, *, locality_hints=None) -> List["Dataset"]:
        refs = self.repartition(n)._execute()
        out = []
        per = max(1, -(-len(refs) // n))
        for i in range(n):
            chunk = refs[i * per:(i + 1) * per]
            ds = Dataset(lambda c=chunk: c, [], name=f"{self._name}-split{i}")
            ds._materialized = chunk
            out.append(ds)
        return out

    def shard(self, rank: int, world_size: int) -> "Dataset":
        """Per-rank shard for Train workers (row-round-robin by block)."""
        refs = self._execute()
        mine = refs[rank::world_size]
        ds = Dataset(lambda: mine, [], name=f"{self._name}-shard{rank}")
        ds._materialized = mine
        return ds

    def streaming_split(self, n: int, *, equal: bool = True,
                        locality_hints=None) -> List["DataIterator"]:
        """One iterator per consumer, fed by a coordinator actor that
        streams this dataset's output round-robin to the consumers WHILE
        upstream stages still run (reference: Dataset.streaming_split →
        stream_split_iterator.py:36 + the SplitCoordinator actor)."""
        import ray_tpu
        from .iterator import StreamSplitIterator
        coordinator_cls = ray_tpu.remote(_SplitCoordinator)
        coordinator = coordinator_cls.options(
            max_concurrency=n + 2).remote(self, n)
        return [StreamSplitIterator(coordinator, i) for i in range(n)]

    def iterator(self) -> "DataIterator":
        from .iterator import DataIterator
        return DataIterator(self)

    # -- writes ----------------------------------------------------------

    def write_parquet(self, path: str):
        import os
        import pyarrow.parquet as pq
        import pyarrow as pa
        import ray_tpu
        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            block = ray_tpu.get(ref)
            table = block if isinstance(block, pa.Table) else \
                pa.table(BlockAccessor(block).to_numpy_batch())
            pq.write_table(table, os.path.join(path, f"part-{i:05d}.parquet"))

    def write_csv(self, path: str):
        import os
        import pyarrow.csv as pacsv
        import pyarrow as pa
        import ray_tpu
        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            block = ray_tpu.get(ref)
            table = block if isinstance(block, pa.Table) else \
                pa.table(BlockAccessor(block).to_numpy_batch())
            pacsv.write_csv(table, os.path.join(path, f"part-{i:05d}.csv"))

    def write_tfrecords(self, path: str):
        """tf.train.Example TFRecord shards, one file per block
        (reference: Dataset.write_tfrecords — encoded without a
        tensorflow dependency; see read_api's Example codec)."""
        import os

        import ray_tpu
        from .read_api import _row_to_example, _tfrecord_write
        os.makedirs(path, exist_ok=True)
        for i, ref in enumerate(self._execute()):
            block = ray_tpu.get(ref)
            rows = BlockAccessor(block).to_pylist()
            _tfrecord_write(
                os.path.join(path, f"part-{i:05d}.tfrecords"),
                (_row_to_example(_jsonable(r)) for r in rows))

    def write_json(self, path: str):
        import json as _json
        import os
        os.makedirs(path, exist_ok=True)
        for i, batch in enumerate([self.take_all()]):
            with open(os.path.join(path, f"part-{i:05d}.json"), "w") as f:
                for row in batch:
                    f.write(_json.dumps(_jsonable(row)) + "\n")

    def __repr__(self):
        return f"Dataset(name={self._name}, stages={len(self._stages)})"


def from_items_rows(rows: List[Any], name: str = "from_rows") -> "Dataset":
    """In-memory Dataset over already-materialized rows (one block)."""
    import ray_tpu
    ref = ray_tpu.put(_rows_to_block(list(rows)))
    ds = Dataset(lambda: [ref], [], name=name)
    ds._materialized = [ref]
    return ds


def _rows_to_block(rows: List[Any]) -> Block:
    return BlockAccessor.from_rows(rows)


def _batches_from_blocks(blocks: Iterable[Block], batch_size: int,
                         batch_format: str, drop_last: bool
                         ) -> Iterator[Any]:
    """Re-batch a stream of blocks into fixed-size batches."""
    carry: Optional[Block] = None
    for block in blocks:
        if carry is not None:
            block = BlockAccessor.concat([carry, block])
            carry = None
        acc = BlockAccessor(block)
        n = acc.num_rows()
        start = 0
        while n - start >= batch_size:
            piece = BlockAccessor(acc.slice(start, start + batch_size))
            yield piece.to_batch(batch_format)
            start += batch_size
        if start < n:
            carry = acc.slice(start, n)
    if carry is not None and not drop_last:
        yield BlockAccessor(carry).to_batch(batch_format)


class _SplitCoordinator:
    """Actor distributing one streaming execution across n consumers.

    Runs the StreamingExecutor in its own process; consumers call
    `get_next(idx)` (a blocking actor method — the actor runs with
    max_concurrency > n so every split can park a thread). Blocks are
    handed out round-robin; queue bounds backpressure the stream when a
    consumer lags."""

    def __init__(self, dataset: "Dataset", n: int):
        import queue as _queue
        import threading as _threading
        self._n = n
        self._error: Optional[str] = None
        self._done = False
        self._queues = [_queue.Queue(maxsize=4) for _ in range(n)]
        self._executor = dataset._make_executor().run_async()
        self._thread = _threading.Thread(target=self._pump, daemon=True)
        # Tracked but not joined: the pump parks on bounded queues and
        # exits with the process; there is no cheap stop signal that
        # does not also break lagging consumers.
        from .._internal.threads import register_daemon_thread
        register_daemon_thread(self._thread, joinable=False)
        self._thread.start()

    # A consumer that stops pulling wedges the round-robin pump on its full
    # queue (that's the intended backpressure for LAGGING consumers, but an
    # ABANDONED one would deadlock every split). After this stall the whole
    # stream fails loudly instead (reference semantics: all splits must be
    # consumed together).
    ABANDONED_CONSUMER_TIMEOUT_S = 120.0

    def _pump(self):
        import queue as _queue
        try:
            for i, ref in enumerate(self._executor.iter_output()):
                q = self._queues[i % self._n]
                waited = 0.0
                while True:
                    try:
                        q.put(ref, timeout=1.0)
                        break
                    except _queue.Full:
                        waited += 1.0
                        if waited >= self.ABANDONED_CONSUMER_TIMEOUT_S:
                            raise RuntimeError(
                                f"streaming split consumer {i % self._n} "
                                f"stopped consuming for {waited:.0f}s — "
                                "all splits must be consumed concurrently")
        except BaseException as e:  # noqa: BLE001 — forwarded to consumers
            self._error = repr(e)
        finally:
            # End-of-stream is a flag, not a sentinel put: a put on a full
            # queue of an abandoned/lagging consumer would block (or leak a
            # thread) and could delay EOS to the other splits.
            self._done = True

    def get_next(self, idx: int):
        """Next block ref for consumer idx, or None at end of stream."""
        import queue as _queue
        while True:
            try:
                return self._queues[idx].get(timeout=0.25)
            except _queue.Empty:
                if self._done:
                    # The pump may have enqueued a final block between our
                    # timeout and the flag check — drain before declaring
                    # end of stream.
                    try:
                        return self._queues[idx].get_nowait()
                    except _queue.Empty:
                        return None

    def get_error(self) -> Optional[str]:
        return self._error


def _as_dict(row, suffix=""):
    if isinstance(row, dict):
        return row if not suffix else {f"{k}{suffix}": v
                                       for k, v in row.items()}
    return {f"item{suffix}": row}


def _jsonable(row):
    if isinstance(row, dict):
        return {k: _jsonable(v) for k, v in row.items()}
    if isinstance(row, np.ndarray):
        return row.tolist()
    if isinstance(row, (np.integer, np.floating)):
        return row.item()
    return row


def _run_map_tasks(refs: List, fns: List[Callable]) -> List:
    """Run fused block transforms as remote tasks with a bounded window."""
    import ray_tpu

    ctx = DataContext.get_current()

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def _apply(block, fns=fns):
        for fn in fns:
            block = fn(block)
        return block

    window = max(1, ctx.max_tasks_in_flight)
    out: List = []
    pending: List = []
    for ref in refs:
        if len(pending) >= window:
            # Backpressure: block until the oldest in-flight task lands
            # before submitting the next one.
            oldest = pending.pop(0)
            ray_tpu.wait([oldest], num_returns=1)
            out.append(oldest)
        pending.append(_apply.remote(ref))
    out.extend(pending)
    return out
