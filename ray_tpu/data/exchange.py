"""Distributed all-to-all exchanges: repartition / shuffle / sort / groupby.

Role of the reference's exchange task schedulers
(python/ray/data/_internal/planner/exchange/push_based_shuffle_task_scheduler.py:460,
sort_task_spec.py:94): a map phase splits every input block into one part
per output partition (tasks, num_returns=N), and a reduce phase merges the
j-th part of every input (one task per output partition). The driver only
routes refs — block payloads never pass through it.
"""

from __future__ import annotations

import random as _random
from typing import Callable, List, Optional, Tuple, Union

from .block import BlockAccessor


def _concat_remote():
    import ray_tpu

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def merge_parts(*blocks):
        return BlockAccessor.concat(list(blocks))

    return merge_parts


def _hash_partition_remote(n_out: int, key: str):
    """Remote fn splitting a block into n_out buckets by _stable_hash of
    row[key] — the map phase every hash exchange shares."""
    import ray_tpu

    @ray_tpu.remote(num_cpus=1, max_retries=2, num_returns=n_out)
    def hash_partition(block):
        acc = BlockAccessor(block)
        buckets: List[List] = [[] for _ in range(n_out)]
        for row in acc.iter_rows():
            buckets[_stable_hash(row[key]) % n_out].append(row)
        parts = tuple(BlockAccessor.from_rows(b) for b in buckets)
        return parts if n_out > 1 else parts[0]

    return hash_partition


def _split_remote(n_out: int):
    import ray_tpu

    @ray_tpu.remote(num_cpus=1, max_retries=2, num_returns=n_out)
    def split_block(block):
        acc = BlockAccessor(block)
        n = acc.num_rows()
        per = -(-n // n_out) if n else 0
        parts = tuple(acc.slice(min(i * per, n), min((i + 1) * per, n))
                      for i in range(n_out))
        return parts if n_out > 1 else parts[0]

    return split_block


def repartition_exchange(refs: List, n_out: int) -> List:
    """Contiguous rebalance into n_out blocks; fully distributed."""
    import ray_tpu
    if not refs:
        return [ray_tpu.put(BlockAccessor.empty()) for _ in range(n_out)]

    split_block = _split_remote(n_out)
    merge = _concat_remote()

    parts = [split_block.remote(r) for r in refs]
    if n_out == 1:
        return [merge.remote(*parts)]
    return [merge.remote(*[parts[i][j] for i in range(len(refs))])
            for j in range(n_out)]


def shuffle_exchange(refs: List, seed: Optional[int]) -> List:
    """Random shuffle: per-block shuffle + round-robin scatter, then
    per-partition merge + local shuffle."""
    import ray_tpu
    if not refs:
        return refs
    n_out = len(refs)

    @ray_tpu.remote(num_cpus=1, max_retries=2, num_returns=n_out)
    def scatter(block, block_seed):
        acc = BlockAccessor(block)
        rows = list(acc.iter_rows())
        rng = _random.Random(block_seed)
        rng.shuffle(rows)
        parts = tuple(BlockAccessor.from_rows(rows[j::n_out])
                      for j in range(n_out))
        return parts if n_out > 1 else parts[0]

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def gather(part_seed, *blocks):
        rows = [r for b in blocks for r in BlockAccessor(b).iter_rows()]
        rng = _random.Random(part_seed)
        rng.shuffle(rows)
        return BlockAccessor.from_rows(rows)

    merge_parts = _concat_remote()
    base = seed if seed is not None else _random.randrange(1 << 30)
    parts = [scatter.remote(r, base + i) for i, r in enumerate(refs)]
    if n_out == 1:
        return [gather.remote(base + 7, *parts)]
    factor = _merge_factor()
    if factor and len(refs) > factor:
        merged = push_merge_rounds(parts, n_out, merge_parts, factor)
        return [gather.remote(base + 7 + j, *merged[j])
                for j in range(n_out)]
    return [gather.remote(base + 7 + j,
                          *[parts[i][j] for i in range(len(refs))])
            for j in range(n_out)]


def _merge_factor() -> int:
    from .context import DataContext
    ctx = DataContext.get_current()
    if ctx.shuffle_strategy != "push":
        return 0
    return max(2, ctx.push_shuffle_merge_factor)


def push_merge_rounds(parts: List, n_out: int, merge_remote,
                      merge_factor: int) -> List[List]:
    """The push-based shuffle scheduler (reference:
    data/_internal/planner/exchange/push_based_shuffle_task_scheduler.py:460).

    `parts[i][j]` is input i's slice for output partition j. Rather than
    handing every reduce task all M inputs at once (M x N refs in flight,
    reduce fan-in M), inputs are consumed in rounds of `merge_factor`:
    as soon as a round's map tasks finish, one merge task per partition
    folds that round's slices into a single partial — merges of round k
    overlap the maps of round k+1, and the final reduce sees only
    ceil(M / merge_factor) partials. Merges CONCATENATE IN INPUT ORDER,
    so downstream reduces observe the same row sequence as the one-shot
    plan — push vs pull is a scheduling choice, not a semantics change.

    Returns per-partition lists of partial refs (each list has
    ceil(M / merge_factor) entries)."""
    merged: List[List] = [[] for _ in range(n_out)]
    for start in range(0, len(parts), merge_factor):
        chunk = parts[start:start + merge_factor]
        for j in range(n_out):
            inputs = [p[j] for p in chunk]
            if len(inputs) == 1:
                merged[j].append(inputs[0])
            else:
                merged[j].append(merge_remote.remote(*inputs))
    return merged


def sort_exchange(refs: List, key: Union[str, Callable],
                  descending: bool) -> List:
    """Sample-partition-merge distributed sort (reference:
    sort_task_spec.py:94 SortTaskSpec boundary sampling)."""
    import ray_tpu
    if not refs:
        return refs
    n_out = len(refs)
    key_fn = key if callable(key) else (lambda r: r[key])

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def sample(block):
        acc = BlockAccessor(block)
        rows = list(acc.iter_rows())
        if not rows:
            return []
        step = max(1, len(rows) // 8)
        return sorted(key_fn(r) for r in rows[::step])

    if n_out == 1:
        @ray_tpu.remote(num_cpus=1, max_retries=2)
        def merge_all(*blocks):
            merged = BlockAccessor.concat(list(blocks))
            return BlockAccessor(merged).sort_by(key, descending)
        return [merge_all.remote(*refs)]

    samples = sorted(s for part in ray_tpu.get([sample.remote(r)
                                                for r in refs])
                     for s in part)
    if not samples:
        return refs
    # n_out-1 boundaries at even sample quantiles.
    boundaries = [samples[(i * len(samples)) // n_out]
                  for i in range(1, n_out)]

    @ray_tpu.remote(num_cpus=1, max_retries=2, num_returns=n_out)
    def partition(block):
        import bisect
        acc = BlockAccessor(block)
        buckets: List[List] = [[] for _ in range(n_out)]
        for row in acc.iter_rows():
            buckets[bisect.bisect_right(boundaries, key_fn(row))].append(row)
        return tuple(BlockAccessor.from_rows(b) for b in buckets)

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def merge_sorted(*blocks):
        merged = BlockAccessor.concat(list(blocks))
        return BlockAccessor(merged).sort_by(key, descending)

    parts = [partition.remote(r) for r in refs]
    factor = _merge_factor()
    if factor and len(refs) > factor:
        # Partial merge-sorts are themselves sorted runs; the final
        # merge_sorted over them equals the one-shot sort (stable sort +
        # in-order concat => identical row order).
        merged = push_merge_rounds(parts, n_out, merge_sorted, factor)
        out = [merge_sorted.remote(*merged[j]) for j in range(n_out)]
    else:
        out = [merge_sorted.remote(*[parts[i][j]
                                     for i in range(len(refs))])
               for j in range(n_out)]
    return list(reversed(out)) if descending else out


def groupby_exchange(refs: List, key: str, agg_fn: Callable,
                     agg_name: str, value_col: Optional[str]) -> List:
    """Hash-partition by key, then per-partition group + aggregate
    (reference: execution/operators/hash_shuffle.py hash aggregate) —
    the single-aggregation special case of map_groups_exchange."""

    def agg_group(rows):
        values = [r[value_col] for r in rows] if value_col else rows
        return {key: rows[0][key], agg_name: agg_fn(values)}

    return map_groups_exchange(refs, key, agg_group)


def hash_join_exchange(left_refs: List, right_refs: List, on: str,
                       how: str = "inner",
                       num_partitions: Optional[int] = None,
                       right_suffix: str = "_right") -> List:
    """Distributed hash join (reference:
    data/_internal/execution/operators/hash_shuffle.py:392,1034 — the
    partition-actor hash join/aggregate family; here the same two-phase
    plan as the other exchanges: hash-partition both sides by key, then
    one build+probe task per partition). Supports inner/left/right/outer.
    """
    import ray_tpu
    if num_partitions is None:
        num_partitions = max(1, min(max(len(left_refs), len(right_refs)),
                                    8))
    n_out = num_partitions
    hash_partition = _hash_partition_remote(n_out, on)

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def join_partition(n_left, *blocks):
        left_rows = [r for b in blocks[:n_left]
                     for r in BlockAccessor(b).iter_rows()]
        right_rows = [r for b in blocks[n_left:]
                      for r in BlockAccessor(b).iter_rows()]
        # Column sets up front: unmatched rows must carry the OTHER
        # side's columns explicitly as None — block construction takes
        # the first row's keys, and ragged rows would silently drop the
        # missing columns (pandas-merge NaN semantics).
        left_cols = list(dict.fromkeys(
            k for r in left_rows for k in r))
        right_cols_raw = list(dict.fromkeys(
            k for r in right_rows for k in r if k != on))
        right_out = {k: (k if k not in left_cols
                         else f"{k}{right_suffix}")
                     for k in right_cols_raw}
        # build on the smaller side, probe with the larger
        build: dict = {}
        for row in right_rows:
            build.setdefault(row[on], []).append(row)
        out = []
        matched_right = set()
        for row in left_rows:
            hits = build.get(row[on])
            if hits:
                matched_right.add(row[on])
                for other in hits:
                    merged = dict(row)
                    for k, v in other.items():
                        if k != on:
                            merged[right_out[k]] = v
                    out.append(merged)
            elif how in ("left", "outer"):
                merged = dict(row)
                for k in right_out.values():
                    merged[k] = None
                out.append(merged)
        if how in ("right", "outer"):
            for row in right_rows:
                if row[on] not in matched_right:
                    merged = {c: None for c in left_cols}
                    merged[on] = row[on]
                    for k, v in row.items():
                        if k != on:
                            merged[right_out[k]] = v
                    out.append(merged)
        out.sort(key=lambda r: _sort_token(r[on]))
        return BlockAccessor.from_rows(out)

    merge_parts = _concat_remote()
    lparts = [hash_partition.remote(r) for r in left_refs]
    rparts = [hash_partition.remote(r) for r in right_refs]
    if n_out == 1:
        return [join_partition.remote(len(lparts), *lparts, *rparts)]
    factor = _merge_factor()
    if factor and max(len(lparts), len(rparts)) > factor:
        lm = push_merge_rounds(lparts, n_out, merge_parts, factor)
        rm = push_merge_rounds(rparts, n_out, merge_parts, factor)
        return [join_partition.remote(len(lm[j]), *lm[j], *rm[j])
                for j in range(n_out)]
    return [join_partition.remote(
        len(lparts),
        *[lparts[i][j] for i in range(len(left_refs))],
        *[rparts[i][j] for i in range(len(right_refs))])
        for j in range(n_out)]


#: (partial_fn, merge_fn, finalize_fn) per aggregation kind — the
#: decomposition that makes per-block PARTIAL aggregation possible (the
#: hash-aggregate structural win over gather-then-aggregate: only
#: (key, partial-state) pairs cross the wire, reference:
#: hash_shuffle.py:1034 hash aggregate).
_AGG_KINDS = {
    "count": (lambda vs: len(vs), sum, lambda s: s),
    "sum": (lambda vs: float(sum(vs)), sum, lambda s: s),
    "min": (min, min, lambda s: s),
    "max": (max, max, lambda s: s),
    "mean": (lambda vs: (float(sum(vs)), len(vs)),
             lambda ss: (sum(a for a, _ in ss), sum(b for _, b in ss)),
             lambda s: s[0] / s[1] if s[1] else None),
}


def hash_aggregate_exchange(refs: List, key: str,
                            aggs: List[Tuple[str, Optional[str]]]) -> List:
    """Multi-aggregation hash aggregate: per-block partial aggregation,
    hash-partition of the (key, partials) rows, per-partition merge +
    finalize. `aggs` = [(kind, column-or-None), ...]."""
    import ray_tpu
    if not refs:
        return refs
    n_out = min(len(refs), 8)
    specs = [(kind, col, f"{kind}({col})" if col else f"{kind}()")
             for kind, col in aggs]

    @ray_tpu.remote(num_cpus=1, max_retries=2, num_returns=n_out)
    def partial_agg(block):
        acc = BlockAccessor(block)
        groups: dict = {}
        for row in acc.iter_rows():
            groups.setdefault(row[key], []).append(row)
        partial_rows: List[List] = [[] for _ in range(n_out)]
        for k, rows in groups.items():
            partials = {}
            for kind, col, out_name in specs:
                partial_fn = _AGG_KINDS[kind][0]
                values = [r[col] for r in rows] if col else rows
                partials[out_name] = partial_fn(values)
            partial_rows[_stable_hash(k) % n_out].append(
                {key: k, "__partials__": partials})
        parts = tuple(BlockAccessor.from_rows(b) for b in partial_rows)
        return parts if n_out > 1 else parts[0]

    def _fold_partials(blocks, do_finalize: bool):
        """Group (key, __partials__) rows and fold each key's partial
        states with the kind's associative merge_fn; finalize only at
        the LAST level (intermediate push-merge rounds keep folding)."""
        merged: dict = {}
        for block in blocks:
            for row in BlockAccessor(block).iter_rows():
                merged.setdefault(row[key], []).append(row["__partials__"])
        out = []
        for k in sorted(merged, key=_sort_token):
            plist = merged[k]
            folded = {}
            for kind, _col, out_name in specs:
                _, merge_fn, finalize = _AGG_KINDS[kind]
                state = merge_fn([p[out_name] for p in plist])
                folded[out_name] = finalize(state) if do_finalize \
                    else state
            if do_finalize:
                out.append({key: k, **folded})
            else:
                out.append({key: k, "__partials__": folded})
        return BlockAccessor.from_rows(out)

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def merge_finalize(*blocks):
        return _fold_partials(blocks, do_finalize=True)

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def merge_partials(*blocks):
        # Intermediate push-merge round: fold WITHOUT finalizing —
        # merge_fn associativity makes merge-of-merges == one-shot merge.
        return _fold_partials(blocks, do_finalize=False)

    parts = [partial_agg.remote(r) for r in refs]
    if n_out == 1:
        return [merge_finalize.remote(*parts)]
    factor = _merge_factor()
    if factor and len(refs) > factor:
        merged = push_merge_rounds(parts, n_out, merge_partials, factor)
        return [merge_finalize.remote(*merged[j]) for j in range(n_out)]
    return [merge_finalize.remote(*[parts[i][j]
                                    for i in range(len(refs))])
            for j in range(n_out)]


def _stable_hash(value) -> int:
    """Deterministic across processes. Only str/bytes builtin hashes are
    per-process randomized; numeric hashes are stable AND equal across
    numerically-equal types (hash(2) == hash(2.0) == hash(np.int64(2))),
    which partitioning must preserve — arrow blocks yield Python ints
    where list blocks may hold numpy scalars for the same key."""
    import zlib
    if isinstance(value, str):
        return zlib.crc32(value.encode())
    if isinstance(value, bytes):
        return zlib.crc32(value)
    if isinstance(value, tuple):
        h = 0
        for item in value:
            h = zlib.crc32(_stable_hash(item).to_bytes(4, "big"), h)
        return h
    import numbers
    if isinstance(value, numbers.Number):
        # Builtin numeric hashing is process-stable AND equates
        # numerically-equal types; anything else hashable may transitively
        # hash strings (frozensets, dataclasses) and inherit the
        # per-process randomization.
        return hash(value) & 0x7FFFFFFF
    return zlib.crc32(repr(value).encode())


def _sort_token(value):
    """Total order over heterogeneous group keys: homogeneous primitives
    sort natively within their type class; everything else by repr."""
    if isinstance(value, bool):
        return (0, "bool", value)
    if isinstance(value, (int, float)):
        return (0, "num", value)
    if isinstance(value, str):
        return (1, "str", value)
    return (2, type(value).__name__, repr(value))


def map_groups_exchange(refs: List, key: str, fn: Callable) -> List:
    """Distributed map_groups (reference: grouped_data.py map_groups —
    one task per hash partition applies `fn(rows)` to each complete
    group): hash-partition by key, then per-partition group + apply.
    Same two-phase plan as the other exchanges; push-merge rounds bound
    reduce fan-in for many input blocks."""
    import ray_tpu
    if not refs:
        return refs
    n_out = min(len(refs), 8)

    hash_partition = _hash_partition_remote(n_out, key)

    @ray_tpu.remote(num_cpus=1, max_retries=2)
    def apply_groups(*blocks):
        groups: dict = {}
        for block in blocks:
            for row in BlockAccessor(block).iter_rows():
                groups.setdefault(row[key], []).append(row)
        out_rows: List = []
        for k in sorted(groups, key=_sort_token):
            result = fn(groups[k])
            out_rows.extend(result if isinstance(result, list)
                            else [result])
        return BlockAccessor.from_rows(out_rows)

    parts = [hash_partition.remote(r) for r in refs]
    if n_out == 1:
        return [apply_groups.remote(*parts)]
    merge_parts = _concat_remote()
    factor = _merge_factor()
    if factor and len(refs) > factor:
        merged = push_merge_rounds(parts, n_out, merge_parts, factor)
        return [apply_groups.remote(*merged[j]) for j in range(n_out)]
    return [apply_groups.remote(*[parts[i][j] for i in range(len(refs))])
            for j in range(n_out)]
