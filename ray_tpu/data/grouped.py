"""Grouped aggregations (reference: python/ray/data/grouped_data.py)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from .block import BlockAccessor


class GroupedData:
    def __init__(self, dataset, key: str):
        self._dataset = dataset
        self._key = key

    def _groups(self) -> Dict[Any, List[Any]]:
        groups: Dict[Any, List[Any]] = {}
        for row in self._dataset.take_all():
            groups.setdefault(row[self._key], []).append(row)
        return groups

    def _agg(self, fn: Callable, on: str, name: str):
        from .dataset import Dataset, _rows_to_block
        key = self._key
        groups = self._groups()
        rows = [{key: k, name: fn([r[on] for r in rs])}
                for k, rs in sorted(groups.items(), key=lambda kv: str(kv[0]))]

        def source():
            import ray_tpu
            return [ray_tpu.put(_rows_to_block(rows))]
        return Dataset(source, [], name=f"groupby({key}).{name}")

    def count(self):
        from .dataset import Dataset, _rows_to_block
        key = self._key
        rows = [{key: k, "count()": len(rs)}
                for k, rs in sorted(self._groups().items(),
                                    key=lambda kv: str(kv[0]))]

        def source():
            import ray_tpu
            return [ray_tpu.put(_rows_to_block(rows))]
        return Dataset(source, [], name=f"groupby({key}).count")

    def sum(self, on: str):
        return self._agg(lambda v: float(np.sum(v)), on, f"sum({on})")

    def mean(self, on: str):
        return self._agg(lambda v: float(np.mean(v)), on, f"mean({on})")

    def min(self, on: str):
        return self._agg(lambda v: float(np.min(v)), on, f"min({on})")

    def max(self, on: str):
        return self._agg(lambda v: float(np.max(v)), on, f"max({on})")

    def std(self, on: str):
        return self._agg(lambda v: float(np.std(v, ddof=1)), on,
                         f"std({on})")

    def map_groups(self, fn: Callable):
        from .dataset import Dataset, _rows_to_block
        groups = self._groups()
        out_rows: List[Any] = []
        for _, rows in sorted(groups.items(), key=lambda kv: str(kv[0])):
            result = fn(rows)
            out_rows.extend(result if isinstance(result, list) else [result])

        def source():
            import ray_tpu
            return [ray_tpu.put(_rows_to_block(out_rows))]
        return Dataset(source, [], name="map_groups")
