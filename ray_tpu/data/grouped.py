"""Grouped aggregations (reference: python/ray/data/grouped_data.py).

Aggregations run as a distributed hash exchange (hash-partition by key,
per-partition group+agg tasks — reference: hash_shuffle.py's aggregate
path) followed by a distributed sort on the key so output order is
deterministic. `map_groups` runs the same hash exchange with one
user-fn apply task per partition — every group lands whole in exactly
one task.
"""

from __future__ import annotations

from typing import Any, Callable, List

import numpy as np


class GroupedData:
    def __init__(self, dataset, key: str):
        self._dataset = dataset
        self._key = key

    def _agg(self, fn: Callable, on, name: str):
        from .exchange import groupby_exchange
        key = self._key

        def plan_fn(refs: List) -> List:
            return groupby_exchange(refs, key, fn, name, on)

        ds = self._dataset._with_stage(("allToAll", plan_fn, "groupby"),
                                       f"groupby({key}).{name}")
        return ds.sort(key)

    def count(self):
        return self._agg(len, None, "count()")

    def sum(self, on: str):
        return self._agg(lambda v: float(np.sum(v)), on, f"sum({on})")

    def mean(self, on: str):
        return self._agg(lambda v: float(np.mean(v)), on, f"mean({on})")

    def min(self, on: str):
        return self._agg(lambda v: float(np.min(v)), on, f"min({on})")

    def max(self, on: str):
        return self._agg(lambda v: float(np.max(v)), on, f"max({on})")

    def std(self, on: str):
        return self._agg(lambda v: float(np.std(v, ddof=1)), on,
                         f"std({on})")

    def aggregate(self, *aggs: "tuple") -> Any:
        """Multi-aggregation in ONE hash-aggregate exchange: per-block
        partial aggregation, then merge+finalize per hash partition
        (reference: hash_shuffle.py:1034). Each agg is (kind, column) or
        (kind, None) for row aggs; kinds: count/sum/min/max/mean.

        >>> ds.groupby("k").aggregate(("count", None), ("mean", "v"))
        """
        from .exchange import hash_aggregate_exchange
        key = self._key
        agg_list = [tuple(a) for a in aggs]

        def plan_fn(refs: List) -> List:
            return hash_aggregate_exchange(refs, key, agg_list)

        ds = self._dataset._with_stage(
            ("allToAll", plan_fn, "hash_aggregate"),
            f"groupby({key}).aggregate")
        return ds.sort(key)

    def map_groups(self, fn: Callable):
        """Apply `fn(rows) -> row | list[row]` to every COMPLETE group,
        distributed (reference: grouped_data.py map_groups): rows
        hash-partition by key so each group lands wholly in one task;
        one apply task per partition. Output order: groups sorted
        within a partition; partitions in hash order."""
        from .exchange import map_groups_exchange
        key = self._key

        def plan_fn(refs: List) -> List:
            return map_groups_exchange(refs, key, fn)

        return self._dataset._with_stage(
            ("allToAll", plan_fn, "map_groups"),
            f"groupby({key}).map_groups")
