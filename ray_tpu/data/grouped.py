"""Grouped aggregations (reference: python/ray/data/grouped_data.py).

Aggregations run as a distributed hash exchange (hash-partition by key,
per-partition group+agg tasks — reference: hash_shuffle.py's aggregate
path) followed by a distributed sort on the key so output order is
deterministic. Only `map_groups` still gathers rows in the driver (its
output shape is user-defined and typically small).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List

import numpy as np

from .block import BlockAccessor


class GroupedData:
    def __init__(self, dataset, key: str):
        self._dataset = dataset
        self._key = key

    def _agg(self, fn: Callable, on, name: str):
        from .exchange import groupby_exchange
        key = self._key

        def plan_fn(refs: List) -> List:
            return groupby_exchange(refs, key, fn, name, on)

        ds = self._dataset._with_stage(("allToAll", plan_fn, "groupby"),
                                       f"groupby({key}).{name}")
        return ds.sort(key)

    def count(self):
        return self._agg(len, None, "count()")

    def sum(self, on: str):
        return self._agg(lambda v: float(np.sum(v)), on, f"sum({on})")

    def mean(self, on: str):
        return self._agg(lambda v: float(np.mean(v)), on, f"mean({on})")

    def min(self, on: str):
        return self._agg(lambda v: float(np.min(v)), on, f"min({on})")

    def max(self, on: str):
        return self._agg(lambda v: float(np.max(v)), on, f"max({on})")

    def std(self, on: str):
        return self._agg(lambda v: float(np.std(v, ddof=1)), on,
                         f"std({on})")

    def aggregate(self, *aggs: "tuple") -> Any:
        """Multi-aggregation in ONE hash-aggregate exchange: per-block
        partial aggregation, then merge+finalize per hash partition
        (reference: hash_shuffle.py:1034). Each agg is (kind, column) or
        (kind, None) for row aggs; kinds: count/sum/min/max/mean.

        >>> ds.groupby("k").aggregate(("count", None), ("mean", "v"))
        """
        from .exchange import hash_aggregate_exchange
        key = self._key
        agg_list = [tuple(a) for a in aggs]

        def plan_fn(refs: List) -> List:
            return hash_aggregate_exchange(refs, key, agg_list)

        ds = self._dataset._with_stage(
            ("allToAll", plan_fn, "hash_aggregate"),
            f"groupby({key}).aggregate")
        return ds.sort(key)

    def map_groups(self, fn: Callable):
        from .dataset import Dataset, _rows_to_block
        groups: Dict[Any, List[Any]] = {}
        for row in self._dataset.take_all():
            groups.setdefault(row[self._key], []).append(row)
        out_rows: List[Any] = []
        for _, rows in sorted(groups.items(), key=lambda kv: str(kv[0])):
            result = fn(rows)
            out_rows.extend(result if isinstance(result, list) else [result])

        def source():
            import ray_tpu
            return [ray_tpu.put(_rows_to_block(out_rows))]
        return Dataset(source, [], name="map_groups")
