"""DataIterator (reference: python/ray/data/iterator.py +
stream_split_iterator): the per-consumer view a Train worker iterates."""

from __future__ import annotations

from typing import Any, Iterator, Optional


class DataIterator:
    def __init__(self, dataset):
        self._dataset = dataset

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     drop_last: bool = False) -> Iterator[Any]:
        return self._dataset.iter_batches(
            batch_size=batch_size, batch_format=batch_format,
            prefetch_batches=prefetch_batches, drop_last=drop_last)

    def iter_rows(self) -> Iterator[Any]:
        return self._dataset.iter_rows()

    def materialize(self):
        return self._dataset.materialize()

    def count(self) -> int:
        return self._dataset.count()
