"""DataIterator (reference: python/ray/data/iterator.py +
_internal/iterator/stream_split_iterator.py:36): the per-consumer view a
Train worker iterates. StreamSplitIterator pulls block refs from the
SplitCoordinator actor as upstream stages produce them."""

from __future__ import annotations

from typing import Any, Iterator, Optional


class DataIterator:
    def __init__(self, dataset):
        self._dataset = dataset

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     drop_last: bool = False) -> Iterator[Any]:
        return self._dataset.iter_batches(
            batch_size=batch_size, batch_format=batch_format,
            prefetch_batches=prefetch_batches, drop_last=drop_last)

    def iter_rows(self) -> Iterator[Any]:
        return self._dataset.iter_rows()

    def materialize(self):
        return self._dataset.materialize()

    def count(self) -> int:
        return self._dataset.count()


class StreamSplitIterator(DataIterator):
    """One consumer's slice of a streaming execution. Blocks arrive from
    the coordinator actor while upstream operators are still running."""

    def __init__(self, coordinator, split_index: int):
        self._coordinator = coordinator
        self._split_index = split_index

    def _iter_blocks(self):
        import ray_tpu
        while True:
            ref = ray_tpu.get(
                self._coordinator.get_next.remote(self._split_index))
            if ref is None:
                error = ray_tpu.get(self._coordinator.get_error.remote())
                if error:
                    raise RuntimeError(f"streaming split failed: {error}")
                return
            yield ray_tpu.get(ref)

    def iter_batches(self, *, batch_size: int = 256,
                     batch_format: str = "numpy",
                     prefetch_batches: int = 1,
                     drop_last: bool = False) -> Iterator[Any]:
        from .dataset import _batches_from_blocks
        return _batches_from_blocks(self._iter_blocks(), batch_size,
                                    batch_format, drop_last)

    def iter_rows(self) -> Iterator[Any]:
        from .block import BlockAccessor
        for block in self._iter_blocks():
            yield from BlockAccessor(block).iter_rows()

    def materialize(self):
        raise NotImplementedError(
            "a streaming split is a one-shot consumer stream")

    def count(self) -> int:
        return sum(1 for _ in self.iter_rows())
