"""Batch LLM inference over Datasets
(reference: python/ray/data/llm.py + llm/_internal/batch/ — the
build_llm_processor API: a Dataset stage that runs every row's prompt
through an engine replica pool with continuous batching).

TPU-native: the processor is an actor-pool map stage whose workers each
hold ONE paged engine (weights + KV pool on device, loaded once);
within a block the prompts run through the engine's continuous-batching
scheduler, so decode steps batch across rows."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional


def build_llm_processor(engine_config, *, concurrency: int = 1,
                        max_new_tokens: int = 32,
                        prompt_column: str = "prompt_tokens",
                        output_column: str = "generated_tokens",
                        params=None,
                        detokenize: Optional[Callable] = None
                        ) -> Callable:
    """Returns `processor(dataset) -> dataset` adding `output_column`
    with each row's generation (reference: data/llm.py
    build_llm_processor -> Processor). `prompt_column` holds token-id
    lists (or strings when `detokenize`'s inverse applies upstream)."""

    class _EngineWorker:
        def __init__(self):
            from ..llm.engine import EngineConfig, LLMEngine
            from ..llm.paged import PagedEngineConfig, PagedLLMEngine
            if isinstance(engine_config, PagedEngineConfig):
                self.engine = PagedLLMEngine(engine_config, params=params)
            elif isinstance(engine_config, EngineConfig):
                self.engine = LLMEngine(engine_config, params=params)
            else:
                raise TypeError(type(engine_config).__name__)

        def __call__(self, batch: Dict[str, Any]) -> Dict[str, Any]:
            import numpy as np
            prompts = [list(map(int, p)) for p in batch[prompt_column]]
            outs = self.engine.generate(prompts,
                                        max_new_tokens=max_new_tokens)
            out = dict(batch)
            result = np.empty(len(outs), dtype=object)
            for i, tokens in enumerate(outs):
                result[i] = detokenize(tokens) if detokenize else tokens
            out[output_column] = result
            return out

    def processor(dataset):
        return dataset.map_batches(
            _EngineWorker, compute="actors", concurrency=concurrency)

    return processor
