"""Logical plan + rule-based optimizer
(reference: python/ray/data/_internal/logical/interfaces/logical_plan.py:10,
optimizer.py:24, rules in _internal/logical/rules/ — the reference lowers
Dataset transformations into LogicalOperator nodes, runs rewrite rules to a
fixpoint, then plans physical operators).

Here a Dataset's stages are `LogicalOp` nodes carrying enough structure for
the rules to reason about: row-preservation (limit pushdown), column sets
(projection pushdown/merging), compute settings (fusion boundaries)."""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Tuple

MAP = "map"
ALL_TO_ALL = "allToAll"


@dataclasses.dataclass
class LogicalOp:
    kind: str                       # MAP | ALL_TO_ALL
    fn: Callable                    # block fn (map) / plan fn (allToAll)
    name: str = ""
    opts: Dict[str, Any] = dataclasses.field(default_factory=dict)
    # True when the op maps rows 1:1 (map / add_column / select / drop):
    # a downstream limit may hop over it (LimitPushdown).
    preserves_rows: bool = False
    # Structured facts rules understand:
    #   {"limit": n}            — this op is limit(n)
    #   {"columns": [...]}      — this op is select_columns(cols)
    #   {"exchange": "sort"|...} — all-to-all flavor
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def is_limit(self) -> bool:
        return "limit" in self.meta

    def is_projection(self) -> bool:
        return "columns" in self.meta


class Rule:
    """One rewrite: returns (ops, source, changed)."""

    def apply(self, ops: List[LogicalOp], source):
        raise NotImplementedError


class LimitPushdown(Rule):
    """Move limit(n) before row-preserving map ops so upstream stages
    process only the blocks the limit will keep (reference:
    logical/rules/limit_pushdown.py)."""

    def apply(self, ops, source):
        changed = False
        out = list(ops)
        i = 1
        while i < len(out):
            op = out[i]
            prev = out[i - 1]
            if op.is_limit() and prev.kind == MAP and prev.preserves_rows:
                out[i - 1], out[i] = op, prev
                changed = True
                i = max(1, i - 1)
            else:
                i += 1
        return out, source, changed


class ProjectionPushdown(Rule):
    """Merge consecutive select_columns and push the leading projection
    into a column-aware datasource (parquet reads only the named columns
    — reference: logical/rules/ projection pushdown into ReadParquet)."""

    def apply(self, ops, source):
        changed = False
        out: List[LogicalOp] = []
        for op in ops:
            if (op.is_projection() and out and out[-1].is_projection()
                    and set(op.meta["columns"]) <=
                    set(out[-1].meta["columns"])):
                # select(a).select(b) == select(b) ONLY when b ⊆ a; the
                # narrower (later) projection wins. A non-subset second
                # select must stay put so it fails at runtime exactly
                # like the unoptimized plan would (the rewrite must not
                # resurrect dropped columns).
                out[-1] = op
                changed = True
            else:
                out.append(op)
        if (out and out[0].is_projection() and source is not None
                and getattr(source, "supports_columns", False)
                and source.columns is None):
            source = source.with_columns(out[0].meta["columns"])
            out = out[1:]
            changed = True
        return out, source, changed


class MapFusion(Rule):
    """Fuse adjacent map ops with identical compute settings into one
    physical stage (reference: logical/rules/operator_fusion.py). After
    the optimizer runs, physical ops are built 1:1 from logical ops, so
    the fused stage count is directly assertable."""

    def apply(self, ops, source):
        changed = False
        out: List[LogicalOp] = []
        for op in ops:
            if (op.kind == MAP and out and out[-1].kind == MAP
                    and _compute_key(out[-1]) == _compute_key(op)):
                prev = out[-1]
                prev_fns = prev.meta.get("fused_fns", [prev.fn])
                fns = prev_fns + op.meta.get("fused_fns", [op.fn])

                def fused(block, _fns=tuple(fns)):
                    for f in _fns:
                        block = f(block)
                    return block

                out[-1] = LogicalOp(
                    MAP, fused, name=f"{prev.name}+{op.name}",
                    opts=prev.opts,
                    preserves_rows=prev.preserves_rows and
                    op.preserves_rows,
                    meta={"fused_fns": fns})
                changed = True
            else:
                out.append(op)
        return out, source, changed


def _compute_key(op: LogicalOp) -> Tuple:
    return (op.opts.get("compute"), op.opts.get("concurrency"))


class Optimizer:
    """Run rules to a fixpoint (reference: optimizer.py:24 — each pass
    applies every rule until none fires)."""

    RULES = (LimitPushdown(), ProjectionPushdown(), MapFusion())

    def optimize(self, ops: List[LogicalOp], source=None):
        for _ in range(16):  # fixpoint bound; rules strictly shrink/shift
            any_changed = False
            for rule in self.RULES:
                ops, source, changed = rule.apply(ops, source)
                any_changed = any_changed or changed
            if not any_changed:
                break
        return ops, source
