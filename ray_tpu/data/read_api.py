"""Dataset creation (reference: python/ray/data/read_api.py)."""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional

import numpy as np

from .block import BlockAccessor
from .context import DataContext
from .dataset import Dataset, _rows_to_block


def _put_blocks(blocks: List) -> Dataset:
    def source():
        import ray_tpu
        return [ray_tpu.put(b) for b in blocks]
    return Dataset(source, [], name="in-memory")


_builtin_range = range  # shadowed below by the Dataset-producing `range`


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    ctx = DataContext.get_current()
    parallelism = parallelism if parallelism > 0 else ctx.read_parallelism
    per = max(1, -(-n // parallelism))

    def source():
        import ray_tpu
        import pyarrow as pa
        refs = []
        for start in _builtin_range(0, n, per):
            stop = min(start + per, n)
            refs.append(ray_tpu.put(
                pa.table({"id": np.arange(start, stop)})))
        return refs
    return Dataset(source, [], name=f"range[{n}]")


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    ctx = DataContext.get_current()
    parallelism = parallelism if parallelism > 0 else ctx.read_parallelism
    per = max(1, -(-len(items) // parallelism)) if items else 1
    blocks = [_rows_to_block(items[i:i + per])
              for i in _builtin_range(0, max(len(items), 1), per)]
    return _put_blocks(blocks)


def from_pandas(dfs) -> Dataset:
    import pyarrow as pa
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _put_blocks([pa.Table.from_pandas(df, preserve_index=False)
                        for df in dfs])


def from_numpy(arrays) -> Dataset:
    if not isinstance(arrays, list):
        arrays = [arrays]
    import pyarrow as pa
    blocks = []
    for arr in arrays:
        blocks.append(BlockAccessor.batch_to_block({"data": arr}))
    return _put_blocks(blocks)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _put_blocks(tables)


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            pattern = os.path.join(path, f"*{suffix}" if suffix else "*")
            out.extend(sorted(_glob.glob(pattern)))
        elif any(ch in path for ch in "*?["):
            out.extend(sorted(_glob.glob(path)))
        else:
            out.append(path)
    return out


class ParquetSource:
    """Column-aware datasource descriptor: the optimizer's
    ProjectionPushdown rewrites `columns` so parquet reads materialize
    only the projected columns (reference: logical/rules projection
    pushdown into the ReadParquet operator)."""

    supports_columns = True

    def __init__(self, files: List[str],
                 columns: Optional[List[str]] = None):
        self.files = files
        self.columns = columns

    def with_columns(self, columns: List[str]) -> "ParquetSource":
        return ParquetSource(self.files, list(columns))

    def describe(self) -> str:
        cols = f" columns={self.columns}" if self.columns else ""
        return f"parquet[{len(self.files)} files{cols}]"

    def fn(self):
        import ray_tpu
        columns = self.columns

        @ray_tpu.remote(num_cpus=1)
        def _read(path, columns=columns):
            import pyarrow.parquet as pq
            return pq.read_table(path, columns=columns)
        return [_read.remote(f) for f in self.files]


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    files = _expand_paths(paths, ".parquet")
    source = ParquetSource(files, columns)
    return Dataset(source.fn, [], name="read_parquet", source=source)


def read_csv(paths) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            import pyarrow.csv as pacsv
            return pacsv.read_csv(path)
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_csv")


def read_json(paths) -> Dataset:
    files = _expand_paths(paths, ".json")

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            import json
            rows = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
            return _rows_to_block(rows)
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_json")


def read_text(paths) -> Dataset:
    files = _expand_paths(paths)

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            with open(path) as f:
                lines = [line.rstrip("\n") for line in f]
            return _rows_to_block([{"text": line} for line in lines])
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_text")


def read_binary_files(paths) -> Dataset:
    files = _expand_paths(paths)

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            with open(path, "rb") as f:
                return [{"path": path, "bytes": f.read()}]
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_binary_files")


def read_images(paths, *, size: Optional[tuple] = None,
                mode: Optional[str] = None,
                include_paths: bool = False) -> Dataset:
    """Decode image files into {"image": HxWxC uint8 array} rows
    (reference: data/_internal/datasource/image_datasource.py — PIL
    decode, optional resize/mode, include_paths)."""
    files = _expand_paths(paths)

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path, size=size, mode=mode,
                  include_paths=include_paths):
            from PIL import Image
            img = Image.open(path)
            if size is not None:
                img = img.resize((size[1], size[0]))  # (h, w) -> PIL wh
            if mode is not None:
                img = img.convert(mode)
            row: Dict[str, Any] = {"image": np.asarray(img)}
            if include_paths:
                row["path"] = path
            return [row]
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_images")


# -- TFRecord wire format (reference: datasource/tfrecords_datasource.py;
# record framing: u64 length, u32 masked-crc(length), payload,
# u32 masked-crc(payload), crc = crc32c with the TF mask rotation) -----

_CRC32C_TABLE = None


def _crc32c(data: bytes) -> int:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        table = []
        for i in _builtin_range(256):
            crc = i
            for _ in _builtin_range(8):
                crc = (crc >> 1) ^ (0x82F63B78 if crc & 1 else 0)
            table.append(crc)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for byte in data:
        crc = _CRC32C_TABLE[(crc ^ byte) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    crc = _crc32c(data)
    return (((crc >> 15) | (crc << 17)) + 0xA282EAD8) & 0xFFFFFFFF


def _tfrecord_iter(path: str):
    import struct
    with open(path, "rb") as f:
        while True:
            header = f.read(12)
            if len(header) < 12:
                return
            (length,), (_len_crc,) = (struct.unpack("<Q", header[:8]),
                                      struct.unpack("<I", header[8:]))
            payload = f.read(length)
            f.read(4)  # payload crc (verification optional, like TF)
            yield payload


def _tfrecord_write(path: str, payloads) -> int:
    import struct
    n = 0
    with open(path, "wb") as f:
        for payload in payloads:
            header = struct.pack("<Q", len(payload))
            f.write(header)
            f.write(struct.pack("<I", _masked_crc(header)))
            f.write(payload)
            f.write(struct.pack("<I", _masked_crc(payload)))
            n += 1
    return n


def _example_to_row(payload: bytes) -> Dict[str, Any]:
    """Decode a tf.train.Example proto without tensorflow: hand-rolled
    protobuf walk of Features -> feature map -> {bytes,float,int64}
    lists (the three TF feature types)."""
    row: Dict[str, Any] = {}

    def varint(buf, pos):
        shift = result = 0
        while True:
            b = buf[pos]
            pos += 1
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                return result, pos
            shift += 7

    def fields(buf):
        pos = 0
        while pos < len(buf):
            tag, pos = varint(buf, pos)
            number, wire = tag >> 3, tag & 7
            if wire == 2:
                size, pos = varint(buf, pos)
                yield number, buf[pos:pos + size]
                pos += size
            elif wire == 0:
                value, pos = varint(buf, pos)
                yield number, value
            elif wire == 5:
                yield number, buf[pos:pos + 4]
                pos += 4
            elif wire == 1:
                yield number, buf[pos:pos + 8]
                pos += 8
            else:
                raise ValueError(f"unsupported wire type {wire}")

    import struct
    for num, features in fields(payload):
        if num != 1:
            continue
        for fnum, entry in fields(features):
            if fnum != 1:
                continue
            key = value = None
            for enum, edata in fields(entry):
                if enum == 1:
                    key = edata.decode()
                elif enum == 2:
                    for vnum, vdata in fields(edata):
                        if vnum == 1:      # BytesList
                            value = [b for _n, b in fields(vdata)]
                        elif vnum == 2:    # FloatList
                            value = []
                            for _in, data in fields(vdata):
                                if isinstance(data, bytes):  # packed
                                    value.extend(struct.unpack(
                                        f"<{len(data) // 4}f", data))
                        elif vnum == 3:    # Int64List
                            def signed(v):
                                # two's-complement decode of the
                                # unsigned varint (TF writes -1 as ten
                                # 0xFF.. bytes)
                                return v - (1 << 64) if v >= (1 << 63) \
                                    else v
                            value = []
                            for _in, data in fields(vdata):
                                if isinstance(data, bytes):  # packed
                                    pos = 0
                                    while pos < len(data):
                                        v, pos = varint(data, pos)
                                        value.append(signed(v))
                                else:      # unpacked varint
                                    value.append(signed(data))
            if key is not None and value is not None:
                row[key] = value[0] if len(value) == 1 else value
    return row


def _row_to_example(row: Dict[str, Any]) -> bytes:
    """Encode a row as a tf.train.Example proto (inverse of
    _example_to_row; enough of protobuf to round-trip with TF)."""
    import struct

    def varint(n: int) -> bytes:
        # protobuf varints are unsigned: negatives go as 64-bit two's
        # complement (ten bytes), like TF writes them
        n &= (1 << 64) - 1
        out = b""
        while True:
            bits = n & 0x7F
            n >>= 7
            if n:
                out += bytes([bits | 0x80])
            else:
                return out + bytes([bits])

    def field(number: int, wire: int, payload: bytes) -> bytes:
        return varint((number << 3) | wire) + payload

    def ld(number: int, payload: bytes) -> bytes:
        return field(number, 2, varint(len(payload)) + payload)

    features = b""
    for key, value in row.items():
        values = value if isinstance(value, (list, tuple)) \
            else [value]
        if all(isinstance(v, (bytes, str)) for v in values):
            blist = b"".join(
                ld(1, v.encode() if isinstance(v, str) else v)
                for v in values)
            feature = ld(1, blist)
        elif all(isinstance(v, (int, np.integer)) for v in values):
            packed = b"".join(varint(int(v)) for v in values)
            feature = ld(3, field(1, 2, varint(len(packed)) + packed))
        else:
            packed = struct.pack(f"<{len(values)}f",
                                 *[float(v) for v in values])
            feature = ld(2, field(1, 2, varint(len(packed)) + packed))
        features += ld(1, ld(1, key.encode()) + ld(2, feature))
    return ld(1, features)


def read_tfrecords(paths) -> Dataset:
    """tf.train.Example TFRecord files -> rows (reference:
    datasource/tfrecords_datasource.py — no tensorflow import; the
    record framing and Example proto are decoded directly)."""
    files = _expand_paths(paths)

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            rows = [_example_to_row(p) for p in _tfrecord_iter(path)]
            return _rows_to_block(rows)
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_tfrecords")


def read_sql(sql: str, connection_factory, *,
             parallelism: int = 1) -> Dataset:
    """Rows from a DBAPI query (reference:
    datasource/sql_datasource.py — connection_factory is a zero-arg
    callable returning a DBAPI connection, e.g. a sqlite3/psycopg
    connector; the query runs once per shard with OFFSET/LIMIT when
    parallelism > 1)."""

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(shard, shards):
            conn = connection_factory()
            try:
                cursor = conn.cursor()
                query = sql
                if shards > 1:
                    # per-shard pagination; assumes a stable ordering in
                    # the query (the reference documents the same). The
                    # subquery alias is required by PostgreSQL and
                    # harmless on sqlite/mysql.
                    count = conn.cursor()
                    count.execute(
                        f"SELECT COUNT(*) FROM ({sql}) AS _rtpu_sub")
                    total = count.fetchone()[0]
                    per = -(-total // shards)
                    query = (f"SELECT * FROM ({sql}) AS _rtpu_sub "
                             f"LIMIT {per} OFFSET {shard * per}")
                cursor.execute(query)
                columns = [d[0] for d in cursor.description]
                rows = [dict(zip(columns, r)) for r in cursor.fetchall()]
                return _rows_to_block(rows)
            finally:
                conn.close()
        return [_read.remote(i, parallelism)
                for i in _builtin_range(parallelism)]
    return Dataset(source, [], name="read_sql")


def read_numpy(paths) -> Dataset:
    """.npy files -> {"data": row} rows, the file's leading axis as the
    row axis (reference: datasource/numpy_datasource.py)."""
    files = _expand_paths(paths, ".npy")

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            arr = np.load(path, allow_pickle=False)
            return [{"data": arr[i]} for i in _builtin_range(len(arr))]
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_numpy")


def read_webdataset(paths) -> Dataset:
    """WebDataset tar shards -> one row per sample (reference:
    datasource/webdataset_datasource.py): members sharing a basename
    stem form a sample; each extension becomes a bytes field plus the
    "__key__" stem. Pure tarfile — no webdataset import."""
    files = _expand_paths(paths, ".tar")

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            import tarfile
            rows: List[Dict[str, Any]] = []
            current: Dict[str, Any] = {}
            with tarfile.open(path) as tar:
                for member in tar:
                    if not member.isfile():
                        continue
                    # key = FULL path up to the first dot of the
                    # basename (webdataset semantics): same-named files
                    # in different directories are different samples
                    head, _, base = member.name.rpartition("/")
                    stem, _, ext = base.partition(".")
                    key = f"{head}/{stem}" if head else stem
                    if current.get("__key__") not in (None, key):
                        rows.append(current)
                        current = {}
                    current["__key__"] = key
                    current[ext] = tar.extractfile(member).read()
            if current:
                rows.append(current)
            return rows
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_webdataset")


def from_torch(torch_dataset, *, parallelism: int = 1) -> Dataset:
    """A torch map-style Dataset -> {"item": sample} rows (reference:
    read_api.from_torch). Materializes on the DRIVER (torch datasets
    are rarely picklable-to-workers; the reference does the same for
    map-style datasets)."""
    items = [{"item": torch_dataset[i]}
             for i in _builtin_range(len(torch_dataset))]
    return from_items(items, parallelism=parallelism)
