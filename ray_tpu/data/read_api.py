"""Dataset creation (reference: python/ray/data/read_api.py)."""

from __future__ import annotations

import glob as _glob
import os
from typing import Any, Dict, List, Optional

import numpy as np

from .block import BlockAccessor
from .context import DataContext
from .dataset import Dataset, _rows_to_block


def _put_blocks(blocks: List) -> Dataset:
    def source():
        import ray_tpu
        return [ray_tpu.put(b) for b in blocks]
    return Dataset(source, [], name="in-memory")


_builtin_range = range  # shadowed below by the Dataset-producing `range`


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001
    ctx = DataContext.get_current()
    parallelism = parallelism if parallelism > 0 else ctx.read_parallelism
    per = max(1, -(-n // parallelism))

    def source():
        import ray_tpu
        import pyarrow as pa
        refs = []
        for start in _builtin_range(0, n, per):
            stop = min(start + per, n)
            refs.append(ray_tpu.put(
                pa.table({"id": np.arange(start, stop)})))
        return refs
    return Dataset(source, [], name=f"range[{n}]")


def from_items(items: List[Any], *, parallelism: int = -1) -> Dataset:
    ctx = DataContext.get_current()
    parallelism = parallelism if parallelism > 0 else ctx.read_parallelism
    per = max(1, -(-len(items) // parallelism)) if items else 1
    blocks = [_rows_to_block(items[i:i + per])
              for i in _builtin_range(0, max(len(items), 1), per)]
    return _put_blocks(blocks)


def from_pandas(dfs) -> Dataset:
    import pyarrow as pa
    if not isinstance(dfs, list):
        dfs = [dfs]
    return _put_blocks([pa.Table.from_pandas(df, preserve_index=False)
                        for df in dfs])


def from_numpy(arrays) -> Dataset:
    if not isinstance(arrays, list):
        arrays = [arrays]
    import pyarrow as pa
    blocks = []
    for arr in arrays:
        blocks.append(BlockAccessor.batch_to_block({"data": arr}))
    return _put_blocks(blocks)


def from_arrow(tables) -> Dataset:
    if not isinstance(tables, list):
        tables = [tables]
    return _put_blocks(tables)


def _expand_paths(paths, suffix: Optional[str] = None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for path in paths:
        if os.path.isdir(path):
            pattern = os.path.join(path, f"*{suffix}" if suffix else "*")
            out.extend(sorted(_glob.glob(pattern)))
        elif any(ch in path for ch in "*?["):
            out.extend(sorted(_glob.glob(path)))
        else:
            out.append(path)
    return out


class ParquetSource:
    """Column-aware datasource descriptor: the optimizer's
    ProjectionPushdown rewrites `columns` so parquet reads materialize
    only the projected columns (reference: logical/rules projection
    pushdown into the ReadParquet operator)."""

    supports_columns = True

    def __init__(self, files: List[str],
                 columns: Optional[List[str]] = None):
        self.files = files
        self.columns = columns

    def with_columns(self, columns: List[str]) -> "ParquetSource":
        return ParquetSource(self.files, list(columns))

    def describe(self) -> str:
        cols = f" columns={self.columns}" if self.columns else ""
        return f"parquet[{len(self.files)} files{cols}]"

    def fn(self):
        import ray_tpu
        columns = self.columns

        @ray_tpu.remote(num_cpus=1)
        def _read(path, columns=columns):
            import pyarrow.parquet as pq
            return pq.read_table(path, columns=columns)
        return [_read.remote(f) for f in self.files]


def read_parquet(paths, *, columns: Optional[List[str]] = None) -> Dataset:
    files = _expand_paths(paths, ".parquet")
    source = ParquetSource(files, columns)
    return Dataset(source.fn, [], name="read_parquet", source=source)


def read_csv(paths) -> Dataset:
    files = _expand_paths(paths, ".csv")

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            import pyarrow.csv as pacsv
            return pacsv.read_csv(path)
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_csv")


def read_json(paths) -> Dataset:
    files = _expand_paths(paths, ".json")

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            import json
            rows = []
            with open(path) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        rows.append(json.loads(line))
            return _rows_to_block(rows)
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_json")


def read_text(paths) -> Dataset:
    files = _expand_paths(paths)

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            with open(path) as f:
                lines = [line.rstrip("\n") for line in f]
            return _rows_to_block([{"text": line} for line in lines])
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_text")


def read_binary_files(paths) -> Dataset:
    files = _expand_paths(paths)

    def source():
        import ray_tpu

        @ray_tpu.remote(num_cpus=1)
        def _read(path):
            with open(path, "rb") as f:
                return [{"path": path, "bytes": f.read()}]
        return [_read.remote(f) for f in files]
    return Dataset(source, [], name="read_binary_files")
