"""Streaming execution of Dataset plans.

Role of the reference's StreamingExecutor
(python/ray/data/_internal/execution/streaming_executor.py:61 — runs as a
thread; _scheduling_loop_step :421) and its operator state machine
(execution/interfaces/physical_operator.py:214):

- the plan becomes a linear topology of operators (map ops with fused
  transform chains; all-to-all exchanges as barriers);
- each map operator keeps a bounded number of tasks in flight and a bounded
  output buffer — when the downstream (ultimately the consumer iterator)
  falls behind, upstream submission stalls: end-to-end backpressure;
- blocks stream to the consumer as they finish, so training can iterate
  batches while upstream stages are still producing;
- map operators run either as a task pool or as an actor pool
  (`compute="actors"` — reference: actor-pool map operator).

The executor is a daemon thread in the consuming process; block payloads
live in the shared-memory object store, only refs flow through the queues.
"""

from __future__ import annotations

import logging
import queue
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from .context import DataContext

logger = logging.getLogger(__name__)

_SENTINEL = object()


class _MapWorker:
    """Actor-pool map worker (reference: ActorPoolMapOperator's workers)."""

    def __init__(self, fns):
        self._fns = fns

    def apply(self, block):
        for fn in self._fns:
            block = fn(block)
        return block

    def ping(self):
        return "pong"


class Op:
    """Base physical operator: pull refs from `input`, push to `out`."""

    def __init__(self, name: str):
        self.name = name
        self.input: deque = deque()
        self.out: deque = deque()
        self.input_done = False
        self.output_done = False

    def start(self):
        pass

    def shutdown(self):
        pass

    def num_in_flight(self) -> int:
        return 0

    def schedule(self, output_room: int) -> bool:
        """Advance; return True if any progress was made."""
        raise NotImplementedError


class MapOp(Op):
    """Fused map chain over blocks; task pool or actor pool."""

    def __init__(self, name: str, fns: List[Callable],
                 compute: Optional[str] = None,
                 concurrency: Optional[Any] = None):
        super().__init__(name)
        self.fns = fns
        self.compute = compute
        # `concurrency` for actor pools may be a (min, max) tuple: the
        # pool autoscales between the bounds on queue depth (reference:
        # data/_internal/execution/autoscaler/ — the actor-pool
        # autoscaler; a plain int is a fixed-size pool).
        if isinstance(concurrency, (tuple, list)):
            self.min_actors, self.max_actors = concurrency
            concurrency = int(self.max_actors)
        else:
            self.min_actors = self.max_actors = concurrency
        self.concurrency = concurrency
        ctx = DataContext.get_current()
        # Static fallback; the executor's ResourceManager overrides this
        # per tick with the op's fair share of the pipeline budget.
        self.window = concurrency or ctx.max_tasks_in_flight
        self.in_flight: List = []
        self._remote_fn = None
        self._actors: List = []
        self._actor_rr = 0
        self._actor_cls = None
        self._idle_since: Optional[float] = None
        self._scale_down_after_s = 1.0

    def start(self):
        import ray_tpu
        if self.compute == "actors":
            self._actor_cls = ray_tpu.remote(_MapWorker)
            # no concurrency given: a fixed pool sized by the default
            # task window (the pre-autoscaler behavior)
            initial = self.min_actors if self.min_actors is not None \
                else self.window
            self._actors = [self._actor_cls.remote(self.fns)
                            for _ in range(max(1, initial))]
        else:
            fns = self.fns

            @ray_tpu.remote(num_cpus=1, max_retries=2)
            def _apply(block, _fns=fns):
                for fn in _fns:
                    block = fn(block)
                return block

            self._remote_fn = _apply

    def shutdown(self):
        import ray_tpu
        for actor in self._actors:
            try:
                ray_tpu.kill(actor)
            except Exception:
                logger.debug("actor kill at op shutdown failed",
                             exc_info=True)
        self._actors = []

    def num_in_flight(self) -> int:
        return len(self.in_flight)

    def _autoscale_actors(self):
        """Grow the pool when the backlog saturates every worker; shrink
        to min after a sustained idle window (reference:
        execution/autoscaler/default_autoscaler.py — queue-depth-driven
        actor-pool scaling)."""
        import time as _time

        import ray_tpu
        if self._actor_cls is None or \
                self.min_actors == self.max_actors:
            return
        busy = len(self.in_flight) >= len(self._actors)
        backlog = len(self.input)
        if busy and backlog > 0 and \
                len(self._actors) < int(self.max_actors):
            self._actors.append(self._actor_cls.remote(self.fns))
            self._idle_since = None
            return
        if backlog == 0 and not self.in_flight:
            now = _time.monotonic()
            if self._idle_since is None:
                self._idle_since = now
            elif now - self._idle_since > self._scale_down_after_s and \
                    len(self._actors) > max(1, int(self.min_actors or 1)):
                doomed = self._actors.pop()
                try:
                    ray_tpu.kill(doomed)
                except Exception:  # noqa: BLE001
                    logger.debug("downscale kill failed", exc_info=True)
        else:
            self._idle_since = None

    def schedule(self, output_room: int,
                 window: Optional[int] = None) -> bool:
        import ray_tpu
        progress = False
        if window is not None:
            self.window = window
        if self._actors:
            self._autoscale_actors()
            # actor pools are bounded by pool size (byte backpressure
            # still applies via window=0)
            if self.window:
                self.window = len(self._actors) * 2
        # Launch: bounded by the task window AND downstream room (the
        # backpressure signal — never produce more than the consumer and
        # output buffer can hold).
        while (self.input and len(self.in_flight) < self.window
               and len(self.in_flight) + len(self.out) < output_room):
            ref = self.input.popleft()
            if self._actors:
                actor = self._actors[self._actor_rr % len(self._actors)]
                self._actor_rr += 1
                self.in_flight.append(actor.apply.remote(ref))
            else:
                self.in_flight.append(self._remote_fn.remote(ref))
            progress = True
        # Harvest finished tasks in order (stream, don't barrier).
        if self.in_flight:
            ready, _ = ray_tpu.wait(self.in_flight,
                                    num_returns=len(self.in_flight),
                                    timeout=0, fetch_local=False)
            ready_set = set(r.id() for r in ready)
            still = []
            for ref in self.in_flight:
                if ref.id() in ready_set:
                    self.out.append(ref)
                    progress = True
                else:
                    still.append(ref)
            self.in_flight = still
        if self.input_done and not self.input and not self.in_flight:
            if not self.output_done:
                self.output_done = True
                progress = True
        return progress


class AllToAllOp(Op):
    """Barrier operator: consume the whole input, then run `plan_fn`
    (which submits the distributed exchange tasks) once."""

    def __init__(self, name: str, plan_fn: Callable[[List], List]):
        super().__init__(name)
        self.plan_fn = plan_fn
        self._ran = False
        self._collected: List = []

    def schedule(self, output_room: int) -> bool:
        progress = False
        while self.input:
            self._collected.append(self.input.popleft())
            progress = True
        if self.input_done and not self._ran:
            self._ran = True
            for ref in self.plan_fn(self._collected):
                self.out.append(ref)
            self._collected = []
            self.output_done = True
            progress = True
        return progress


class ResourceManager:
    """Per-pipeline resource budget (reference:
    data/_internal/execution/resource_manager.py + backpressure_policy/).

    Map operators share one CPU budget fairly instead of each claiming a
    fixed window: with k active map ops on a pipeline budget of B task
    slots, each op may keep ~B/k tasks in flight (an op with explicit
    `concurrency` is additionally capped by it). Ops that finish release
    their share to the survivors, so a single straggler stage ramps up to
    the whole budget instead of starving behind a fixed window."""

    def __init__(self, ops: List[Op]):
        ctx = DataContext.get_current()
        budget = ctx.execution_cpu_budget
        if budget is None:
            try:
                import ray_tpu
                budget = int(ray_tpu.cluster_resources().get("CPU", 0))
            except Exception:  # noqa: BLE001 — no cluster yet
                budget = 0
        self.budget = max(1, budget or ctx.max_tasks_in_flight)
        self.byte_budget = ctx.execution_object_store_byte_budget
        self._map_ops = [op for op in ops if isinstance(op, MapOp)]
        self._ops = ops
        self._size_cache: Dict[str, int] = {}
        self._default_size = ctx.target_min_block_size
        self.buffered_bytes = 0
        self._over_bytes = False

    def _ref_size(self, ref) -> int:
        """Local size of a buffered block (memory store / plasma);
        cached per ref — queue membership changes, sizes don't."""
        key = ref.hex()
        size = self._size_cache.get(key)
        if size is not None:
            return size
        size = self._default_size
        try:
            from .._internal.core_worker import get_core_worker
            cw = get_core_worker()
            oid = ref.id()
            entry = cw.memory_store.get_entry(oid)
            raw = getattr(entry, "raw", None) if entry is not None \
                else None
            if raw is not None:
                size = len(raw)
            elif cw.plasma.contains(oid):
                size = cw.plasma.size_of(oid)
        except Exception:  # noqa: BLE001 — size is advisory
            logger.debug("block size probe failed", exc_info=True)
        self._size_cache[key] = size
        if len(self._size_cache) > 4096:
            self._size_cache.clear()
        return size

    def update_byte_usage(self, out_queue=None):
        """Recompute bytes of PRODUCED blocks still buffered — operator
        outputs, downstream inputs, and the consumer queue; sets the
        over-budget flag the windows consult. The source op's own input
        refs are excluded: those bytes can only shrink by LAUNCHING
        tasks, so gating launches on them would livelock (they are the
        reference's 'reserved' budget, not the throttleable part)."""
        if self.byte_budget is None:
            return
        total = 0
        for i, op in enumerate(self._ops):
            refs = list(op.out)
            if i > 0:
                refs += list(op.input)
            for ref in refs:
                total += self._ref_size(ref)
        if out_queue is not None:
            for ref in list(out_queue.queue):
                if ref is not _SENTINEL:
                    total += self._ref_size(ref)
        self.buffered_bytes = total
        self._over_bytes = total >= self.byte_budget

    def window_for(self, op: "MapOp") -> int:
        if self._over_bytes:
            # Byte backpressure: stop LAUNCHING; in-flight tasks finish
            # and buffered blocks drain to the consumer. Liveness: if
            # nothing is in flight anywhere, admit ONE task on the
            # first unfinished op so a budget smaller than a single
            # block still makes progress.
            if not any(o.in_flight for o in self._map_ops):
                first_active = next(
                    (o for o in self._map_ops if not o.output_done),
                    None)
                if op is first_active:
                    return 1
            return 0
        active = [o for o in self._map_ops if not o.output_done]
        share = max(1, self.budget // max(1, len(active)))
        if op.concurrency:
            return min(share, op.concurrency) if op.compute != "actors" \
                else op.concurrency
        return share

    def usage(self) -> Dict[str, int]:
        return {op.name: len(op.in_flight) for op in self._map_ops}


class StreamingExecutor:
    """Drives a topology of ops in a daemon thread; the consumer iterates
    `out_queue` (bounded — consumer lag backpressures the whole stream)."""

    def __init__(self, source_fn: Callable[[], List], ops: List[Op],
                 name: str = "dataset"):
        self.source_fn = source_fn
        self.ops = ops
        self.name = name
        ctx = DataContext.get_current()
        self.out_queue: "queue.Queue" = queue.Queue(
            maxsize=max(2, ctx.streaming_output_buffer_blocks))
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._thread: Optional[threading.Thread] = None

    # -- consumer interface ---------------------------------------------

    def run_async(self) -> "StreamingExecutor":
        # Tracking-only: a node teardown sweep must not silently halt a
        # pipeline mid-iteration; the executor's own shutdown()/consumer
        # exit sets _stop.
        from .._internal.threads import register_daemon_thread
        self._thread = threading.Thread(
            target=self._run, name=f"rtpu-data-{self.name}", daemon=True)
        register_daemon_thread(self._thread, joinable=False)
        self._thread.start()
        return self

    def iter_output(self):
        """Yield block refs as they are produced."""
        if self._thread is None:
            self.run_async()
        while True:
            item = self.out_queue.get()
            if item is _SENTINEL:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def stop(self):
        """Abandon the stream (early consumer exit, e.g. take(n))."""
        self._stop.set()
        # Drain so a blocked producer wakes up and sees the stop flag.
        try:
            while True:
                self.out_queue.get_nowait()
        except queue.Empty:
            pass

    # -- executor thread -------------------------------------------------

    def _run(self):
        ctx = DataContext.get_current()
        per_op_buffer = max(2, ctx.op_output_buffer_blocks)
        try:
            for op in self.ops:
                op.start()
            first = self.ops[0] if self.ops else None
            source_refs = list(self.source_fn())
            if first is not None:
                first.input.extend(source_refs)
                first.input_done = True
            else:
                for ref in source_refs:
                    if not self._emit(ref):
                        return
                return
            resource_manager = ResourceManager(self.ops)
            self.resource_manager = resource_manager
            idle_backoff = 0.001
            while not self._stop.is_set():
                progress = False
                resource_manager.update_byte_usage(self.out_queue)
                for i, op in enumerate(self.ops):
                    if i + 1 < len(self.ops):
                        room = per_op_buffer
                    else:
                        # Last op: its room is the consumer queue's slack.
                        room = max(
                            1, self.out_queue.maxsize - self.out_queue.qsize()
                            + op.num_in_flight())
                    if isinstance(op, MapOp):
                        scheduled = op.schedule(
                            room, window=resource_manager.window_for(op))
                    else:
                        scheduled = op.schedule(room)
                    if scheduled:
                        progress = True
                    # Move outputs downstream / to the consumer.
                    if i + 1 < len(self.ops):
                        nxt = self.ops[i + 1]
                        while op.out and len(nxt.input) < per_op_buffer:
                            nxt.input.append(op.out.popleft())
                            progress = True
                        if op.output_done and not op.out:
                            if not nxt.input_done:
                                nxt.input_done = True
                                progress = True
                    else:
                        while op.out:
                            if not self._emit(op.out.popleft()):
                                return
                            progress = True
                        if op.output_done and not op.out:
                            return
                if not progress:
                    self._stop.wait(idle_backoff)
                    idle_backoff = min(idle_backoff * 2, 0.05)
                else:
                    idle_backoff = 0.001
        except BaseException as e:  # noqa: BLE001 — surfaced to consumer
            self._error = e
        finally:
            for op in self.ops:
                try:
                    op.shutdown()
                except Exception:
                    logger.debug("operator shutdown failed", exc_info=True)
            self._finish()

    def _emit(self, ref) -> bool:
        while not self._stop.is_set():
            try:
                self.out_queue.put(ref, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _finish(self):
        while not self._stop.is_set():
            try:
                self.out_queue.put(_SENTINEL, timeout=0.1)
                return
            except queue.Full:
                continue


def build_ops(logical_ops: List) -> List[Op]:
    """Lower an OPTIMIZED logical plan into physical ops, one per node —
    map fusion already ran as an optimizer rule (logical.py MapFusion),
    so the physical stage count equals the logical node count."""
    ops: List[Op] = []
    for node in logical_ops:
        if node.kind == "map":
            ops.append(MapOp(node.name or "map", [node.fn],
                             compute=node.opts.get("compute"),
                             concurrency=node.opts.get("concurrency")))
        else:
            ops.append(AllToAllOp(node.name or "exchange", node.fn))
    return ops
