"""Experimental APIs (reference: python/ray/experimental/)."""

from typing import List, Optional


def push_object(ref, node_ids: Optional[List[str]] = None,
                timeout: float = 600.0) -> int:
    """Owner-initiated broadcast of a plasma object to other nodes
    (reference: src/ray/object_manager/push_manager.cc). The source
    raylet streams chunks down a binary forwarding tree, so source
    egress stays O(2 x object size) regardless of receiver count and
    tree levels transfer in parallel. Returns the number of receivers.

    `node_ids=None` pushes to every other alive node. Subsequent
    `ray.get` of the ref on those nodes hits the local store.
    """
    from .._internal.core_worker import get_core_worker

    worker = get_core_worker()
    oid = ref.id()
    entry = worker.memory_store.get_entry(oid)
    if entry is not None and not entry.in_plasma:
        raise ValueError(
            "push_object requires a plasma (shared-memory) object; this "
            "ref resolves to a small in-process value")
    raylet = worker.clients.get(worker.raylet_address)
    reply = raylet.call_sync("push_object", object_hex=oid.hex(),
                             target_node_ids=node_ids, timeout=timeout)
    if not reply.get("ok"):
        raise RuntimeError(f"push_object failed: {reply.get('error')}")
    return reply.get("receivers", 0)


from .device_objects import (DeviceObjectDescriptor, device_get,  # noqa: E402,F401
                             device_put_ref)
