"""Channels: fixed-topology data paths between processes
(reference: python/ray/experimental/channel/ —
shared_memory_channel.py (mutable plasma objects), intra_process_channel.py,
communicator.py ABC; the accelerator channel
torch_tensor_accelerator_channel.py:49 maps here to keeping tensors
device-resident and passing only control tokens).

SharedMemoryChannel: single-producer single-consumer seqlock ring over one
mmap file in /dev/shm — write payload, then bump the 8-byte aligned write
counter; the reader acks by matching its counter. No RPC, no allocation,
no serialization of the channel itself — this is the per-step hot path of
a compiled DAG, where the task RPC plane would dominate the microsecond
budget."""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, Optional

from .._internal import serialization

_HEADER = struct.Struct("<QQQ")  # write_seq, ack_seq, payload_len
HEADER_SIZE = _HEADER.size


class ChannelTimeoutError(TimeoutError):
    pass


class ChannelClosedError(RuntimeError):
    pass


class DagTaskError(RuntimeError):
    """A bound method raised inside a compiled DAG; carries the remote
    traceback. Forwarded through channels as a poison pill so the driver
    sees the real error instead of an output timeout."""

    def __init__(self, method: str, traceback_str: str):
        super().__init__(f"DAG task {method} failed:\n{traceback_str}")
        self.method = method
        self.traceback_str = traceback_str

    def __reduce__(self):
        return (DagTaskError, (self.method, self.traceback_str))


_CLOSE_SENTINEL = (1 << 64) - 1


class SharedMemoryChannel:
    """One-slot SPSC channel backed by an mmap file.

    Writer: wait until the previous payload is acked, write, bump
    write_seq. Reader: wait for write_seq to advance, read, bump ack_seq.
    The single 8-byte aligned counter store is the publication point.
    """

    def __init__(self, path: str, capacity: int = 8 * 1024 * 1024,
                 create: bool = False):
        self.path = path
        self.capacity = capacity
        if create:
            fd = os.open(path, os.O_CREAT | os.O_RDWR, 0o600)
            os.ftruncate(fd, HEADER_SIZE + capacity)
        else:
            deadline = time.monotonic() + 30
            while not os.path.exists(path):
                if time.monotonic() > deadline:
                    raise FileNotFoundError(path)
                time.sleep(0.005)
            fd = os.open(path, os.O_RDWR)
            self.capacity = os.fstat(fd).st_size - HEADER_SIZE
        try:
            self._mm = mmap.mmap(fd, HEADER_SIZE + self.capacity)
        finally:
            os.close(fd)
        self._view = memoryview(self._mm)

    # -- low-level header access ------------------------------------------

    def _read_header(self):
        return _HEADER.unpack_from(self._view, 0)

    def _set_write_seq(self, seq: int):
        struct.pack_into("<Q", self._view, 0, seq)

    def _set_ack_seq(self, seq: int):
        struct.pack_into("<Q", self._view, 8, seq)

    def _set_len(self, n: int):
        struct.pack_into("<Q", self._view, 16, n)

    # -- API ---------------------------------------------------------------

    def put(self, value: Any, timeout: Optional[float] = 10.0):
        sobj = serialization.serialize(value)
        total = sobj.total_bytes()
        if total > self.capacity:
            raise ValueError(
                f"value of {total} bytes exceeds channel capacity "
                f"{self.capacity}")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            write_seq, ack_seq, _len = self._read_header()
            if write_seq == _CLOSE_SENTINEL:
                raise ChannelClosedError(self.path)
            if ack_seq == write_seq:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"reader did not consume within {timeout}s")
            time.sleep(0.0001)
        sobj.write_into(self._view[HEADER_SIZE:HEADER_SIZE + total])
        self._set_len(total)
        self._set_write_seq(write_seq + 1)

    def get(self, timeout: Optional[float] = 10.0) -> Any:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            write_seq, ack_seq, length = self._read_header()
            if write_seq == _CLOSE_SENTINEL:
                raise ChannelClosedError(self.path)
            if write_seq > ack_seq:
                break
            if deadline is not None and time.monotonic() > deadline:
                raise ChannelTimeoutError(
                    f"no value within {timeout}s on {self.path}")
            time.sleep(0.0001)
        # Copy out before acking: deserialize_from_buffer keeps zero-copy
        # views, and the writer reuses the slot immediately after the ack.
        payload = bytes(self._view[HEADER_SIZE:HEADER_SIZE + length])
        value = serialization.deserialize_from_buffer(memoryview(payload))
        self._set_ack_seq(write_seq)
        return value

    def close(self):
        try:
            self._set_write_seq(_CLOSE_SENTINEL)
        except (ValueError, OSError):
            pass

    def destroy(self):
        self.close()
        try:
            self._view.release()
            self._mm.close()
        except (BufferError, ValueError):
            pass
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass

    def __reduce__(self):
        return (SharedMemoryChannel, (self.path, self.capacity, False))


class IntraProcessChannel:
    """Same-process channel (reference: intra_process_channel.py)."""

    def __init__(self):
        import queue
        self._q = queue.Queue(maxsize=1)
        self._closed = False

    def put(self, value: Any, timeout: Optional[float] = 10.0):
        if self._closed:
            raise ChannelClosedError("intra-process channel closed")
        self._q.put(value, timeout=timeout)

    def get(self, timeout: Optional[float] = 10.0) -> Any:
        import queue
        try:
            value = self._q.get(timeout=timeout)
        except queue.Empty:
            if self._closed:
                raise ChannelClosedError("intra-process channel closed")
            raise ChannelTimeoutError("no value")
        return value

    def close(self):
        self._closed = True

    def destroy(self):
        self.close()


class DeviceChannel:
    """SPSC channel for device-resident jax.Arrays (reference:
    experimental/channel/torch_tensor_accelerator_channel.py:49 — NCCL
    P2P between pinned actors; here PJRT cross-runtime DMA via
    jax.experimental.transfer, which rides ICI/DCN on TPU).

    Control tokens (transfer address + uuid + aval) ride a tiny
    SharedMemoryChannel; the array payload moves runtime-to-runtime and
    never touches host shared memory. Constructed on the writer, shipped
    to the reader by pickling (like SharedMemoryChannel).
    """

    # Arrays kept staged until overwritten. The ctrl channel is a
    # ONE-SLOT SPSC (put blocks until the reader ACKS the previous
    # message, whatever the byte capacity), so a writer can be at most
    # ~2 entries ahead of the reader's payload pull — the RPC-fallback
    # unstage below can never evict an entry the reader still needs.
    _PIN_DEPTH = 4

    def __init__(self, path: str, _role: str = "writer"):
        self._ctrl = SharedMemoryChannel(path, capacity=1 << 16,
                                         create=(_role == "writer"))
        self._path = path
        self._role = _role
        self._uuid = int.from_bytes(os.urandom(4), "big") << 16
        self._staged = []   # writer: [(uuid, array)] keep-alive window
        self._conn = None   # reader: TransferConnection to the writer

    def put(self, array, timeout: Optional[float] = 10.0):
        from . import device_objects as dobj
        server = dobj._ensure_server()
        # Staged arrays hold HBM until overwritten: account them against
        # the same process budget as device_put_ref pins so a fast writer
        # backpressures instead of silently growing the keep-alive window
        # (reference: gpu_object_manager's producer/consumer accounting).
        nbytes = int(array.nbytes)
        if not dobj.reserve_bytes(nbytes, timeout):
            raise TimeoutError(
                "DeviceChannel.put blocked on the device-object HBM "
                f"budget for {timeout}s (pinned={dobj.pinned_bytes()}B)")
        self._uuid += 1
        if server is not None:
            server.await_pull(self._uuid, [array])
            addr = dobj._server_addr
            rpc_addr = None
        else:
            # No transfer API in this runtime: stage for the chunked
            # RPC pull (still no host shared memory for the payload).
            dobj.stage_rpc(self._uuid, array)
            addr = ""
            from .._internal.core_worker import get_core_worker
            rpc_addr = tuple(get_core_worker().rpc_address)
        self._staged.append((self._uuid, array, nbytes))
        if len(self._staged) > self._PIN_DEPTH:
            old_uuid, _, old_bytes = self._staged.pop(0)
            dobj.release_bytes(old_bytes)
            if server is None:
                dobj.unstage_rpc(old_uuid)
        self._ctrl.put((addr, rpc_addr, self._uuid,
                        tuple(array.shape), str(array.dtype)), timeout)

    def get(self, timeout: Optional[float] = 10.0):
        import jax
        import numpy as np

        from . import device_objects as dobj
        addr, rpc_addr, uuid, shape, dtype = self._ctrl.get(timeout)
        if not addr:
            return self._rpc_get(rpc_addr, uuid, shape, dtype)
        server = dobj._ensure_server()
        if server is None:
            raise RuntimeError(
                "writer published a PJRT transfer address but this "
                "process's jax has no transfer API")
        if self._conn is None:
            self._conn = server.connect(addr)
        spec = jax.ShapeDtypeStruct(
            shape, np.dtype(dtype),
            sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]))
        return self._conn.pull(uuid, [spec])[0]

    def _rpc_get(self, rpc_addr, uuid, shape, dtype):
        import numpy as np

        from . import device_objects as dobj
        from .._internal.core_worker import get_core_worker

        client = get_core_worker().clients.get(tuple(rpc_addr))
        nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
        return dobj._chunk_pull(client, "device_object_fetch_staged",
                                nbytes, dtype, shape, uuid=uuid)

    def close(self):
        self._ctrl.close()

    def destroy(self):
        if self._staged:
            from . import device_objects as dobj
            for uuid, _, nbytes in self._staged:
                dobj.release_bytes(nbytes)
                dobj.unstage_rpc(uuid)
        self._staged.clear()
        self._ctrl.destroy()

    def __reduce__(self):
        return (DeviceChannel, (self._path, "reader"))
