"""Device-resident objects: jax.Arrays that stay in accelerator memory
with only a control-plane descriptor crossing the object store.

Role of the reference's GPU objects
(python/ray/experimental/gpu_object_manager/gpu_object_manager.py:61 —
tensors live on-device, Ray carries refs; collective/NIXL transports move
them device-to-device). TPU-native design:

- `device_put_ref(array)` in the producing actor pins the array in a
  process-local store and returns an ObjectRef OWNED BY THE PRODUCER
  whose control-plane value is a tiny `DeviceObjectDescriptor`. The
  array itself never leaves HBM and never touches /dev/shm.
- `device_get(ref)` anywhere resolves the descriptor (normal object
  path: bytes-sized), then pulls the array runtime-to-runtime through
  `jax.experimental.transfer` (PJRT cross-host DMA — ICI/DCN on TPU) —
  or returns the pinned array directly when the consumer IS the
  producer process.
- Lifetime rides the existing borrower protocol: consumers hold borrows
  of the producer-owned descriptor; when the last ref drops, the
  producer's `_free_owned_object` fires `on_free` and the pin is
  released.

Transport selection: `jax.experimental.transfer` (PJRT cross-runtime
DMA) when the installed jax has it; otherwise a chunked RPC pull over
the native ring (`device_object_fetch`) — the payload still never
touches the object store or /dev/shm (the property the zero-copy tests
pin), it just rides the worker's socket instead of the PJRT transport.
A descriptor with an empty `transfer_addr` means "pull me over RPC".
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from .._internal.ids import ObjectID
from .._internal.object_ref import ObjectRef

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_cond = threading.Condition(_lock)
_pinned: Dict[ObjectID, Any] = {}          # oid -> jax.Array (producer)
_pinned_nbytes: Dict[ObjectID, int] = {}
_accounted_bytes = [0]                     # pins + channel staging
_server = None                             # this process's TransferServer
_server_addr: Optional[str] = None
_next_uuid = [1]
_conns: Dict[str, Any] = {}                # addr -> TransferConnection


def _build_device_object_metrics():
    from types import SimpleNamespace

    from ..util.metrics import Counter, Gauge
    return SimpleNamespace(
        pinned_bytes=Gauge(
            "rtpu_device_object_pinned_bytes",
            "HBM bytes pinned for device-resident objects "
            "(device_put_ref + DeviceChannel staging)"),
        pulls=Counter(
            "rtpu_device_object_pulls_total",
            "Runtime-to-runtime device-object pulls started by this "
            "process"),
        pull_bytes=Counter(
            "rtpu_device_object_pull_bytes_total",
            "Bytes moved by runtime-to-runtime device-object pulls"),
    )


from ..util.metrics import LazyMetrics  # noqa: E402 — after _build def

_metrics = LazyMetrics(_build_device_object_metrics)


def _update_gauge():
    try:
        _metrics().pinned_bytes.set(float(_accounted_bytes[0]))
    except Exception:  # noqa: BLE001 — metrics best-effort
        logger.debug("pinned-bytes gauge update failed", exc_info=True)


def pinned_bytes() -> int:
    """HBM bytes currently accounted (pins + channel staging)."""
    with _lock:
        return _accounted_bytes[0]


def reserve_bytes(nbytes: int, timeout_s: Optional[float] = None) -> bool:
    """Backpressure gate: block until `nbytes` fits under the HBM budget
    (CONFIG.device_object_hbm_budget; 0 = unlimited). Returns False on
    timeout — callers then spill to host instead of OOMing HBM, and the
    exhaustion is published as a DEVICE_MEMORY_PRESSURE event (silent
    degradation made a slow pipeline look healthy while every pin was
    detouring through the host store)."""
    from .._internal.config import CONFIG
    budget = CONFIG.device_object_hbm_budget
    if timeout_s is None:
        timeout_s = CONFIG.device_object_backpressure_timeout_s
    held = 0
    with _cond:
        if not budget:
            _accounted_bytes[0] += nbytes
            _update_gauge()
            return True
        import time as _time
        deadline = _time.monotonic() + timeout_s
        ok = True
        while _accounted_bytes[0] + nbytes > budget:
            remaining = deadline - _time.monotonic()
            if remaining <= 0 or nbytes > budget:
                ok = False
                held = _accounted_bytes[0]
                break
            _cond.wait(remaining)
        if ok:
            _accounted_bytes[0] += nbytes
            _update_gauge()
            return True
    # Emission OUTSIDE the condition lock: it is a (best-effort,
    # bounded) GCS RPC from this user thread.
    from .._internal import accel
    accel.emit_pressure_event(
        f"device-object HBM budget exhausted: {nbytes} B requested, "
        f"{held}/{budget} B pinned after {timeout_s:g}s — spilling "
        "to host object store",
        fields={"requested_bytes": nbytes, "pinned_bytes": held,
                "budget_bytes": budget, "source": "device_objects"})
    return False


def release_bytes(nbytes: int):
    with _cond:
        _accounted_bytes[0] = max(0, _accounted_bytes[0] - nbytes)
        _update_gauge()
        _cond.notify_all()


@dataclass
class DeviceObjectDescriptor:
    object_hex: str
    transfer_addr: str          # producer's TransferServer address
    producer_rpc_addr: Tuple[str, int]
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int


# Chunk size for the RPC-fallback pull: big enough to amortize the
# per-call overhead, small enough that a 1 GiB array never builds a
# frame near the ring's 1 GiB oversized-prefix guard.
_FETCH_CHUNK = 64 * 1024 * 1024

_transfer_mod = [None]  # [None]=unprobed, [False]=unavailable, [module]


def _transfer_module():
    if _transfer_mod[0] is None:
        try:
            from jax.experimental import transfer
            _transfer_mod[0] = transfer
        except ImportError:
            # older jax (e.g. 0.4.x): no PJRT transfer API — the RPC
            # fallback transport takes over
            _transfer_mod[0] = False
    return _transfer_mod[0] or None


def _ensure_server():
    """The PJRT transfer server, or None when the installed jax has no
    transfer API (consumers then pull over RPC)."""
    global _server, _server_addr
    transfer = _transfer_module()
    if transfer is None:
        return None
    with _lock:
        if _server is None:
            import jax
            client = jax.devices()[0].client
            # A bulk-transport address is REQUIRED for cross-process
            # pulls (the default server only short-circuits locally).
            host = os.environ.get("RTPU_TRANSFER_HOST", "127.0.0.1")
            _server = transfer.start_transfer_server(
                client, f"{host}:0", [f"{host}:0"])
            _server_addr = _server.address()
        return _server


def device_put_ref(array, *, timeout_s: Optional[float] = None
                   ) -> ObjectRef:
    """Pin `array` on-device in this process and return a control-plane
    ref to it. Call inside the producing actor; return the ref (or a
    structure containing it) to consumers.

    HBM accounting: pins count against
    CONFIG.device_object_hbm_budget. When producers outrun consumers the
    call BLOCKS (up to device_object_backpressure_timeout_s) for frees,
    then falls back to spilling the array to the host object store — the
    returned ref then resolves through the normal object path and
    device_get re-devices it (reference: gpu_object_manager.py:61)."""
    import numpy as np

    from .._internal.core_worker import get_core_worker

    worker = get_core_worker()
    nbytes = int(array.nbytes)
    if not reserve_bytes(nbytes, timeout_s):
        # Budget exhausted: spill to host instead of risking HBM OOM.
        import ray_tpu
        return ray_tpu.put(np.asarray(array))
    _ensure_server()
    oid = ObjectID.from_random()
    with _lock:
        _pinned[oid] = array
        _pinned_nbytes[oid] = nbytes
    desc = DeviceObjectDescriptor(
        object_hex=oid.hex(), transfer_addr=_server_addr or "",
        producer_rpc_addr=tuple(worker.rpc_address),
        shape=tuple(array.shape), dtype=str(np.dtype(array.dtype)),
        nbytes=nbytes)
    worker.reference_counter.add_owned(oid)
    worker.memory_store.put(oid, desc)
    _register_free_hook()
    return ObjectRef(oid, worker.rpc_address)


def device_get(ref: ObjectRef):
    """Resolve a device-object ref to a jax.Array in THIS process's
    runtime. Same-process: the pinned array itself (zero copy). Remote:
    a runtime-to-runtime pull via jax.experimental.transfer — no host
    shared-memory file is ever written."""
    import ray_tpu

    oid = ref.id()
    with _lock:
        local = _pinned.get(oid)
    if local is not None:
        return local
    return resolve_control(ray_tpu.get(ref), ref)


def resolve_control(control, ref=None):
    """The device_get tail for a caller that already fetched the ref's
    control-plane value (saves the duplicate ray_tpu.get per hop on hot
    paths like the MPMD pipeline's activation resolve)."""
    if isinstance(control, DeviceObjectDescriptor):
        return _pull(control)
    import numpy as np
    if isinstance(control, np.ndarray):
        # producer spilled to host under HBM backpressure — re-device
        import jax.numpy as jnp
        return jnp.asarray(control)
    raise TypeError(f"{ref if ref is not None else 'control value'} is "
                    f"not a device object (got {type(control).__name__})")


def _pull(desc: DeviceObjectDescriptor):
    import jax
    import numpy as np

    from .._internal.core_worker import get_core_worker

    metrics = _metrics()
    metrics.pulls.inc()
    metrics.pull_bytes.inc(desc.nbytes)
    worker = get_core_worker()
    client = worker.clients.get(tuple(desc.producer_rpc_addr))
    if not desc.transfer_addr:
        return _rpc_pull(desc, client)
    server = _ensure_server()
    if server is None:
        # Producer published a transfer address this process cannot
        # dial (no transfer API here) — fall back to the RPC pull.
        return _rpc_pull(desc, client)
    # Ask the producer to stage the array for one pull under a fresh
    # uuid (await_pull is single-shot; N consumers = N stagings).
    reply = client.call_sync("device_object_stage",
                             object_hex=desc.object_hex, timeout=120)
    if not reply.get("ok"):
        raise RuntimeError(
            f"device object {desc.object_hex[:12]} unavailable: "
            f"{reply.get('error')}")
    uuid = reply["uuid"]
    with _lock:
        conn = _conns.get(desc.transfer_addr)
        if conn is None:
            conn = server.connect(desc.transfer_addr)
            _conns[desc.transfer_addr] = conn
    spec = jax.ShapeDtypeStruct(
        desc.shape, np.dtype(desc.dtype),
        sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]))
    out = conn.pull(uuid, [spec])
    return out[0]


def _chunk_pull(client, method: str, nbytes: int, dtype: str, shape,
                **ids):
    """Consumer half of the RPC-fallback transport, shared by the
    descriptor pull and DeviceChannel: bounded chunks (every frame far
    below the ring's 1 GiB guard), one host->device copy at the end."""
    import jax.numpy as jnp
    import numpy as np

    buf = bytearray(nbytes)
    offset = 0
    while offset < nbytes:
        length = min(_FETCH_CHUNK, nbytes - offset)
        reply = client.call_sync(method, offset=offset, length=length,
                                 timeout=120, **ids)
        if not reply.get("ok"):
            raise RuntimeError(
                f"device object chunk pull ({method} {ids}) failed: "
                f"{reply.get('error')}")
        data = reply["data"]
        buf[offset:offset + len(data)] = data
        offset += len(data)
    # frombuffer over the bytearray is a zero-copy view; jnp.asarray is
    # the single host->device copy (2x nbytes peak, not 3x).
    return jnp.asarray(np.frombuffer(buf, dtype=np.dtype(dtype))
                       .reshape(shape))


def _rpc_pull(desc: DeviceObjectDescriptor, client):
    """Fallback transport: pull the pinned array in bounded chunks over
    the producer's RPC ring. The payload never enters the object store
    or /dev/shm (and peak staging memory on the producer stays one host
    copy of the array)."""
    return _chunk_pull(client, "device_object_fetch", desc.nbytes,
                       desc.dtype, desc.shape,
                       object_hex=desc.object_hex)


# -- producer-side plumbing -------------------------------------------------

def _stage_for_pull(object_hex: str) -> Dict[str, Any]:
    """RPC handler body: stage one pull of a pinned array."""
    oid = ObjectID.from_hex(object_hex)
    with _lock:
        array = _pinned.get(oid)
        if array is None:
            return {"ok": False, "error": "not pinned in this process"}
        uuid = _next_uuid[0]
        _next_uuid[0] += 1
    _ensure_server().await_pull(uuid, [array])
    return {"ok": True, "uuid": uuid}


# uuid -> jax.Array staged for RPC-fallback DeviceChannel pulls
_rpc_staged: Dict[int, Any] = {}

# ("pin", oid) / ("staged", uuid) -> flat uint8 host view of an array
# mid-chunk-pull: ONE device->host materialization per pull sequence,
# not per chunk (np.asarray of a 1 GiB array for each 64 MiB chunk was
# O(nbytes^2/chunk)). Evicted when the last chunk is served, on free,
# and on unstage, so a dead consumer can't pin a host copy forever.
_host_views: Dict[Any, Any] = {}


def _chunk_of(key, array, offset: int, length: int) -> Dict[str, Any]:
    import numpy as np

    with _lock:
        flat = _host_views.get(key)
    if flat is None:
        flat = np.asarray(array).reshape(-1).view(np.uint8)
        with _lock:
            _host_views[key] = flat
    data = flat[offset:offset + length].tobytes()
    if offset + length >= flat.size:
        with _lock:
            _host_views.pop(key, None)
    return {"ok": True, "data": data}


def _fetch_chunk(object_hex: str, offset: int, length: int
                 ) -> Dict[str, Any]:
    """RPC handler body: one bounded chunk of a pinned array (the
    fallback transport — no jax transfer API in this runtime)."""
    oid = ObjectID.from_hex(object_hex)
    with _lock:
        array = _pinned.get(oid)
    if array is None:
        return {"ok": False, "error": "not pinned in this process"}
    return _chunk_of(("pin", oid), array, offset, length)


def _fetch_staged_chunk(uuid: int, offset: int, length: int
                        ) -> Dict[str, Any]:
    """Same, for DeviceChannel's keep-alive staging window."""
    with _lock:
        array = _rpc_staged.get(uuid)
    if array is None:
        return {"ok": False, "error": "not staged (window advanced?)"}
    return _chunk_of(("staged", uuid), array, offset, length)


def stage_rpc(uuid: int, array) -> None:
    """DeviceChannel writer-side staging for the RPC fallback."""
    ensure_handlers()
    with _lock:
        _rpc_staged[uuid] = array


def unstage_rpc(uuid: int) -> None:
    with _lock:
        _rpc_staged.pop(uuid, None)
        _host_views.pop(("staged", uuid), None)


_hook_installed = False


def _register_free_hook():
    """Install the RPC handlers + free callback on this process's
    worker."""
    global _hook_installed
    if _hook_installed:
        return
    from .._internal.core_worker import get_core_worker

    worker = get_core_worker()

    async def handle_device_object_stage(object_hex: str):
        return _stage_for_pull(object_hex)

    async def handle_device_object_fetch(object_hex: str, offset: int,
                                         length: int):
        return _fetch_chunk(object_hex, offset, length)

    async def handle_device_object_fetch_staged(uuid: int, offset: int,
                                                length: int):
        return _fetch_staged_chunk(uuid, offset, length)

    worker.server.register("device_object_stage", handle_device_object_stage)
    worker.server.register("device_object_fetch", handle_device_object_fetch)
    worker.server.register("device_object_fetch_staged",
                           handle_device_object_fetch_staged)
    worker.device_object_free_hooks.append(on_free)
    _hook_installed = True


def ensure_handlers():
    """Public alias: DeviceChannel's RPC-fallback writer needs the
    fetch handlers installed without pinning an object ref."""
    _register_free_hook()


def on_free(object_id: ObjectID):
    with _lock:
        _pinned.pop(object_id, None)
        _host_views.pop(("pin", object_id), None)
        nbytes = _pinned_nbytes.pop(object_id, 0)
    if nbytes:
        release_bytes(nbytes)


def num_pinned() -> int:
    with _lock:
        return len(_pinned)
