"""Device-resident objects: jax.Arrays that stay in accelerator memory
with only a control-plane descriptor crossing the object store.

Role of the reference's GPU objects
(python/ray/experimental/gpu_object_manager/gpu_object_manager.py:61 —
tensors live on-device, Ray carries refs; collective/NIXL transports move
them device-to-device). TPU-native design:

- `device_put_ref(array)` in the producing actor pins the array in a
  process-local store and returns an ObjectRef OWNED BY THE PRODUCER
  whose control-plane value is a tiny `DeviceObjectDescriptor`. The
  array itself never leaves HBM and never touches /dev/shm.
- `device_get(ref)` anywhere resolves the descriptor (normal object
  path: bytes-sized), then pulls the array runtime-to-runtime through
  `jax.experimental.transfer` (PJRT cross-host DMA — ICI/DCN on TPU) —
  or returns the pinned array directly when the consumer IS the
  producer process.
- Lifetime rides the existing borrower protocol: consumers hold borrows
  of the producer-owned descriptor; when the last ref drops, the
  producer's `_free_owned_object` fires `on_free` and the pin is
  released.
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

from .._internal.ids import ObjectID
from .._internal.object_ref import ObjectRef

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_cond = threading.Condition(_lock)
_pinned: Dict[ObjectID, Any] = {}          # oid -> jax.Array (producer)
_pinned_nbytes: Dict[ObjectID, int] = {}
_accounted_bytes = [0]                     # pins + channel staging
_server = None                             # this process's TransferServer
_server_addr: Optional[str] = None
_next_uuid = [1]
_conns: Dict[str, Any] = {}                # addr -> TransferConnection


def _build_device_object_metrics():
    from types import SimpleNamespace

    from ..util.metrics import Counter, Gauge
    return SimpleNamespace(
        pinned_bytes=Gauge(
            "rtpu_device_object_pinned_bytes",
            "HBM bytes pinned for device-resident objects "
            "(device_put_ref + DeviceChannel staging)"),
        pulls=Counter(
            "rtpu_device_object_pulls_total",
            "Runtime-to-runtime device-object pulls started by this "
            "process"),
        pull_bytes=Counter(
            "rtpu_device_object_pull_bytes_total",
            "Bytes moved by runtime-to-runtime device-object pulls"),
    )


from ..util.metrics import LazyMetrics  # noqa: E402 — after _build def

_metrics = LazyMetrics(_build_device_object_metrics)


def _update_gauge():
    try:
        _metrics().pinned_bytes.set(float(_accounted_bytes[0]))
    except Exception:  # noqa: BLE001 — metrics best-effort
        logger.debug("pinned-bytes gauge update failed", exc_info=True)


def pinned_bytes() -> int:
    """HBM bytes currently accounted (pins + channel staging)."""
    with _lock:
        return _accounted_bytes[0]


def reserve_bytes(nbytes: int, timeout_s: Optional[float] = None) -> bool:
    """Backpressure gate: block until `nbytes` fits under the HBM budget
    (CONFIG.device_object_hbm_budget; 0 = unlimited). Returns False on
    timeout — callers then spill to host instead of OOMing HBM, and the
    exhaustion is published as a DEVICE_MEMORY_PRESSURE event (silent
    degradation made a slow pipeline look healthy while every pin was
    detouring through the host store)."""
    from .._internal.config import CONFIG
    budget = CONFIG.device_object_hbm_budget
    if timeout_s is None:
        timeout_s = CONFIG.device_object_backpressure_timeout_s
    held = 0
    with _cond:
        if not budget:
            _accounted_bytes[0] += nbytes
            _update_gauge()
            return True
        import time as _time
        deadline = _time.monotonic() + timeout_s
        ok = True
        while _accounted_bytes[0] + nbytes > budget:
            remaining = deadline - _time.monotonic()
            if remaining <= 0 or nbytes > budget:
                ok = False
                held = _accounted_bytes[0]
                break
            _cond.wait(remaining)
        if ok:
            _accounted_bytes[0] += nbytes
            _update_gauge()
            return True
    # Emission OUTSIDE the condition lock: it is a (best-effort,
    # bounded) GCS RPC from this user thread.
    from .._internal import accel
    accel.emit_pressure_event(
        f"device-object HBM budget exhausted: {nbytes} B requested, "
        f"{held}/{budget} B pinned after {timeout_s:g}s — spilling "
        "to host object store",
        fields={"requested_bytes": nbytes, "pinned_bytes": held,
                "budget_bytes": budget, "source": "device_objects"})
    return False


def release_bytes(nbytes: int):
    with _cond:
        _accounted_bytes[0] = max(0, _accounted_bytes[0] - nbytes)
        _update_gauge()
        _cond.notify_all()


@dataclass
class DeviceObjectDescriptor:
    object_hex: str
    transfer_addr: str          # producer's TransferServer address
    producer_rpc_addr: Tuple[str, int]
    shape: Tuple[int, ...]
    dtype: str
    nbytes: int


def _ensure_server():
    global _server, _server_addr
    with _lock:
        if _server is None:
            import jax
            from jax.experimental import transfer
            client = jax.devices()[0].client
            # A bulk-transport address is REQUIRED for cross-process
            # pulls (the default server only short-circuits locally).
            host = os.environ.get("RTPU_TRANSFER_HOST", "127.0.0.1")
            _server = transfer.start_transfer_server(
                client, f"{host}:0", [f"{host}:0"])
            _server_addr = _server.address()
        return _server


def device_put_ref(array, *, timeout_s: Optional[float] = None
                   ) -> ObjectRef:
    """Pin `array` on-device in this process and return a control-plane
    ref to it. Call inside the producing actor; return the ref (or a
    structure containing it) to consumers.

    HBM accounting: pins count against
    CONFIG.device_object_hbm_budget. When producers outrun consumers the
    call BLOCKS (up to device_object_backpressure_timeout_s) for frees,
    then falls back to spilling the array to the host object store — the
    returned ref then resolves through the normal object path and
    device_get re-devices it (reference: gpu_object_manager.py:61)."""
    import numpy as np

    from .._internal.core_worker import get_core_worker

    worker = get_core_worker()
    nbytes = int(array.nbytes)
    if not reserve_bytes(nbytes, timeout_s):
        # Budget exhausted: spill to host instead of risking HBM OOM.
        import ray_tpu
        return ray_tpu.put(np.asarray(array))
    _ensure_server()
    oid = ObjectID.from_random()
    with _lock:
        _pinned[oid] = array
        _pinned_nbytes[oid] = nbytes
    desc = DeviceObjectDescriptor(
        object_hex=oid.hex(), transfer_addr=_server_addr,
        producer_rpc_addr=tuple(worker.rpc_address),
        shape=tuple(array.shape), dtype=str(np.dtype(array.dtype)),
        nbytes=nbytes)
    worker.reference_counter.add_owned(oid)
    worker.memory_store.put(oid, desc)
    _register_free_hook()
    return ObjectRef(oid, worker.rpc_address)


def device_get(ref: ObjectRef):
    """Resolve a device-object ref to a jax.Array in THIS process's
    runtime. Same-process: the pinned array itself (zero copy). Remote:
    a runtime-to-runtime pull via jax.experimental.transfer — no host
    shared-memory file is ever written."""
    import ray_tpu

    oid = ref.id()
    with _lock:
        local = _pinned.get(oid)
    if local is not None:
        return local
    desc = ray_tpu.get(ref)
    if not isinstance(desc, DeviceObjectDescriptor):
        import numpy as np
        if isinstance(desc, np.ndarray):
            # producer spilled to host under HBM backpressure — re-device
            import jax.numpy as jnp
            return jnp.asarray(desc)
        raise TypeError(f"{ref} is not a device object (got "
                        f"{type(desc).__name__})")
    return _pull(desc)


def _pull(desc: DeviceObjectDescriptor):
    import jax
    import numpy as np

    from .._internal.core_worker import get_core_worker

    metrics = _metrics()
    metrics.pulls.inc()
    metrics.pull_bytes.inc(desc.nbytes)
    server = _ensure_server()
    worker = get_core_worker()
    # Ask the producer to stage the array for one pull under a fresh
    # uuid (await_pull is single-shot; N consumers = N stagings).
    client = worker.clients.get(tuple(desc.producer_rpc_addr))
    reply = client.call_sync("device_object_stage",
                             object_hex=desc.object_hex, timeout=120)
    if not reply.get("ok"):
        raise RuntimeError(
            f"device object {desc.object_hex[:12]} unavailable: "
            f"{reply.get('error')}")
    uuid = reply["uuid"]
    with _lock:
        conn = _conns.get(desc.transfer_addr)
        if conn is None:
            conn = server.connect(desc.transfer_addr)
            _conns[desc.transfer_addr] = conn
    spec = jax.ShapeDtypeStruct(
        desc.shape, np.dtype(desc.dtype),
        sharding=jax.sharding.SingleDeviceSharding(jax.devices()[0]))
    out = conn.pull(uuid, [spec])
    return out[0]


# -- producer-side plumbing -------------------------------------------------

def _stage_for_pull(object_hex: str) -> Dict[str, Any]:
    """RPC handler body: stage one pull of a pinned array."""
    oid = ObjectID.from_hex(object_hex)
    with _lock:
        array = _pinned.get(oid)
        if array is None:
            return {"ok": False, "error": "not pinned in this process"}
        uuid = _next_uuid[0]
        _next_uuid[0] += 1
    _ensure_server().await_pull(uuid, [array])
    return {"ok": True, "uuid": uuid}


_hook_installed = False


def _register_free_hook():
    """Install the RPC handler + free callback on this process's worker."""
    global _hook_installed
    if _hook_installed:
        return
    from .._internal.core_worker import get_core_worker

    worker = get_core_worker()

    async def handle_device_object_stage(object_hex: str):
        return _stage_for_pull(object_hex)

    worker.server.register("device_object_stage", handle_device_object_stage)
    worker.device_object_free_hooks.append(on_free)
    _hook_installed = True


def on_free(object_id: ObjectID):
    with _lock:
        _pinned.pop(object_id, None)
        nbytes = _pinned_nbytes.pop(object_id, 0)
    if nbytes:
        release_bytes(nbytes)


def num_pinned() -> int:
    with _lock:
        return len(_pinned)
