"""Job submission (reference: python/ray/dashboard/modules/job/ —
JobManager job_manager.py:60, JobSupervisor job_supervisor.py:56, REST
routes job_head.py; SDK python/ray/job_submission/)."""

from .job_manager import JobManager, JobStatus
from .client import JobSubmissionClient

__all__ = ["JobManager", "JobStatus", "JobSubmissionClient"]
