"""JobSubmissionClient: HTTP SDK against the dashboard REST
(reference: python/ray/job_submission/job_submission_client.py wrapping
dashboard/modules/job REST routes)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional


class JobSubmissionClient:
    def __init__(self, address: str):
        """address: http://host:port of the dashboard."""
        self._base = address.rstrip("/")

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None):
        data = json.dumps(payload).encode() if payload is not None else None
        req = urllib.request.Request(
            self._base + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                body = resp.read()
        except urllib.error.HTTPError as e:
            raise RuntimeError(
                f"{method} {path} -> {e.code}: {e.read().decode()}") from e
        return json.loads(body) if body else None

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        reply = self._request("POST", "/api/jobs/", {
            "entrypoint": entrypoint, "submission_id": submission_id,
            "runtime_env": runtime_env, "metadata": metadata})
        return reply["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        return self._request("GET", f"/api/jobs/{submission_id}")["status"]

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._request("GET", "/api/jobs/")

    def get_job_logs(self, submission_id: str) -> str:
        return self._request("GET",
                             f"/api/jobs/{submission_id}/logs")["logs"]

    def stop_job(self, submission_id: str) -> bool:
        return self._request("POST",
                             f"/api/jobs/{submission_id}/stop")["stopped"]

    def tail_job_logs(self, submission_id: str, interval_s: float = 0.5):
        """Generator yielding new log output until the job finishes."""
        import time
        from .job_manager import JobStatus
        seen = 0
        while True:
            logs = self.get_job_logs(submission_id)
            if len(logs) > seen:
                yield logs[seen:]
                seen = len(logs)
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                rest = self.get_job_logs(submission_id)
                if len(rest) > seen:
                    yield rest[seen:]
                return
            time.sleep(interval_s)
