"""JobManager + JobSupervisor
(reference: dashboard/modules/job/job_manager.py:60 — submit/stop/status/
logs; job_supervisor.py:56 — an actor managing the entrypoint driver
subprocess).

A submitted job = one detached supervisor actor that runs the entrypoint
command as a subprocess (a driver: it may ray_tpu.init() against this
cluster), captures combined output to a log file in the session dir, and
records status transitions in the GCS KV."""

from __future__ import annotations

import json
import os
import time
import uuid
from typing import Any, Dict, List, Optional

JOBS_KV_NS = "jobs_api"


class JobStatus:
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    SUCCEEDED = "SUCCEEDED"
    FAILED = "FAILED"
    STOPPED = "STOPPED"

    TERMINAL = (SUCCEEDED, FAILED, STOPPED)


class _JobSupervisor:
    """Detached actor owning one job's driver subprocess."""

    def __init__(self, submission_id: str, entrypoint: str,
                 log_path: str, env_vars: Optional[Dict[str, str]] = None,
                 working_dir: Optional[str] = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.log_path = log_path
        self.env_vars = env_vars or {}
        self.working_dir = working_dir
        self._proc = None

    def _put_status(self, status: str, message: str = ""):
        from .._internal.core_worker import get_core_worker
        worker = get_core_worker()
        raw = worker.gcs.get(JOBS_KV_NS, self.submission_id)
        record = json.loads(raw.decode()) if raw else {}
        record.update(status=status, message=message,
                      end_time=time.time()
                      if status in JobStatus.TERMINAL else None)
        worker.gcs.put(JOBS_KV_NS, self.submission_id,
                       json.dumps(record).encode())

    def run(self) -> str:
        """Blocks until the entrypoint exits; returns the final status."""
        import subprocess
        env = dict(os.environ)
        env.update(self.env_vars)
        env["RTPU_JOB_SUBMISSION_ID"] = self.submission_id
        self._put_status(JobStatus.RUNNING)
        os.makedirs(os.path.dirname(self.log_path), exist_ok=True)
        with open(self.log_path, "ab") as log:
            self._proc = subprocess.Popen(
                self.entrypoint, shell=True, stdout=log,
                stderr=subprocess.STDOUT, env=env,
                cwd=self.working_dir or None)
            rc = self._proc.wait()
        if rc == 0:
            self._put_status(JobStatus.SUCCEEDED)
            return JobStatus.SUCCEEDED
        if rc < 0:  # killed by signal (stop_job)
            self._put_status(JobStatus.STOPPED,
                             f"terminated by signal {-rc}")
            return JobStatus.STOPPED
        self._put_status(JobStatus.FAILED, f"entrypoint exited rc={rc}")
        return JobStatus.FAILED

    def stop(self) -> bool:
        if self._proc is not None and self._proc.poll() is None:
            self._proc.terminate()
            return True
        return False

    def ping(self):
        return True


class JobManager:
    """Driver/head-side job orchestration; the dashboard REST wraps this."""

    def __init__(self):
        from .._internal.core_worker import get_core_worker
        self._worker = get_core_worker()

    def _log_path(self, submission_id: str) -> str:
        from .._internal import api as api_mod
        node = api_mod._local_node
        base = node.session_dir if node is not None else "/tmp/rtpu-jobs"
        return os.path.join(base, "job-logs", f"{submission_id}.log")

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   runtime_env: Optional[Dict[str, Any]] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        import ray_tpu
        submission_id = submission_id or \
            f"rtpu-job-{uuid.uuid4().hex[:10]}"
        if self._worker.gcs.get(JOBS_KV_NS, submission_id) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        runtime_env = runtime_env or {}
        log_path = self._log_path(submission_id)
        record = {
            "submission_id": submission_id, "entrypoint": entrypoint,
            "status": JobStatus.PENDING, "message": "",
            "start_time": time.time(), "end_time": None,
            "metadata": metadata or {}, "log_path": log_path,
            "runtime_env": {k: v for k, v in runtime_env.items()
                            if k in ("env_vars", "working_dir")},
        }
        self._worker.gcs.put(JOBS_KV_NS, submission_id,
                             json.dumps(record).encode())
        supervisor_cls = ray_tpu.remote(_JobSupervisor)
        supervisor = supervisor_cls.options(
            name=f"_job_supervisor_{submission_id}", lifetime="detached",
            namespace="_jobs", num_cpus=0, max_concurrency=4,
        ).remote(submission_id, entrypoint, log_path,
                 env_vars=runtime_env.get("env_vars"),
                 working_dir=runtime_env.get("working_dir"))
        supervisor.run.remote()  # fire and track via KV status
        return submission_id

    def get_job_status(self, submission_id: str) -> Optional[str]:
        info = self.get_job_info(submission_id)
        return info["status"] if info else None

    def get_job_info(self, submission_id: str) -> Optional[Dict[str, Any]]:
        raw = self._worker.gcs.get(JOBS_KV_NS, submission_id)
        return json.loads(raw.decode()) if raw else None

    def list_jobs(self) -> List[Dict[str, Any]]:
        jobs = []
        for key in self._worker.gcs.keys(JOBS_KV_NS, ""):
            info = self.get_job_info(key)
            if info:
                jobs.append(info)
        jobs.sort(key=lambda j: j.get("start_time") or 0)
        return jobs

    def get_job_logs(self, submission_id: str,
                     tail_bytes: Optional[int] = None) -> str:
        info = self.get_job_info(submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        try:
            with open(info["log_path"], "rb") as f:
                if tail_bytes:
                    f.seek(0, os.SEEK_END)
                    size = f.tell()
                    f.seek(max(0, size - tail_bytes))
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def get_job_logs_paged(self, submission_id: str, limit: int = 1000,
                           since: int = 0) -> Dict[str, Any]:
        """Cursor-paginated job logs (the /api/tasks limit/since
        pattern): up to `limit` lines starting at byte offset `since`,
        plus the `cursor` to pass back for the next page. The old
        one-unbounded-string surface stays for small outputs; a
        long-running job's dashboard poll fetches increments instead of
        re-shipping the whole file every tick."""
        info = self.get_job_info(submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        limit = max(1, min(int(limit), 10_000))
        budget = limit * 200 + 65536
        try:
            with open(info["log_path"], "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                since = max(0, min(int(since), size))
                f.seek(since)
                # bounded read: ~200 bytes/line budget + one max-length
                # straggler, NOT the whole remainder
                data = f.read(budget)
        except FileNotFoundError:
            return {"lines": [], "cursor": 0, "eof": True,
                    "total_bytes": 0}
        chunks = data.split(b"\n")
        complete, partial = chunks[:-1], chunks[-1]
        lines = [c.decode(errors="replace") for c in complete[:limit]]
        consumed = sum(len(c) + 1 for c in complete[:limit])
        cursor = since + consumed
        if partial and len(lines) < limit and len(complete) <= limit:
            terminal = info.get("status") in JobStatus.TERMINAL
            if len(data) >= budget and not complete:
                # one line longer than the whole read budget would wedge
                # the cursor forever: serve it as a truncated chunk
                lines.append(partial.decode(errors="replace"))
                cursor += len(partial)
            elif terminal and since + len(data) >= size:
                # finished job whose file lacks a trailing newline: the
                # final partial line is final — deliver it (a RUNNING
                # job's partial stays buffered; it is still being
                # written)
                lines.append(partial.decode(errors="replace"))
                cursor += len(partial)
        return {"lines": lines, "cursor": cursor,
                "eof": cursor >= size,
                "total_bytes": size}

    def stop_job(self, submission_id: str) -> bool:
        import ray_tpu
        info = self.get_job_info(submission_id)
        if info is None or info["status"] in JobStatus.TERMINAL:
            return False
        try:
            supervisor = ray_tpu.get_actor(
                f"_job_supervisor_{submission_id}", namespace="_jobs")
            return ray_tpu.get(supervisor.stop.remote(), timeout=30)
        except ValueError:
            return False

    def wait_until_finished(self, submission_id: str,
                            timeout_s: float = 300.0) -> str:
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in JobStatus.TERMINAL:
                return status
            time.sleep(0.25)
        raise TimeoutError(f"job {submission_id} not finished "
                           f"after {timeout_s}s")
