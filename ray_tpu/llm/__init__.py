"""ray_tpu.llm — native TPU LLM serving
(reference: python/ray/llm — serve deployments wrapping vLLM
llm/_internal/serve/deployments/llm/vllm/; builders
serve/llm/__init__.py:92 build_llm_deployment / :168 build_openai_app).

The reference delegates the engine to vLLM (CUDA); no such engine exists
for TPU, so this package IS the engine (SURVEY §7 step 8): a
continuous-batching decode loop over slot-structured KV caches, jitted
once per shape bucket, deployed behind ray_tpu.serve."""

from .disagg import (PDDecodeServer, PrefillServer, build_pd_disagg_app)
from .engine import EngineConfig, GenerationRequest, LLMEngine
from .openai import ByteTokenizer, OpenAIServer, build_openai_app
from .paged import PagedEngineConfig, PagedLLMEngine
from .radix import RadixPrefixCache
from .serving import LLMServer, build_llm_deployment

__all__ = ["EngineConfig", "GenerationRequest", "LLMEngine",
           "PagedEngineConfig", "PagedLLMEngine", "LLMServer",
           "build_llm_deployment", "OpenAIServer", "build_openai_app",
           "ByteTokenizer", "PrefillServer", "PDDecodeServer",
           "build_pd_disagg_app", "RadixPrefixCache"]
