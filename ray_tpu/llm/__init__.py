"""ray_tpu.llm — native TPU LLM serving
(reference: python/ray/llm — serve deployments wrapping vLLM
llm/_internal/serve/deployments/llm/vllm/; builders
serve/llm/__init__.py:92 build_llm_deployment / :168 build_openai_app).

The reference delegates the engine to vLLM (CUDA); no such engine exists
for TPU, so this package IS the engine (SURVEY §7 step 8): a
continuous-batching decode loop over slot-structured KV caches, jitted
once per shape bucket, deployed behind ray_tpu.serve.

Exports resolve lazily (PEP 562): the engines pull in jax at import
time, but jax-free processes — the serve proxy stamping request-trace
events, the dashboard folding `reqtrace` payloads — must be able to
import this package (and its light submodules) without paying the jax
import."""

_EXPORTS = {
    "EngineConfig": ".engine",
    "GenerationRequest": ".engine",
    "LLMEngine": ".engine",
    "PagedEngineConfig": ".paged",
    "PagedLLMEngine": ".paged",
    "LLMServer": ".serving",
    "build_llm_deployment": ".serving",
    "OpenAIServer": ".openai",
    "build_openai_app": ".openai",
    "ByteTokenizer": ".openai",
    "PrefillServer": ".disagg",
    "PDDecodeServer": ".disagg",
    "build_pd_disagg_app": ".disagg",
    "RadixPrefixCache": ".radix",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    submodule = _EXPORTS.get(name)
    if submodule is None:
        raise AttributeError(
            f"module {__name__!r} has no attribute {name!r}")
    from importlib import import_module
    return getattr(import_module(submodule, __name__), name)


def __dir__():
    return sorted(set(globals()) | set(_EXPORTS))
