"""Prefill/decode disaggregation
(reference: llm/_internal/serve/deployments/prefill_decode_disagg/ —
separate prefill and decode engine pools with KV transfer between them,
so compute-bound prefill and latency-bound decode scale independently).

TPU-native shape: the prefill deployment runs chunked prefill only and
returns the prompt's KV pages + final logits; the decode deployment's
paged engine installs them via `submit_prefilled` (page allocation,
prefix sharing, streaming all behave exactly as with local prefill).
KV moves over the object plane as numpy arrays; on real multi-host
topologies the same handoff rides device-objects/ICI transfer
(experimental/device_objects.py) instead of host shm."""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

from .serving import LLMServer

logger = logging.getLogger(__name__)


class PrefillServer:
    """Prefill-only deployment: owns a paged engine but never decodes."""

    def __init__(self, engine_config, params=None):
        from .paged import PagedEngineConfig, PagedLLMEngine
        if not isinstance(engine_config, PagedEngineConfig):
            raise TypeError("PD-disagg requires PagedEngineConfig")
        self._engine = PagedLLMEngine(engine_config, params=params)

    async def prefill(self, prompt_tokens: List[int]):
        """Chunked prefill; returns (last_logits, per-layer (k, v) numpy
        pairs trimmed to the prompt's pages)."""
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            None, self._engine.prefill_only, list(prompt_tokens))

    def engine_stats(self) -> Dict[str, Any]:
        return self._engine.stats()


class PDDecodeServer(LLMServer):
    """Decode-side server: prefill is delegated to the PrefillServer
    deployment; everything else (streaming, cancel, HTTP shapes) is
    inherited from LLMServer."""

    def __init__(self, engine_config, params=None, prefill_handle=None):
        super().__init__(engine_config, params=params)
        if not self._paged:
            raise TypeError("PD-disagg requires the paged engine")
        if prefill_handle is None:
            raise ValueError("PDDecodeServer needs a prefill_handle")
        self._prefill_handle = prefill_handle

    async def _submit(self, request, done_callback, token_callback=None):
        last_logits, caches = await \
            self._prefill_handle.prefill.remote(request.prompt_tokens)
        self._ensure_loop()
        self._engine.submit_prefilled(
            request, caches, last_logits, done_callback=done_callback,
            token_callback=token_callback)
        self._wake.set()


def build_pd_disagg_app(engine_config, *, params=None,
                        num_prefill_replicas: int = 1,
                        num_decode_replicas: int = 1,
                        max_ongoing_requests: int = 64):
    """Disaggregated serving application: ingress = decode deployment,
    composed with a prefill deployment (reference:
    prefill_decode_disagg/ builders). Both pools must share params —
    pass them explicitly, or rely on the deterministic seed init."""
    from .. import serve
    prefill_app = serve.deployment(
        PrefillServer, name="PrefillServer",
        num_replicas=num_prefill_replicas,
        max_ongoing_requests=max_ongoing_requests,
    ).bind(engine_config, params)
    decode = serve.deployment(
        PDDecodeServer, name="PDDecodeServer",
        num_replicas=num_decode_replicas,
        max_ongoing_requests=max_ongoing_requests)
    return decode.bind(engine_config, params, prefill_app)
