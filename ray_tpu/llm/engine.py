"""Continuous-batching LLM engine
(the TPU-native replacement for the reference's vLLM delegation,
vllm_models.py — scheduling/continuous batching live HERE, not in an
external engine; conceptually the Orca/vLLM iteration-level scheduler).

Design for the MXU/XLA:
- KV caches are slot-structured: [max_batch, kv_heads, max_len, head_dim]
  per layer. A request occupies one slot from admission to completion.
- ONE jitted decode step serves every active slot together: q_len-1
  forward with per-slot positions (per-row one-hot cache writes), then
  greedy/temperature sampling — a single compiled program per engine.
- Prefill is jitted per power-of-two length bucket (static shapes — no
  recompiles per prompt) on a batch-1 slice, then the slot's rows are
  scattered into the big cache with `dynamic_update_slice`.
- Inactive slots still flow through the decode matmuls (masked out after)
  — wasted FLOPs are cheaper than dynamic shapes on TPU.
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import os

from ..models.llama import LlamaConfig, LlamaModel, init_kv_caches
from . import reqtrace
from ._metrics import llm_metrics

_TAGS = {"engine": "slot"}
# gauges are per-process series (see _metrics.py on the merge semantics)
_GAUGE_TAGS = {"engine": "slot", "pid": str(os.getpid())}


@dataclasses.dataclass
class EngineConfig:
    model: LlamaConfig
    max_batch: int = 4
    max_len: int = 512
    prefill_buckets: Tuple[int, ...] = (32, 64, 128, 256)
    temperature: float = 0.0  # 0 = greedy
    eos_token: Optional[int] = None
    seed: int = 0


@dataclasses.dataclass
class GenerationRequest:
    prompt_tokens: List[int]
    max_new_tokens: int = 32
    request_id: str = ""
    temperature: Optional[float] = None
    # 0/None = no k filter; 1.0/None = no nucleus filter (vLLM-style
    # SamplingParams; applied inside the jitted decode, sampling.py)
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    # request-observatory labels: propagated by the serve proxy
    # (X-RTPU-Tenant, matched route prefix) down to the engine and
    # folded into per-tenant/per-route percentiles (llm/reqtrace.py)
    tenant: Optional[str] = None
    route: Optional[str] = None


@dataclasses.dataclass
class _Slot:
    request: Optional[GenerationRequest] = None
    position: int = 0            # next cache write index
    generated: List[int] = dataclasses.field(default_factory=list)
    last_token: int = 0
    done_callback: Optional[Callable] = None


class LLMEngine:
    def __init__(self, config: EngineConfig, params: Optional[Any] = None,
                 mesh=None):
        self.config = config
        self.model = LlamaModel(config.model)
        self.mesh = mesh
        # accel plane: compile listeners precede this engine's compiles
        from .._internal import accel as _accel
        _accel.ensure_installed()
        rng = jax.random.PRNGKey(config.seed)
        if params is None:
            from ..parallel.mesh import unbox
            sample = jnp.zeros((1, 8), jnp.int32)
            params = unbox(self.model.init(rng, sample)["params"])
        self.params = params
        self._rng = rng
        B, L = config.max_batch, config.max_len
        self.kv_caches = init_kv_caches(config.model, B, L)
        self.slots: List[_Slot] = [_Slot() for _ in range(B)]
        self._pending: "queue.Queue[GenerationRequest]" = queue.Queue()
        self._steps = 0
        self._tokens_generated = 0

        # -- jitted programs ----------------------------------------------
        model = self.model

        def decode_step(params, caches, tokens, positions, rng,
                        temperature, top_k, top_p):
            # tokens [B,1]; positions [B]; sampling params [B] (per slot
            # — requests with different settings share one batch).
            logits, new_caches = model.apply(
                {"params": params}, tokens, positions=positions[:, None],
                kv_caches=caches, cache_index=positions)
            last = logits[:, -1, :].astype(jnp.float32)
            from .sampling import sample_tokens
            out = sample_tokens(rng, last, temperature, top_k, top_p)
            return out.astype(jnp.int32), new_caches

        self._decode = jax.jit(decode_step, donate_argnums=(1,))

        def prefill(params, tokens, positions):
            # Single sequence [1, bucket]; fresh caches for the bucket.
            caches = init_kv_caches(config.model, 1, L)
            logits, new_caches = model.apply(
                {"params": params}, tokens, positions=positions,
                kv_caches=caches, cache_index=0)
            return logits.astype(jnp.float32), new_caches

        self._prefill = jax.jit(prefill)

        def write_slot(caches, slot_caches, slot_index):
            out = []
            for (ck, cv), (sk, sv) in zip(caches, slot_caches):
                ck = jax.lax.dynamic_update_slice(
                    ck, sk.astype(ck.dtype), (slot_index, 0, 0, 0))
                cv = jax.lax.dynamic_update_slice(
                    cv, sv.astype(cv.dtype), (slot_index, 0, 0, 0))
                out.append((ck, cv))
            return out

        self._write_slot = jax.jit(write_slot, donate_argnums=(0,))

    # -- submission --------------------------------------------------------

    def submit(self, request: GenerationRequest,
               done_callback: Optional[Callable] = None):
        n = len(request.prompt_tokens)
        if n >= self.config.max_len:
            raise ValueError("prompt longer than max_len")
        if n > self.config.prefill_buckets[-1]:
            raise ValueError(
                f"prompt of {n} tokens exceeds the largest prefill bucket "
                f"{self.config.prefill_buckets[-1]}")
        request._done_callback = done_callback  # type: ignore[attr-defined]
        request._submit_ts = time.monotonic()  # type: ignore[attr-defined]
        reqtrace.record(request.request_id, reqtrace.QUEUED,
                        engine="slot", prompt_tokens=n,
                        max_new=request.max_new_tokens,
                        tenant=request.tenant, route=request.route)
        self._pending.put(request)
        llm_metrics().queue_depth.set(self._pending.qsize(),
                                      tags=_GAUGE_TAGS)

    def has_work(self) -> bool:
        return (not self._pending.empty()) or \
            any(s.request is not None for s in self.slots)

    def fail_all(self, error: Exception):
        """Resolve every active and queued request with `error` (see
        PagedLLMEngine.fail_all — callers must see step() failures)."""
        import queue as _queue
        for slot in self.slots:
            if slot.request is None:
                continue
            request, slot.request = slot.request, None
            llm_metrics().requests_finished.inc(
                tags=dict(_TAGS, outcome="error"))
            reqtrace.record(request.request_id, reqtrace.FAILED,
                            error=type(error).__name__)
            callback = getattr(request, "_done_callback", None)
            if callback is not None:
                callback(request, error)
        try:
            while True:
                request = self._pending.get_nowait()
                llm_metrics().requests_finished.inc(
                    tags=dict(_TAGS, outcome="error"))
                reqtrace.record(request.request_id, reqtrace.FAILED,
                                error=type(error).__name__)
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, error)
        except _queue.Empty:
            pass

    # -- the scheduler tick ------------------------------------------------

    def step(self) -> List[Tuple[GenerationRequest, List[int]]]:
        """One iteration: admit waiting requests into free slots
        (prefill), then one batched decode step; returns newly finished
        (request, tokens) pairs."""
        self._admit()
        finished = []
        active = [i for i, s in enumerate(self.slots)
                  if s.request is not None]
        if active:
            finished.extend(self._decode_tick(active))
        self._steps += 1
        metrics = llm_metrics()
        metrics.queue_depth.set(self._pending.qsize(), tags=_GAUGE_TAGS)
        metrics.running.set(
            sum(1 for s in self.slots if s.request is not None),
            tags=_GAUGE_TAGS)
        return finished

    def _admit(self):
        for index, slot in enumerate(self.slots):
            if slot.request is not None:
                continue
            try:
                request = self._pending.get_nowait()
            except queue.Empty:
                return
            try:
                self._prefill_into(index, request)
            except Exception as e:  # noqa: BLE001 — per-request failure
                # A bad request must neither kill the engine loop nor
                # strand its submitter: deliver the error via the
                # callback (tokens slot carries the exception).
                llm_metrics().requests_finished.inc(
                    tags=dict(_TAGS, outcome="error"))
                reqtrace.record(request.request_id, reqtrace.FAILED,
                                error=type(e).__name__)
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, e)

    def _bucket(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(f"prompt of {n} tokens exceeds the largest "
                         f"prefill bucket {self.config.prefill_buckets[-1]}")

    def _prefill_into(self, index: int, request: GenerationRequest):
        prompt = request.prompt_tokens
        reqtrace.record(request.request_id, reqtrace.ADMITTED,
                        slot=index)
        bucket = self._bucket(len(prompt))
        tokens = np.zeros((1, bucket), np.int32)
        tokens[0, :len(prompt)] = prompt
        positions = np.arange(bucket, dtype=np.int32)[None, :]
        logits, slot_caches = self._prefill(
            self.params, jnp.asarray(tokens), jnp.asarray(positions))
        self.kv_caches = self._write_slot(self.kv_caches, slot_caches,
                                          index)
        last_logits = np.asarray(logits[0, len(prompt) - 1],
                                 dtype=np.float64)
        temp = self._temp_of(request)
        if temp > 0:
            self._rng, key = jax.random.split(self._rng)
            scaled = last_logits / max(temp, 1e-6)
            from .sampling import filter_logits
            scaled = filter_logits(
                scaled, top_k=getattr(request, "top_k", None) or 0,
                top_p=getattr(request, "top_p", None))
            probs = np.exp(scaled - scaled.max())
            probs /= probs.sum()
            first_token = int(np.random.default_rng(
                int(jax.random.randint(key, (), 0, 2**31 - 1))
            ).choice(len(probs), p=probs))
        else:
            first_token = int(np.argmax(last_logits))
        slot = self.slots[index]
        slot.request = request
        slot.position = len(prompt)
        slot.generated = [first_token]
        slot.last_token = first_token
        self._tokens_generated += 1
        metrics = llm_metrics()
        metrics.prefill_tokens.inc(len(prompt), tags=_TAGS)
        submit_ts = getattr(request, "_submit_ts", None)
        if submit_ts is not None:
            ttft = time.monotonic() - submit_ts
            metrics.ttft.observe(ttft, tags=_TAGS)
            reqtrace.record(request.request_id, reqtrace.DECODE,
                            ttft_s=round(ttft, 6))

    def _temp_of(self, request: GenerationRequest) -> float:
        return request.temperature if request.temperature is not None \
            else self.config.temperature

    def _decode_tick(self, active: List[int]):
        tick_start = time.monotonic()
        B = self.config.max_batch
        tokens = np.zeros((B, 1), np.int32)
        positions = np.zeros((B,), np.int32)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        for i in active:
            req = self.slots[i].request
            tokens[i, 0] = self.slots[i].last_token
            positions[i] = self.slots[i].position
            temps[i] = self._temp_of(req)
            top_ks[i] = req.top_k if getattr(req, "top_k", None) else 0
            top_ps[i] = req.top_p if getattr(req, "top_p", None) \
                is not None else 1.0
        self._rng, key = jax.random.split(self._rng)
        out, self.kv_caches = self._decode(
            self.params, self.kv_caches, jnp.asarray(tokens),
            jnp.asarray(positions), key, jnp.asarray(temps),
            jnp.asarray(top_ks), jnp.asarray(top_ps))
        out = np.asarray(out)
        finished = []
        for i in active:
            slot = self.slots[i]
            token = int(out[i])
            slot.generated.append(token)
            slot.last_token = token
            slot.position += 1
            self._tokens_generated += 1
            request = slot.request
            hit_eos = (self.config.eos_token is not None
                       and token == self.config.eos_token)
            out_len = len(slot.generated)
            if hit_eos or out_len >= request.max_new_tokens or \
                    slot.position >= self.config.max_len - 1:
                finished.append((request, list(slot.generated)))
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, list(slot.generated))
                self.slots[i] = _Slot()
        metrics = llm_metrics()
        metrics.token_latency.observe(time.monotonic() - tick_start,
                                      tags=_TAGS)
        metrics.decode_tokens.inc(len(active), tags=_TAGS)
        for request, _tokens in finished:
            metrics.requests_finished.inc(
                tags=dict(_TAGS, outcome="done"))
            reqtrace.record(request.request_id, reqtrace.FINISHED,
                            tokens=len(_tokens))
            submit_ts = getattr(request, "_submit_ts", None)
            if submit_ts is not None:
                metrics.request_latency.observe(
                    time.monotonic() - submit_ts, tags=_TAGS)
        return finished

    # -- conveniences ------------------------------------------------------

    def generate(self, prompts: List[List[int]],
                 max_new_tokens: int = 32,
                 timeout_s: float = 300.0) -> List[List[int]]:
        """Synchronous batch generation (drives the loop inline)."""
        results: Dict[int, List[int]] = {}
        for i, prompt in enumerate(prompts):
            request = GenerationRequest(prompt_tokens=prompt,
                                        max_new_tokens=max_new_tokens,
                                        request_id=str(i))
            self.submit(request)
        deadline = time.monotonic() + timeout_s
        while len(results) < len(prompts):
            if time.monotonic() > deadline:
                raise TimeoutError("generation timed out")
            for request, tokens in self.step():
                results[int(request.request_id)] = tokens
        return [results[i] for i in range(len(prompts))]

    def stats(self) -> Dict[str, Any]:
        return {
            "steps": self._steps,
            "tokens_generated": self._tokens_generated,
            "active_slots": sum(1 for s in self.slots
                                if s.request is not None),
            "pending": self._pending.qsize(),
        }
