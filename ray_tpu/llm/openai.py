"""OpenAI-compatible serving app
(reference: llm/_internal/serve/builders/application_builders.py:60
build_openai_app + public serve/llm/__init__.py:168 — an HTTP app exposing
/v1/completions, /v1/chat/completions, /v1/models over the LLM engine).

The deployment subclasses `LLMServer`: same engine drive / stream plumbing,
plus tokenization and the OpenAI request/response shapes. Token streams go
out as SSE `data:` events through the proxy's chunked-HTTP relay."""

from __future__ import annotations

import json
import time
import uuid
from typing import Any, Dict, List, Optional

from .serving import LLMServer
from .tokenizer import ByteTokenizer, get_tokenizer  # noqa: F401 — re-export


def _chat_prompt(messages: List[Dict[str, str]]) -> str:
    """Minimal chat template (reference models apply their HF chat
    template; the wire contract — not the template — is what the
    OpenAI-compat layer owns)."""
    parts = [f"{m.get('role', 'user')}: {m.get('content', '')}"
             for m in messages]
    parts.append("assistant:")
    return "\n".join(parts)


class OpenAIServer(LLMServer):
    """LLMServer speaking the OpenAI REST wire shapes."""

    def __init__(self, engine_config, params=None,
                 model_id: str = "ray-tpu-llm", tokenizer=None):
        super().__init__(engine_config, params=params)
        self.model_id = model_id
        # str → load tokenizer.json (native BPE) / checkpoint dir;
        # None → byte fallback; object → duck-typed encode/decode.
        self.tokenizer = get_tokenizer(tokenizer)
        # stream_id -> SSE formatting state
        self._sse: Dict[str, Dict[str, Any]] = {}

    # -- HTTP dispatch -----------------------------------------------------

    async def __call__(self, http_request):
        path = http_request.path
        from ._metrics import llm_metrics

        def _count(route: str):
            llm_metrics().openai_requests.inc(tags={"route": route})

        if path.endswith("/v1/models"):
            _count("/v1/models")
            return {"object": "list",
                    "data": [{"id": self.model_id, "object": "model",
                              "owned_by": "ray_tpu"}]}
        if path.endswith("/v1/completions"):
            _count("/v1/completions")
            return await self._completions(http_request.json(), chat=False)
        if path.endswith("/v1/chat/completions"):
            _count("/v1/chat/completions")
            return await self._completions(http_request.json(), chat=True)
        return (404, {"error": f"no route {path}"})

    async def _completions(self, body: Dict[str, Any], chat: bool):
        if chat:
            prompt_text = _chat_prompt(body.get("messages", []))
        else:
            prompt_text = body.get("prompt", "")
        prompt_tokens = self.tokenizer.encode(prompt_text)
        max_new = int(body.get("max_tokens", 16))
        temperature = body.get("temperature")
        top_k = body.get("top_k")
        top_p = body.get("top_p")
        # the proxy-stamped id (X-RTPU-Request-Id) IS the completion id
        # when present, so `why_slow(<header id>)` resolves client-side
        request_id = self._context_request_id() \
            or f"cmpl-{uuid.uuid4().hex[:24]}"
        if body.get("stream"):
            stream_id = await self.generate_stream_start(
                prompt_tokens, max_new_tokens=max_new,
                temperature=temperature, top_k=top_k, top_p=top_p,
                request_id=request_id)
            self._sse[stream_id] = {
                "chat": chat, "id": request_id,
                "created": int(time.time()), "first": True}
            return {"__rtpu_stream__": stream_id}
        out = await self.generate(
            prompt_tokens, max_new_tokens=max_new,
            temperature=temperature, top_k=top_k, top_p=top_p,
            request_id=request_id)
        text = self.tokenizer.decode(out["tokens"])
        created = int(time.time())
        usage = {"prompt_tokens": len(prompt_tokens),
                 "completion_tokens": out["num_generated"],
                 "total_tokens": len(prompt_tokens) +
                 out["num_generated"]}
        if chat:
            return {"id": request_id, "object": "chat.completion",
                    "created": created, "model": self.model_id,
                    "choices": [{"index": 0,
                                 "message": {"role": "assistant",
                                             "content": text},
                                 "finish_reason": "stop"}],
                    "usage": usage}
        return {"id": request_id, "object": "text_completion",
                "created": created, "model": self.model_id,
                "choices": [{"index": 0, "text": text,
                             "finish_reason": "stop"}],
                "usage": usage}

    # -- SSE stream formatting --------------------------------------------

    async def stream_next(self, stream_id: str,
                          timeout_s: float = 10.0) -> Dict[str, Any]:
        meta = self._sse.get(stream_id)
        batch = await super().stream_next(stream_id, timeout_s)
        if meta is None:  # plain (non-OpenAI) stream
            return batch
        events = []
        if batch.get("tokens"):
            text = self.tokenizer.decode(batch["tokens"])
            if meta["chat"]:
                delta: Dict[str, Any] = {"content": text}
                if meta.pop("first", None):
                    delta["role"] = "assistant"
                chunk = {"id": meta["id"],
                         "object": "chat.completion.chunk",
                         "created": meta["created"],
                         "model": self.model_id,
                         "choices": [{"index": 0, "delta": delta,
                                      "finish_reason": None}]}
            else:
                chunk = {"id": meta["id"], "object": "text_completion",
                         "created": meta["created"],
                         "model": self.model_id,
                         "choices": [{"index": 0, "text": text,
                                      "finish_reason": None}]}
            events.append(f"data: {json.dumps(chunk)}\n\n")
        if batch.get("error"):
            # mid-stream engine failure: surface it as an SSE event so
            # the client sees the error, not a silent [DONE] — with the
            # request id, so the failure stays attributable (why_slow)
            events.append("data: " + json.dumps(
                {"error": {"message": batch["error"],
                           "type": "engine_error",
                           "request_id": meta["id"]}}) + "\n\n")
        if batch["done"]:
            self._sse.pop(stream_id, None)
            events.append("data: [DONE]\n\n")
        return {"data": "".join(events), "done": batch["done"]}


def build_openai_app(engine_config, *, model_id: str = "ray-tpu-llm",
                     tokenizer=None, name: str = "OpenAIServer",
                     num_replicas: int = 1, params=None,
                     max_ongoing_requests: int = 64):
    """OpenAI-compatible application over the TPU engine (reference:
    serve/llm/__init__.py:168 build_openai_app). Deploy with
    `serve.run(app, request_router="prefix")` for prompt-prefix replica
    affinity."""
    from .. import serve
    deployment = serve.deployment(
        OpenAIServer, name=name, num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests)
    return deployment.bind(engine_config, params, model_id, tokenizer)
