"""Paged-KV continuous-batching engine with prefix page sharing
(reference: vLLM's PagedAttention as delegated by
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py, and
the prefix-aware machinery in serve/request_router/; re-designed
TPU-native: page pools in the Pallas paged-attention kernel's layout,
one jitted decode step for the whole active batch).

vs the slot engine (`engine.py`): HBM scales with tokens-in-flight
(`num_pages x page_size`), not `max_batch x max_len`; full prompt pages
shared byte-identically across requests via a prefix hash (system
prompts stored once); admission blocks on page budget, not slot shape.
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import os
import queue
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig, LlamaModel, init_kv_caches
from ._metrics import llm_metrics
from .engine import GenerationRequest

_TAGS = {"engine": "paged"}
# gauges are per-process series (see _metrics.py on the merge semantics)
_GAUGE_TAGS = {"engine": "paged", "pid": str(os.getpid())}


@dataclasses.dataclass
class PagedEngineConfig:
    model: LlamaConfig
    max_batch: int = 4            # concurrent decode rows
    max_len: int = 512            # per-request logical cap
    page_size: int = 16
    num_pages: int = 256          # pool capacity = num_pages * page_size
    prefill_buckets: Tuple[int, ...] = (32, 64, 128, 256)
    temperature: float = 0.0
    eos_token: Optional[int] = None
    seed: int = 0

    @property
    def pages_per_seq(self) -> int:
        return -(-self.max_len // self.page_size)


class PagePool:
    """Physical page allocator with refcounts (shared prefix pages)."""

    def __init__(self, num_pages: int):
        self._free = list(range(num_pages - 1, 0, -1))
        # page 0 is the null page block tables pad with; never allocated
        self.refs = np.zeros(num_pages, np.int32)
        self.refs[0] = 1

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        page = self._free.pop()
        self.refs[page] = 1
        return page

    def incref(self, page: int):
        self.refs[page] += 1

    def decref(self, page: int):
        self.refs[page] -= 1
        if self.refs[page] == 0:
            self._free.append(page)

    def num_free(self) -> int:
        return len(self._free)


@dataclasses.dataclass
class _Seq:
    request: Optional[GenerationRequest] = None
    pages: List[int] = dataclasses.field(default_factory=list)
    own_from: int = 0            # pages[:own_from] are shared (prefix)
    length: int = 0              # cached tokens
    generated: List[int] = dataclasses.field(default_factory=list)
    last_token: int = 0
    cancelled: bool = False


class PagedLLMEngine:
    """Same external surface as LLMEngine (submit/step/generate/stats)
    plus cancel() and per-token streaming callbacks.

    Tensor parallelism: pass `mesh` (a jax Mesh with a `tensor` axis) and
    params + KV pages are sharded over it — params by their flax logical
    axes (heads/kv_heads/mlp/vocab -> tensor), pages on the kv_heads dim
    — so models larger than one chip's HBM serve across chips. The page
    table and scheduler stay host-side and see only logical page ids
    (reference: TP×PP engine-worker placement in
    llm/_internal/serve/deployments/llm/vllm/vllm_models.py:169-178,251;
    here TP is a mesh axis and GSPMD/shard_map insert the collectives)."""

    def __init__(self, config: PagedEngineConfig,
                 params: Optional[Any] = None, mesh=None):
        self.config = config
        cfg = config.model
        self.model = LlamaModel(cfg)
        self.mesh = mesh
        self._tp = int(mesh.shape.get("tensor", 1)) if mesh is not None \
            else 1
        if self._tp > 1:
            if cfg.num_kv_heads % self._tp or cfg.num_heads % self._tp:
                raise ValueError(
                    f"num_heads={cfg.num_heads}/num_kv_heads="
                    f"{cfg.num_kv_heads} not divisible by tensor axis "
                    f"size {self._tp}")
        rng = jax.random.PRNGKey(config.seed)
        self._page_sharding = None
        self._dense_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as PSpec
            from ..parallel.mesh import DEFAULT_LOGICAL_AXIS_RULES, unbox
            from ..parallel.spmd import logical_names_tree, shardings_tree
            rules = dict(DEFAULT_LOGICAL_AXIS_RULES)
            sample = jnp.zeros((1, 8), jnp.int32)
            names = logical_names_tree(self.model, rng, sample)
            pshard = shardings_tree(names, mesh, rules)
            if params is None:
                def _init(r):
                    p = unbox(self.model.init(r, sample)["params"])
                    return jax.tree_util.tree_map(
                        jax.lax.with_sharding_constraint, p, pshard)
                with mesh:
                    params = jax.jit(_init)(rng)
            else:
                # Params from a single-device engine or a checkpoint:
                # scatter to the mesh layout.
                params = jax.device_put(params, pshard)
            # pages: [kv_heads, pages, page_size, hd] sharded on kv_heads
            self._page_sharding = NamedSharding(mesh, PSpec("tensor"))
            # dense prefill caches: [1, kv_heads, L, hd]
            self._dense_sharding = NamedSharding(mesh, PSpec(None, "tensor"))
        elif params is None:
            from ..parallel.mesh import unbox
            params = unbox(self.model.init(
                rng, jnp.zeros((1, 8), jnp.int32))["params"])
        self.params = params
        self._rng = rng
        kvh, hd = cfg.num_kv_heads, cfg.head_dim_
        P, ps = config.num_pages, config.page_size
        # kernel layout: [kv_heads, num_pages, page_size, head_dim]
        def _zero_pages():
            z = jnp.zeros((kvh, P, ps, hd), cfg.dtype)
            if self._page_sharding is not None:
                z = jax.device_put(z, self._page_sharding)
            return z
        self.k_pages = [_zero_pages() for _ in range(cfg.num_layers)]
        self.v_pages = [_zero_pages() for _ in range(cfg.num_layers)]
        self.pool = PagePool(P)
        # prefix cache: hash(token-prefix through page k) -> per-layer page
        self.prefix_pages: Dict[Tuple, List[int]] = {}
        # true LRU: ordered keys, O(1) move-to-end on hit / popitem on
        # evict (the old list.pop(0) was an O(n) shift and hits never
        # refreshed recency — a hot system prompt aged out under churn)
        self._prefix_lru: "collections.OrderedDict[Tuple, None]" = \
            collections.OrderedDict()
        self._prefix_hits = 0
        self._prefix_misses = 0
        self.seqs: List[_Seq] = [_Seq() for _ in range(config.max_batch)]
        self._pending: "queue.Queue[GenerationRequest]" = queue.Queue()
        self._by_id: Dict[str, _Seq] = {}
        self._steps = 0
        self._tokens_generated = 0
        # accelerator-plane step telemetry (StepTimer on the decode
        # tick): decode forward ≈ 2 FLOPs per param per token. Checked
        # once here so a killed plane costs the tick two attribute
        # loads, nothing more.
        from .._internal import accel as _accel
        self._accel = _accel if not _accel.accel_disabled() else None
        if self._accel is not None:
            # listeners precede this engine's prefill/decode compiles
            _accel.ensure_installed()
        # per-tick timings fold locally and flush one aggregated report
        # every 16 ticks — the tick itself pays a perf_counter pair
        self._step_accum = _accel.StepAccumulator("decode") \
            if self._accel is not None else None
        self._num_params = sum(
            int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(self.params))
        model = self.model
        page_sharding = self._page_sharding

        def decode_step(params, k_pages, v_pages, block_tables, lengths,
                        tokens, rng, temperature, top_k, top_p):
            caches = [
                {"k": k_pages[i], "v": v_pages[i],
                 "block_tables": block_tables, "lengths": lengths}
                for i in range(cfg.num_layers)
            ]
            logits, new_caches = model.apply(
                {"params": params}, tokens, positions=lengths[:, None],
                kv_caches=caches, cache_index=None)
            last = logits[:, -1, :].astype(jnp.float32)
            from .sampling import sample_tokens
            out = sample_tokens(rng, last, temperature, top_k, top_p)
            nk = [c["k"] for c in new_caches]
            nv = [c["v"] for c in new_caches]
            if page_sharding is not None:
                # pin the updated pools to the kv-head sharding so the
                # donated-buffer layout is stable across steps
                nk = [jax.lax.with_sharding_constraint(a, page_sharding)
                      for a in nk]
                nv = [jax.lax.with_sharding_constraint(a, page_sharding)
                      for a in nv]
            return out.astype(jnp.int32), nk, nv

        self._decode = jax.jit(decode_step, donate_argnums=(1, 2))

        def chunk_prefill(params, tokens, positions, dense_caches, offset):
            """One prefill chunk: write K/V for `tokens` into the dense
            caches at `offset`, attend causally over everything cached so
            far. Chunked prefill lifts the prompt cap to max_len — any
            prompt runs as ceil(n/bucket) chunks of one compiled shape
            per bucket (reference: vLLM chunked prefill, delegated by
            llm/_internal/serve/deployments/llm/vllm/)."""
            logits, new_caches = model.apply(
                {"params": params}, tokens, positions=positions,
                kv_caches=dense_caches, cache_index=offset)
            return logits.astype(jnp.float32), new_caches

        self._chunk_prefill = jax.jit(chunk_prefill, donate_argnums=(3,))

        def _dense_zero_caches():
            # Length covers the worst chunked-prefill write: the last
            # chunk is bucket-rounded, so a prompt ending near max_len
            # writes up to (largest_bucket - 1) tokens of padding past
            # it. Without the slack, dynamic_update_slice would CLAMP
            # the start index and silently corrupt earlier positions.
            slack = config.prefill_buckets[-1]
            return init_kv_caches(
                cfg, 1, config.pages_per_seq * config.page_size + slack)

        self._dense_zero_caches = jax.jit(
            _dense_zero_caches,
            out_shardings=self._dense_sharding)  # None = default

        def write_pages(k_pages, v_pages, dense_caches, page_ids,
                        start_tok):
            """Scatter pages of a [1, kvh, L, hd] dense prefill cache
            into the pools at physical ids `page_ids`, starting at token
            offset `start_tok` (traced: no recompile per prefix hit)."""
            ps_ = config.page_size
            nk, nv = [], []
            for (kp, vp, (dk, dv)) in zip(k_pages, v_pages, dense_caches):
                # [1, kvh, L, hd] -> [n, kvh, ps, hd] page-major rows
                seg_k = jax.lax.dynamic_slice_in_dim(
                    dk[0], start_tok, page_ids.shape[0] * ps_, axis=1)
                seg_v = jax.lax.dynamic_slice_in_dim(
                    dv[0], start_tok, page_ids.shape[0] * ps_, axis=1)
                kvh_ = seg_k.shape[0]
                seg_k = seg_k.reshape(kvh_, page_ids.shape[0], ps_, -1)
                seg_v = seg_v.reshape(kvh_, page_ids.shape[0], ps_, -1)
                uk = kp.at[:, page_ids].set(seg_k.astype(kp.dtype))
                uv = vp.at[:, page_ids].set(seg_v.astype(vp.dtype))
                if page_sharding is not None:
                    uk = jax.lax.with_sharding_constraint(uk, page_sharding)
                    uv = jax.lax.with_sharding_constraint(uv, page_sharding)
                nk.append(uk)
                nv.append(uv)
            return nk, nv

        self._write_pages = jax.jit(write_pages, donate_argnums=(0, 1),
                                    static_argnums=())

    def _mesh_scope(self):
        """Context for jit calls: marks the serving mesh active so the
        model's attention detects the tensor axis at trace time
        (shard_map over the Pallas/gather kernel)."""
        if self.mesh is None:
            return contextlib.nullcontext()
        from ..parallel.mesh import serving_mesh
        return serving_mesh(self.mesh)

    # -- submission / cancel ---------------------------------------------

    def submit(self, request: GenerationRequest,
               done_callback: Optional[Callable] = None,
               token_callback: Optional[Callable] = None):
        n = len(request.prompt_tokens)
        if n >= self.config.max_len:
            raise ValueError("prompt longer than max_len")
        request._done_callback = done_callback  # type: ignore
        request._token_callback = token_callback  # type: ignore
        request._submit_ts = time.monotonic()  # type: ignore
        self._pending.put(request)
        llm_metrics().queue_depth.set(self._pending.qsize(),
                                      tags=_GAUGE_TAGS)

    def submit_prefilled(self, request: GenerationRequest, dense_caches,
                         last_logits,
                         done_callback: Optional[Callable] = None,
                         token_callback: Optional[Callable] = None):
        """Submit a request whose prefill ran on ANOTHER engine
        (prefill/decode disaggregation): `dense_caches` are per-layer
        (k, v) arrays trimmed to the prompt's pages, `last_logits` the
        prompt's final-position logits. Admission (page budget, prefix
        sharing) happens on the normal scheduler tick."""
        n = len(request.prompt_tokens)
        if n >= self.config.max_len:
            raise ValueError("prompt longer than max_len")
        request._done_callback = done_callback  # type: ignore
        request._token_callback = token_callback  # type: ignore
        request._submit_ts = time.monotonic()  # type: ignore
        self._pending.put((request, dense_caches, last_logits))
        llm_metrics().queue_depth.set(self._pending.qsize(),
                                      tags=_GAUGE_TAGS)

    def cancel(self, request_id: str) -> bool:
        """Abort a request: frees its slot+pages on the next tick if
        running, or drops it from the queue."""
        seq = self._by_id.get(request_id)
        if seq is not None and seq.request is not None:
            seq.cancelled = True
            return True
        # queued: rebuild the queue without it
        kept, found = [], False
        dropped = None
        try:
            while True:
                entry = self._pending.get_nowait()
                r = entry[0] if isinstance(entry, tuple) else entry
                if r.request_id == request_id and not found:
                    found = True
                    dropped = r
                    continue
                kept.append(entry)
        except queue.Empty:
            pass
        for r in kept:
            self._pending.put(r)
        if dropped is not None:
            # queued cancellations must still resolve their waiters
            llm_metrics().requests_finished.inc(
                tags=dict(_TAGS, outcome="cancelled"))
            callback = getattr(dropped, "_done_callback", None)
            if callback is not None:
                callback(dropped, None)  # None = cancelled
        return found

    def has_work(self) -> bool:
        return (not self._pending.empty()) or \
            any(s.request is not None for s in self.seqs)

    def fail_all(self, error: Exception):
        """Resolve every active and queued request with `error` (the
        serving drive loop calls this when step() raises — callers must
        see the failure, not hang on a silently-spinning engine)."""
        for i, seq in enumerate(self.seqs):
            if seq.request is None:
                continue
            request = seq.request
            self._release(seq)
            self.seqs[i] = _Seq()
            llm_metrics().requests_finished.inc(
                tags=dict(_TAGS, outcome="error"))
            callback = getattr(request, "_done_callback", None)
            if callback is not None:
                callback(request, error)
        try:
            while True:
                entry = self._pending.get_nowait()
                r = entry[0] if isinstance(entry, tuple) else entry
                llm_metrics().requests_finished.inc(
                    tags=dict(_TAGS, outcome="error"))
                callback = getattr(r, "_done_callback", None)
                if callback is not None:
                    callback(r, error)
        except queue.Empty:
            pass

    # -- scheduler tick ----------------------------------------------------

    def step(self) -> List[Tuple[GenerationRequest, Any]]:
        self._admit()
        finished = []
        active = [i for i, s in enumerate(self.seqs)
                  if s.request is not None]
        if active:
            finished.extend(self._decode_tick(active))
        elif self._step_accum is not None:
            # idle tick: flush the partial window so step telemetry
            # never lags a drained engine by up to `every` ticks
            self._step_accum.flush()
        self._steps += 1
        metrics = llm_metrics()
        metrics.queue_depth.set(self._pending.qsize(), tags=_GAUGE_TAGS)
        metrics.running.set(
            sum(1 for s in self.seqs if s.request is not None),
            tags=_GAUGE_TAGS)
        metrics.kv_utilization.set(
            1.0 - self.pool.num_free() / max(1, self.config.num_pages),
            tags=_GAUGE_TAGS)
        return finished

    def _pages_needed(self, request: GenerationRequest) -> int:
        total = len(request.prompt_tokens) + request.max_new_tokens
        return -(-min(total + 1, self.config.max_len)
                 // self.config.page_size)

    def _admit(self):
        for index, seq in enumerate(self.seqs):
            if seq.request is not None:
                continue
            try:
                entry = self._pending.get_nowait()
            except queue.Empty:
                return
            # plain request (local prefill) or (request, caches, logits)
            # from submit_prefilled (disaggregated prefill)
            prefilled = isinstance(entry, tuple)
            request = entry[0] if prefilled else entry
            if self.pool.num_free() < self._pages_needed(request):
                # page budget exhausted: requeue and stop admitting —
                # decode completions will free pages
                self._pending.put(entry)
                return
            try:
                if prefilled:
                    self._admit_prefilled(index, request, entry[1],
                                          entry[2])
                else:
                    self._prefill_into(index, request)
            except Exception as e:  # noqa: BLE001
                llm_metrics().requests_finished.inc(
                    tags=dict(_TAGS, outcome="error"))
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, e)

    def _bucket(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        return self.config.prefill_buckets[-1]

    def _run_chunked_prefill(self, prompt: List[int]):
        """Prefill the whole prompt in bucket-sized chunks against a dense
        per-request cache; returns (last_token_logits, dense_caches). One
        compiled program per bucket size, regardless of prompt length."""
        with self._mesh_scope():
            caches = self._dense_zero_caches()
            largest = self.config.prefill_buckets[-1]
            off = 0
            last_logits = None
            while off < len(prompt):
                rem = len(prompt) - off
                chunk = self._bucket(min(rem, largest))
                take = min(rem, chunk)
                tokens = np.zeros((1, chunk), np.int32)
                tokens[0, :take] = prompt[off:off + take]
                # pad positions clamp to the rope table; their garbage K/V
                # lands past the prompt and is never copied to pages
                positions = np.minimum(
                    np.arange(off, off + chunk, dtype=np.int32),
                    self.config.model.max_seq_len - 1)[None, :]
                logits, caches = self._chunk_prefill(
                    self.params, jnp.asarray(tokens),
                    jnp.asarray(positions), caches,
                    jnp.asarray(off, jnp.int32))
                if off + take == len(prompt):
                    last_logits = np.asarray(
                        logits[0, take - 1], np.float64)
                off += take
            return last_logits, caches

    def prefill_only(self, prompt: List[int]):
        """Run chunked prefill WITHOUT admitting a sequence: returns
        (last_token_logits, per-layer dense (k, v) numpy pairs) trimmed to
        whole pages. This is the prefill half of prefill/decode
        disaggregation (reference:
        llm/_internal/serve/deployments/prefill_decode_disagg/) — the KV
        ships to a decode engine's `submit_prefilled`."""
        last_logits, caches = self._run_chunked_prefill(prompt)
        n_tok = -(-len(prompt) // self.config.page_size) * \
            self.config.page_size
        out = [(np.asarray(k[:, :, :n_tok]), np.asarray(v[:, :, :n_tok]))
               for (k, v) in caches]
        return last_logits, out

    def _prefill_into(self, index: int, request: GenerationRequest):
        # chunked dense prefill of the whole prompt (compute), paged
        # storage — prompts run to max_len, not the largest bucket
        last_logits, dense_caches = self._run_chunked_prefill(
            request.prompt_tokens)
        self._admit_prefilled(index, request, dense_caches, last_logits)

    def _admit_prefilled(self, index: int, request: GenerationRequest,
                         dense_caches, last_logits):
        """Install an already-prefilled request: page allocation, prefix
        sharing/registration, first-token pick, sequence setup.
        `dense_caches` may be numpy (shipped from a prefill server) or
        on-device arrays (local prefill)."""
        cfg = self.config
        prompt = request.prompt_tokens
        ps = cfg.page_size
        dense_caches = [(jnp.asarray(k), jnp.asarray(v))
                        for (k, v) in dense_caches]
        # 1. prefix reuse: full pages whose token prefix is already pooled
        shared: List[int] = []
        n_full = len(prompt) // ps
        for k in range(n_full, 0, -1):
            key = tuple(prompt[:k * ps])
            hit = self.prefix_pages.get(key)
            if hit is not None:
                # incref every layer-0 page id (ids shared across layers)
                for page in hit:
                    self.pool.incref(page)
                shared = list(hit)
                # a hit refreshes recency — hot prefixes (system
                # prompts) must not age out while they're being
                # reused. Ancestor keys (shorter prefixes of the hit,
                # whose pages this hit shares) refresh too, so
                # eviction order never inverts the sharing hierarchy.
                for j in range(1, k + 1):
                    akey = tuple(prompt[:j * ps])
                    if akey in self._prefix_lru:
                        self._prefix_lru.move_to_end(akey)
                self._prefix_hits += 1
                llm_metrics().prefix_hits.inc(tags=_TAGS)
                break
        else:
            if n_full:
                self._prefix_misses += 1
                llm_metrics().prefix_misses.inc(tags=_TAGS)
        n_pages = self._pages_needed(request)
        new_ids = []
        for _ in range(n_pages - len(shared)):
            page = self.pool.alloc()
            assert page is not None, "admission checked the budget"
            new_ids.append(page)
        # write only non-shared pages holding PROMPT tokens (shared ones
        # are byte-identical by construction; generation-room pages are
        # filled token-by-token at decode — and a disaggregated prefill
        # ships a cache trimmed to exactly the prompt pages)
        n_prompt_pages = -(-len(prompt) // ps)
        write_ids = new_ids[:max(0, n_prompt_pages - len(shared))]
        if write_ids:
            with self._mesh_scope():
                self.k_pages, self.v_pages = self._write_pages(
                    self.k_pages, self.v_pages, dense_caches,
                    jnp.asarray(write_ids, jnp.int32),
                    jnp.asarray(len(shared) * ps, jnp.int32))
        pages = shared + new_ids
        # 3. register newly-complete full-page prefixes for reuse
        for k in range(1, n_full + 1):
            key = tuple(prompt[:k * ps])
            if key not in self.prefix_pages:
                for page in pages[:k]:
                    self.pool.incref(page)
                self.prefix_pages[key] = pages[:k]
                self._prefix_lru[key] = None
        self._evict_prefixes()
        # 4. first token from the prefill logits (sampled when the request
        # asks for temperature > 0, mirroring the slot engine's branch —
        # engine.py:195-204 — so the two engines agree beyond greedy)
        temp = request.temperature if request.temperature is not None \
            else self.config.temperature
        if temp > 0:
            self._rng, key = jax.random.split(self._rng)
            scaled = last_logits / max(temp, 1e-6)
            # shared host-side filter (sampling.filter_logits) so the
            # FIRST token honors the request's top_k/top_p too
            from .sampling import filter_logits
            scaled = filter_logits(
                scaled, top_k=getattr(request, "top_k", None) or 0,
                top_p=getattr(request, "top_p", None))
            probs = np.exp(scaled - scaled.max())
            probs /= probs.sum()
            first_token = int(np.random.default_rng(
                int(jax.random.randint(key, (), 0, 2**31 - 1))
            ).choice(len(probs), p=probs))
        else:
            first_token = int(np.argmax(last_logits))
        seq = self.seqs[index]
        seq.request = request
        seq.pages = pages
        seq.own_from = len(shared)
        seq.length = len(prompt)
        seq.generated = [first_token]
        seq.last_token = first_token
        seq.cancelled = False
        self._by_id[request.request_id] = seq
        self._tokens_generated += 1
        metrics = llm_metrics()
        metrics.prefill_tokens.inc(len(prompt), tags=_TAGS)
        submit_ts = getattr(request, "_submit_ts", None)
        if submit_ts is not None:
            metrics.ttft.observe(time.monotonic() - submit_ts, tags=_TAGS)
        self._emit_token(seq, first_token)

    def _evict_prefixes(self, max_entries: int = 128):
        while len(self._prefix_lru) > max_entries:
            key, _ = self._prefix_lru.popitem(last=False)  # oldest first
            pages = self.prefix_pages.pop(key, None)
            if pages:
                for page in pages:
                    self.pool.decref(page)
        llm_metrics().prefix_entries.set(len(self._prefix_lru),
                                         tags=_GAUGE_TAGS)

    def _emit_token(self, seq: _Seq, token: int):
        callback = getattr(seq.request, "_token_callback", None)
        if callback is not None:
            callback(seq.request, token)

    def _release(self, seq: _Seq):
        for page in seq.pages:
            self.pool.decref(page)
        self._by_id.pop(seq.request.request_id, None)

    def _decode_tick(self, active: List[int]):
        tick_start = time.monotonic()
        cfg = self.config
        B = cfg.max_batch
        # cancelled sequences release before the step
        finished = []
        for i in list(active):
            seq = self.seqs[i]
            if seq.cancelled:
                request = seq.request
                self._release(seq)
                self.seqs[i] = _Seq()
                active.remove(i)
                llm_metrics().requests_finished.inc(
                    tags=dict(_TAGS, outcome="cancelled"))
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, None)  # None = cancelled
        if not active:
            return finished
        block_tables = np.zeros((B, cfg.pages_per_seq), np.int32)
        lengths = np.zeros((B,), np.int32)
        tokens = np.zeros((B, 1), np.int32)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        for i in active:
            seq = self.seqs[i]
            block_tables[i, :len(seq.pages)] = seq.pages
            lengths[i] = seq.length
            tokens[i, 0] = seq.last_token
            temp = seq.request.temperature
            temps[i] = temp if temp is not None else cfg.temperature
            req_k = getattr(seq.request, "top_k", None)
            top_ks[i] = req_k if req_k else 0
            req_p = getattr(seq.request, "top_p", None)
            top_ps[i] = req_p if req_p is not None else 1.0
        self._rng, key = jax.random.split(self._rng)
        accel = self._accel
        timer = accel.StepTimer(
            "decode", tokens=len(active),
            flops=2.0 * self._num_params * len(active),
            sink=self._step_accum) \
            if accel is not None else None
        with timer if timer is not None else contextlib.nullcontext():
            with self._mesh_scope():
                with (timer.device() if timer is not None
                      else contextlib.nullcontext()):
                    out, self.k_pages, self.v_pages = self._decode(
                        self.params, self.k_pages, self.v_pages,
                        jnp.asarray(block_tables), jnp.asarray(lengths),
                        jnp.asarray(tokens), key, jnp.asarray(temps),
                        jnp.asarray(top_ks), jnp.asarray(top_ps))
                    out = np.asarray(out)  # fences the dispatch
            for i in active:
                seq = self.seqs[i]
                token = int(out[i])
                seq.generated.append(token)
                seq.last_token = token
                seq.length += 1
                self._tokens_generated += 1
                self._emit_token(seq, token)
                request = seq.request
                hit_eos = (cfg.eos_token is not None
                           and token == cfg.eos_token)
                capacity = len(seq.pages) * cfg.page_size
                if hit_eos \
                        or len(seq.generated) >= request.max_new_tokens \
                        or seq.length + 1 >= capacity \
                        or seq.length >= cfg.max_len - 1:
                    finished.append((request, list(seq.generated)))
                    callback = getattr(request, "_done_callback", None)
                    if callback is not None:
                        callback(request, list(seq.generated))
                    self._release(seq)
                    self.seqs[i] = _Seq()
            metrics = llm_metrics()
            metrics.token_latency.observe(time.monotonic() - tick_start,
                                          tags=_TAGS)
            metrics.decode_tokens.inc(len(active), tags=_TAGS)
            for request, _tokens in finished:
                metrics.requests_finished.inc(
                    tags=dict(_TAGS, outcome="done"))
                submit_ts = getattr(request, "_submit_ts", None)
                if submit_ts is not None:
                    metrics.request_latency.observe(
                        time.monotonic() - submit_ts, tags=_TAGS)
        return finished

    # -- conveniences ------------------------------------------------------

    def generate(self, prompts: List[List[int]],
                 max_new_tokens: int = 32,
                 timeout_s: float = 300.0) -> List[List[int]]:
        results: Dict[int, List[int]] = {}
        for i, prompt in enumerate(prompts):
            self.submit(GenerationRequest(
                prompt_tokens=prompt, max_new_tokens=max_new_tokens,
                request_id=str(i)))
        deadline = time.monotonic() + timeout_s
        while len(results) < len(prompts):
            if time.monotonic() > deadline:
                raise TimeoutError("generation timed out")
            for request, tokens in self.step():
                results[int(request.request_id)] = tokens
        return [results[i] for i in range(len(prompts))]

    def stats(self) -> Dict[str, Any]:
        if self._step_accum is not None:
            self._step_accum.flush()  # surfaces the partial window
        cache_bytes = (2 * self.config.model.num_layers *
                       int(np.prod(self.k_pages[0].shape)) *
                       self.k_pages[0].dtype.itemsize)
        param_bytes = sum(
            int(np.prod(p.shape)) * p.dtype.itemsize
            for p in jax.tree_util.tree_leaves(self.params))
        return {
            "steps": self._steps,
            "tokens_generated": self._tokens_generated,
            "active": sum(1 for s in self.seqs if s.request is not None),
            "pending": self._pending.qsize(),
            "free_pages": self.pool.num_free(),
            "prefix_entries": len(self.prefix_pages),
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "tp": self._tp,
            "hbm_cache_bytes": cache_bytes,
            # per-chip residency: pages shard on kv_heads, params on
            # their logical axes — both divide by the tensor degree (the
            # fsdp/replicated leaves make this a ceiling for params)
            "hbm_cache_bytes_per_device": cache_bytes // self._tp,
            "hbm_param_bytes": param_bytes,
            "hbm_param_bytes_per_device": self._param_bytes_per_device(),
        }

    def _param_bytes_per_device(self) -> int:
        """Actual per-device parameter residency: sums each leaf's
        addressable shard size on device 0 (exact, not estimated)."""
        total = 0
        for p in jax.tree_util.tree_leaves(self.params):
            if hasattr(p, "sharding") and hasattr(p, "addressable_shards"):
                shard = p.addressable_shards[0]
                total += int(np.prod(shard.data.shape)) * p.dtype.itemsize
            else:
                total += int(np.prod(p.shape)) * p.dtype.itemsize
        return total
