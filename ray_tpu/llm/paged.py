"""Paged-KV continuous-batching engine with prefix page sharing
(reference: vLLM's PagedAttention as delegated by
python/ray/llm/_internal/serve/deployments/llm/vllm/vllm_models.py, and
the prefix-aware machinery in serve/request_router/; re-designed
TPU-native: page pools in the Pallas paged-attention kernel's layout,
one jitted decode step for the whole active batch).

vs the slot engine (`engine.py`): HBM scales with tokens-in-flight
(`num_pages x page_size`), not `max_batch x max_len`; full prompt pages
shared byte-identically across requests via a prefix hash (system
prompts stored once); admission blocks on page budget, not slot shape.

Scheduling is CONTINUOUS (iteration-level) by default: every tick fills
freed slots from the waiting queue, advances at most
`prefill_decode_ratio` chunked-prefill chunks interleaved with the
decode batch, and under page pressure preempts the youngest sequence
(pages released, request parked for re-admission with its generated
tokens as a prompt extension) instead of exhausting the pool. Prefix
reuse rides a radix tree over KV pages (`radix.py`): admission maps the
longest cached prefix copy-on-write into the block table and prefills
only the tail. RTPU_NO_CONT_BATCH=1 is the exact-legacy per-drain A/B
arm (blocking inline prefill, upfront page reservation, token-tuple
prefix LRU, no preemption).
"""

from __future__ import annotations

import collections
import contextlib
import dataclasses
import os
import queue
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .._internal.config import CONFIG
from ..models.llama import LlamaConfig, LlamaModel, init_kv_caches
from . import reqtrace
from ._metrics import llm_metrics
from .engine import GenerationRequest
from .radix import RadixPrefixCache

_TAGS = {"engine": "paged"}
# gauges are per-process series (see _metrics.py on the merge semantics)
_GAUGE_TAGS = {"engine": "paged", "pid": str(os.getpid())}


@dataclasses.dataclass
class PagedEngineConfig:
    model: LlamaConfig
    max_batch: int = 4            # concurrent decode rows
    max_len: int = 512            # per-request logical cap
    page_size: int = 16
    num_pages: int = 256          # pool capacity = num_pages * page_size
    prefill_buckets: Tuple[int, ...] = (32, 64, 128, 256)
    temperature: float = 0.0
    eos_token: Optional[int] = None
    seed: int = 0
    # continuous batching: prefill chunks advanced per scheduler tick
    # (bounds how much prefill compute a tick may steal from decode)
    prefill_decode_ratio: int = 1

    @property
    def pages_per_seq(self) -> int:
        return -(-self.max_len // self.page_size)


class PagePool:
    """Physical page allocator with refcounts (shared prefix pages)."""

    def __init__(self, num_pages: int):
        self._free = list(range(num_pages - 1, 0, -1))
        # page 0 is the null page block tables pad with; never allocated
        self.refs = np.zeros(num_pages, np.int32)
        self.refs[0] = 1

    def alloc(self) -> Optional[int]:
        if not self._free:
            return None
        page = self._free.pop()
        self.refs[page] = 1
        return page

    def incref(self, page: int):
        self.refs[page] += 1

    def decref(self, page: int):
        self.refs[page] -= 1
        if self.refs[page] == 0:
            self._free.append(page)

    def num_free(self) -> int:
        return len(self._free)


@dataclasses.dataclass
class _Seq:
    request: Optional[GenerationRequest] = None
    pages: List[int] = dataclasses.field(default_factory=list)
    own_from: int = 0            # pages[:own_from] are shared (prefix)
    length: int = 0              # cached tokens
    generated: List[int] = dataclasses.field(default_factory=list)
    last_token: int = 0
    cancelled: bool = False
    # continuous-batching state
    phase: str = "decode"        # "prefill" until the prompt is cached
    prompt: List[int] = dataclasses.field(default_factory=list)
    # tokens generated before a preemption, re-prefilled as prompt
    resume: List[int] = dataclasses.field(default_factory=list)
    prefill_off: int = 0         # prompt tokens cached so far
    dense_caches: Any = None     # in-flight chunked-prefill cache
    last_logits: Any = None
    admit_at: int = 0            # admission order (preemption picks max)


class PagedLLMEngine:
    """Same external surface as LLMEngine (submit/step/generate/stats)
    plus cancel() and per-token streaming callbacks.

    Tensor parallelism: pass `mesh` (a jax Mesh with a `tensor` axis) and
    params + KV pages are sharded over it — params by their flax logical
    axes (heads/kv_heads/mlp/vocab -> tensor), pages on the kv_heads dim
    — so models larger than one chip's HBM serve across chips. The page
    table and scheduler stay host-side and see only logical page ids
    (reference: TP×PP engine-worker placement in
    llm/_internal/serve/deployments/llm/vllm/vllm_models.py:169-178,251;
    here TP is a mesh axis and GSPMD/shard_map insert the collectives)."""

    def __init__(self, config: PagedEngineConfig,
                 params: Optional[Any] = None, mesh=None):
        self.config = config
        cfg = config.model
        self.model = LlamaModel(cfg)
        self.mesh = mesh
        self._tp = int(mesh.shape.get("tensor", 1)) if mesh is not None \
            else 1
        if self._tp > 1:
            if cfg.num_kv_heads % self._tp or cfg.num_heads % self._tp:
                raise ValueError(
                    f"num_heads={cfg.num_heads}/num_kv_heads="
                    f"{cfg.num_kv_heads} not divisible by tensor axis "
                    f"size {self._tp}")
        rng = jax.random.PRNGKey(config.seed)
        self._page_sharding = None
        self._dense_sharding = None
        if mesh is not None:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as PSpec
            from ..parallel.mesh import DEFAULT_LOGICAL_AXIS_RULES, unbox
            from ..parallel.spmd import logical_names_tree, shardings_tree
            rules = dict(DEFAULT_LOGICAL_AXIS_RULES)
            sample = jnp.zeros((1, 8), jnp.int32)
            names = logical_names_tree(self.model, rng, sample)
            pshard = shardings_tree(names, mesh, rules)
            if params is None:
                def _init(r):
                    p = unbox(self.model.init(r, sample)["params"])
                    return jax.tree_util.tree_map(
                        jax.lax.with_sharding_constraint, p, pshard)
                with mesh:
                    params = jax.jit(_init)(rng)
            else:
                # Params from a single-device engine or a checkpoint:
                # scatter to the mesh layout.
                params = jax.device_put(params, pshard)
            # pages: [kv_heads, pages, page_size, hd] sharded on kv_heads
            self._page_sharding = NamedSharding(mesh, PSpec("tensor"))
            # dense prefill caches: [1, kv_heads, L, hd]
            self._dense_sharding = NamedSharding(mesh, PSpec(None, "tensor"))
        elif params is None:
            from ..parallel.mesh import unbox
            params = unbox(self.model.init(
                rng, jnp.zeros((1, 8), jnp.int32))["params"])
        self.params = params
        self._rng = rng
        kvh, hd = cfg.num_kv_heads, cfg.head_dim_
        P, ps = config.num_pages, config.page_size
        # kernel layout: [kv_heads, num_pages, page_size, head_dim]
        def _zero_pages():
            z = jnp.zeros((kvh, P, ps, hd), cfg.dtype)
            if self._page_sharding is not None:
                z = jax.device_put(z, self._page_sharding)
            return z
        self.k_pages = [_zero_pages() for _ in range(cfg.num_layers)]
        self.v_pages = [_zero_pages() for _ in range(cfg.num_layers)]
        self.pool = PagePool(P)
        # scheduling mode: continuous (per-tick admission, chunked
        # prefill interleave, preemption, radix prefix tree) unless the
        # exact-legacy kill switch is set. Read once — a mode is an
        # engine-lifetime property, not a per-tick branch.
        self._continuous = not CONFIG.no_cont_batch
        self.radix: Optional[RadixPrefixCache] = None
        if self._continuous:
            self.radix = RadixPrefixCache(
                self.pool, ps,
                max_entries=int(CONFIG.prefix_cache_entries))
        # waiting queue (continuous mode): _pending is the thread-safe
        # ingress; the tick drains it into _parked, which also receives
        # preempted requests at its FRONT (they re-admit first)
        self._parked: "collections.deque" = collections.deque()
        self._admit_clock = 0
        self._preemptions = 0
        # recent TTFTs feed autoscaling_metrics() (median over a window)
        self._recent_ttfts: "collections.deque" = collections.deque(
            maxlen=64)
        # prefix cache: hash(token-prefix through page k) -> per-layer page
        self.prefix_pages: Dict[Tuple, List[int]] = {}
        # true LRU: ordered keys, O(1) move-to-end on hit / popitem on
        # evict (the old list.pop(0) was an O(n) shift and hits never
        # refreshed recency — a hot system prompt aged out under churn)
        self._prefix_lru: "collections.OrderedDict[Tuple, None]" = \
            collections.OrderedDict()
        self._prefix_hits = 0
        self._prefix_misses = 0
        self.seqs: List[_Seq] = [_Seq() for _ in range(config.max_batch)]
        self._pending: "queue.Queue[GenerationRequest]" = queue.Queue()
        self._by_id: Dict[str, _Seq] = {}
        self._steps = 0
        self._tokens_generated = 0
        # accelerator-plane step telemetry (StepTimer on the decode
        # tick): decode forward ≈ 2 FLOPs per param per token. Checked
        # once here so a killed plane costs the tick two attribute
        # loads, nothing more.
        from .._internal import accel as _accel
        self._accel = _accel if not _accel.accel_disabled() else None
        if self._accel is not None:
            # listeners precede this engine's prefill/decode compiles
            _accel.ensure_installed()
        # per-tick timings fold locally and flush one aggregated report
        # every 16 ticks — the tick itself pays a perf_counter pair
        self._step_accum = _accel.StepAccumulator("decode") \
            if self._accel is not None else None
        self._num_params = sum(
            int(np.prod(p.shape))
            for p in jax.tree_util.tree_leaves(self.params))
        model = self.model
        page_sharding = self._page_sharding

        def decode_step(params, k_pages, v_pages, block_tables, lengths,
                        tokens, rng, temperature, top_k, top_p):
            caches = [
                {"k": k_pages[i], "v": v_pages[i],
                 "block_tables": block_tables, "lengths": lengths}
                for i in range(cfg.num_layers)
            ]
            logits, new_caches = model.apply(
                {"params": params}, tokens, positions=lengths[:, None],
                kv_caches=caches, cache_index=None)
            last = logits[:, -1, :].astype(jnp.float32)
            from .sampling import sample_tokens
            out = sample_tokens(rng, last, temperature, top_k, top_p)
            nk = [c["k"] for c in new_caches]
            nv = [c["v"] for c in new_caches]
            if page_sharding is not None:
                # pin the updated pools to the kv-head sharding so the
                # donated-buffer layout is stable across steps
                nk = [jax.lax.with_sharding_constraint(a, page_sharding)
                      for a in nk]
                nv = [jax.lax.with_sharding_constraint(a, page_sharding)
                      for a in nv]
            return out.astype(jnp.int32), nk, nv

        self._decode = jax.jit(decode_step, donate_argnums=(1, 2))

        def chunk_prefill(params, tokens, positions, dense_caches, offset):
            """One prefill chunk: write K/V for `tokens` into the dense
            caches at `offset`, attend causally over everything cached so
            far. Chunked prefill lifts the prompt cap to max_len — any
            prompt runs as ceil(n/bucket) chunks of one compiled shape
            per bucket (reference: vLLM chunked prefill, delegated by
            llm/_internal/serve/deployments/llm/vllm/)."""
            logits, new_caches = model.apply(
                {"params": params}, tokens, positions=positions,
                kv_caches=dense_caches, cache_index=offset)
            return logits.astype(jnp.float32), new_caches

        self._chunk_prefill = jax.jit(chunk_prefill, donate_argnums=(3,))

        def _dense_zero_caches():
            # Length covers the worst chunked-prefill write: the last
            # chunk is bucket-rounded, so a prompt ending near max_len
            # writes up to (largest_bucket - 1) tokens of padding past
            # it. Without the slack, dynamic_update_slice would CLAMP
            # the start index and silently corrupt earlier positions.
            slack = config.prefill_buckets[-1]
            return init_kv_caches(
                cfg, 1, config.pages_per_seq * config.page_size + slack)

        self._dense_zero_caches = jax.jit(
            _dense_zero_caches,
            out_shardings=self._dense_sharding)  # None = default

        def write_pages(k_pages, v_pages, dense_caches, page_ids,
                        start_tok):
            """Scatter pages of a [1, kvh, L, hd] dense prefill cache
            into the pools at physical ids `page_ids`, starting at token
            offset `start_tok`. `page_ids` is padded to pages_per_seq
            with the null page so there is ONE compiled shape per
            dense-cache length (a per-sequence page count would compile
            a program per distinct tail size); clamped gathers send the
            pad lanes' garbage to the reserved null page, never a live
            one."""
            ps_ = config.page_size
            n = page_ids.shape[0]
            nk, nv = [], []
            for (kp, vp, (dk, dv)) in zip(k_pages, v_pages, dense_caches):
                # [1, kvh, L, hd] -> [kvh, n, ps, hd] page-major rows
                idx = start_tok + jnp.arange(n * ps_, dtype=jnp.int32)
                idx = jnp.minimum(idx, dk.shape[2] - 1)
                seg_k = jnp.take(dk[0], idx, axis=1)
                seg_v = jnp.take(dv[0], idx, axis=1)
                kvh_ = seg_k.shape[0]
                seg_k = seg_k.reshape(kvh_, n, ps_, -1)
                seg_v = seg_v.reshape(kvh_, n, ps_, -1)
                uk = kp.at[:, page_ids].set(seg_k.astype(kp.dtype))
                uv = vp.at[:, page_ids].set(seg_v.astype(vp.dtype))
                if page_sharding is not None:
                    uk = jax.lax.with_sharding_constraint(uk, page_sharding)
                    uv = jax.lax.with_sharding_constraint(uv, page_sharding)
                nk.append(uk)
                nv.append(uv)
            return nk, nv

        self._write_pages = jax.jit(write_pages, donate_argnums=(0, 1),
                                    static_argnums=())
        dense_sharding = self._dense_sharding

        def gather_pages(k_pages, v_pages, dense_caches, page_ids):
            """Inverse of write_pages: copy pooled pages into the head
            of a dense prefill cache, so a radix-shared prefix span is
            attended over without recomputing it (zero prefill FLOPs
            for the span). `page_ids` is padded to pages_per_seq with
            the null page for a single compiled shape; padded garbage
            lands at or after the first real tail position, so it is
            either overwritten by the tail chunks or causally masked."""
            out = []
            for (kp, vp, (dk, dv)) in zip(k_pages, v_pages, dense_caches):
                kvh_ = kp.shape[0]
                seg_k = kp[:, page_ids].reshape(
                    kvh_, -1, kp.shape[-1])[None]
                seg_v = vp[:, page_ids].reshape(
                    kvh_, -1, vp.shape[-1])[None]
                ndk = jax.lax.dynamic_update_slice_in_dim(
                    dk, seg_k.astype(dk.dtype), 0, axis=2)
                ndv = jax.lax.dynamic_update_slice_in_dim(
                    dv, seg_v.astype(dv.dtype), 0, axis=2)
                if dense_sharding is not None:
                    ndk = jax.lax.with_sharding_constraint(
                        ndk, dense_sharding)
                    ndv = jax.lax.with_sharding_constraint(
                        ndv, dense_sharding)
                out.append((ndk, ndv))
            return out

        self._gather_pages = jax.jit(gather_pages, donate_argnums=(2,))

    def _mesh_scope(self):
        """Context for jit calls: marks the serving mesh active so the
        model's attention detects the tensor axis at trace time
        (shard_map over the Pallas/gather kernel)."""
        if self.mesh is None:
            return contextlib.nullcontext()
        from ..parallel.mesh import serving_mesh
        return serving_mesh(self.mesh)

    # -- submission / cancel ---------------------------------------------

    def submit(self, request: GenerationRequest,
               done_callback: Optional[Callable] = None,
               token_callback: Optional[Callable] = None):
        n = len(request.prompt_tokens)
        if n >= self.config.max_len:
            raise ValueError("prompt longer than max_len")
        request._done_callback = done_callback  # type: ignore
        request._token_callback = token_callback  # type: ignore
        request._submit_ts = time.monotonic()  # type: ignore
        reqtrace.record(request.request_id, reqtrace.QUEUED,
                        engine="paged", prompt_tokens=n,
                        max_new=request.max_new_tokens,
                        tenant=getattr(request, "tenant", None),
                        route=getattr(request, "route", None))
        self._pending.put(request)
        llm_metrics().queue_depth.set(self._pending.qsize(),
                                      tags=_GAUGE_TAGS)

    def submit_prefilled(self, request: GenerationRequest, dense_caches,
                         last_logits,
                         done_callback: Optional[Callable] = None,
                         token_callback: Optional[Callable] = None):
        """Submit a request whose prefill ran on ANOTHER engine
        (prefill/decode disaggregation): `dense_caches` are per-layer
        (k, v) arrays trimmed to the prompt's pages, `last_logits` the
        prompt's final-position logits. Admission (page budget, prefix
        sharing) happens on the normal scheduler tick."""
        n = len(request.prompt_tokens)
        if n >= self.config.max_len:
            raise ValueError("prompt longer than max_len")
        request._done_callback = done_callback  # type: ignore
        request._token_callback = token_callback  # type: ignore
        request._submit_ts = time.monotonic()  # type: ignore
        reqtrace.record(request.request_id, reqtrace.QUEUED,
                        engine="paged", prompt_tokens=n,
                        max_new=request.max_new_tokens, prefilled=True,
                        tenant=getattr(request, "tenant", None),
                        route=getattr(request, "route", None))
        self._pending.put((request, dense_caches, last_logits))
        llm_metrics().queue_depth.set(self._pending.qsize(),
                                      tags=_GAUGE_TAGS)

    def cancel(self, request_id: str) -> bool:
        """Abort a request: frees its slot+pages on the next tick if
        running, or drops it from the queue."""
        seq = self._by_id.get(request_id)
        if seq is not None and seq.request is not None:
            seq.cancelled = True
            return True
        # queued: rebuild the queue without it
        kept, found = [], False
        dropped = None
        try:
            while True:
                entry = self._pending.get_nowait()
                r = entry[0] if isinstance(entry, tuple) else entry
                if r.request_id == request_id and not found:
                    found = True
                    dropped = r
                    continue
                kept.append(entry)
        except queue.Empty:
            pass
        for r in kept:
            self._pending.put(r)
        if not found:
            # continuous-mode waiting queue (drained arrivals and
            # preemption-parked requests)
            for entry in list(self._parked):
                r = entry[0] if isinstance(entry, tuple) else entry
                if r.request_id == request_id:
                    try:
                        self._parked.remove(entry)
                    except ValueError:
                        break  # admitted concurrently
                    found = True
                    dropped = r
                    break
        if dropped is not None:
            # queued cancellations must still resolve their waiters
            llm_metrics().requests_finished.inc(
                tags=dict(_TAGS, outcome="cancelled"))
            reqtrace.record(dropped.request_id, reqtrace.CANCELLED,
                            where="queued")
            callback = getattr(dropped, "_done_callback", None)
            if callback is not None:
                callback(dropped, None)  # None = cancelled
        return found

    def has_work(self) -> bool:
        return (not self._pending.empty()) or bool(self._parked) or \
            any(s.request is not None for s in self.seqs)

    def fail_all(self, error: Exception):
        """Resolve every active and queued request with `error` (the
        serving drive loop calls this when step() raises — callers must
        see the failure, not hang on a silently-spinning engine)."""
        for i, seq in enumerate(self.seqs):
            if seq.request is None:
                continue
            request = seq.request
            self._release(seq)
            self.seqs[i] = _Seq()
            llm_metrics().requests_finished.inc(
                tags=dict(_TAGS, outcome="error"))
            reqtrace.record(request.request_id, reqtrace.FAILED,
                            error=type(error).__name__)
            callback = getattr(request, "_done_callback", None)
            if callback is not None:
                callback(request, error)
        self._drain_pending()
        while self._parked:
            entry = self._parked.popleft()
            r = entry[0] if isinstance(entry, tuple) else entry
            llm_metrics().requests_finished.inc(
                tags=dict(_TAGS, outcome="error"))
            reqtrace.record(r.request_id, reqtrace.FAILED,
                            error=type(error).__name__)
            callback = getattr(r, "_done_callback", None)
            if callback is not None:
                callback(r, error)

    # -- scheduler tick ----------------------------------------------------

    def step(self) -> List[Tuple[GenerationRequest, Any]]:
        if self._continuous:
            return self._step_continuous()
        self._admit()
        finished = []
        active = [i for i, s in enumerate(self.seqs)
                  if s.request is not None]
        if active:
            finished.extend(self._decode_tick(active))
        elif self._step_accum is not None:
            # idle tick: flush the partial window so step telemetry
            # never lags a drained engine by up to `every` ticks
            self._step_accum.flush()
        self._steps += 1
        self._set_gauges()
        return finished

    def _step_continuous(self) -> List[Tuple[GenerationRequest, Any]]:
        """One continuous-batching tick: reap cancellations, fill freed
        slots from the waiting queue (radix prefix match, tail-only
        prefill setup), advance bounded chunked prefill, then decode the
        running batch — admission happens every tick, not per drain."""
        finished: List[Tuple[GenerationRequest, Any]] = []
        self._reap_cancelled()
        self._admit_continuous()
        self._prefill_tick(finished)
        active = [i for i, s in enumerate(self.seqs)
                  if s.request is not None and s.phase == "decode"]
        if active:
            finished.extend(self._decode_tick(active))
        elif self._step_accum is not None:
            self._step_accum.flush()
        self._steps += 1
        self._set_gauges()
        return finished

    def _waiting_count(self) -> int:
        return self._pending.qsize() + len(self._parked)

    def _set_gauges(self):
        metrics = llm_metrics()
        metrics.queue_depth.set(self._waiting_count(), tags=_GAUGE_TAGS)
        metrics.running.set(
            sum(1 for s in self.seqs if s.request is not None),
            tags=_GAUGE_TAGS)
        free = self.pool.num_free()
        metrics.kv_utilization.set(
            1.0 - free / max(1, self.config.num_pages), tags=_GAUGE_TAGS)
        metrics.kv_occupancy.set(self.config.num_pages - 1 - free,
                                 tags=_GAUGE_TAGS)
        metrics.waiting.set(self._waiting_count(), tags=_GAUGE_TAGS)
        if self.radix is not None:
            shared = self.radix.shared_pages()
        else:
            shared = sum(1 for p in self.prefix_pinned_pages()
                         if self.pool.refs[p] > 1)
        metrics.shared_pages.set(shared, tags=_GAUGE_TAGS)

    def _reap_cancelled(self):
        """Release cancelled sequences in ANY phase (a mid-prefill
        cancel must return its pages too) before admission reuses the
        slots."""
        for i, seq in enumerate(self.seqs):
            if seq.request is None or not seq.cancelled:
                continue
            request = seq.request
            self._release(seq)
            self.seqs[i] = _Seq()
            llm_metrics().requests_finished.inc(
                tags=dict(_TAGS, outcome="cancelled"))
            reqtrace.record(request.request_id, reqtrace.CANCELLED,
                            where=seq.phase)
            callback = getattr(request, "_done_callback", None)
            if callback is not None:
                callback(request, None)  # None = cancelled

    def _drain_pending(self):
        try:
            while True:
                self._parked.append(self._pending.get_nowait())
        except queue.Empty:
            pass

    def _next_admit_id(self) -> int:
        self._admit_clock += 1
        return self._admit_clock

    # -- park bookkeeping (request observatory + park histogram) ---------

    def _compile_total(self) -> float:
        """Disjoint backend-compile seconds so far (the PR-7 tracker);
        0 when the accel plane is killed — compile attribution then
        degrades to zero, it never invents time."""
        return (self._accel.backend_compile_seconds_total()
                if self._accel is not None else 0.0)

    def _park_note(self, request: GenerationRequest, reason: str):
        """Open a park episode ONCE (admission retries every tick while
        pages are short — one PARKED event and one histogram sample per
        episode, not per retry)."""
        if getattr(request, "_rt_park_ts", None) is None:
            request._rt_park_ts = time.monotonic()  # type: ignore
            request._rt_park_reason = reason  # type: ignore
            reqtrace.record(request.request_id, reqtrace.PARKED,
                            reason=reason)

    def _unpark_note(self, request: GenerationRequest) -> float:
        """Close a park episode at (re-)admission: observe the park
        histogram by reason, accumulate per-request park seconds (the
        why_slow park bucket's metric twin), and stamp RESUMED for
        preempted requests. Returns total park seconds so far."""
        park_ts = getattr(request, "_rt_park_ts", None)
        if park_ts is not None:
            parked = time.monotonic() - park_ts
            reason = getattr(request, "_rt_park_reason", "unknown")
            llm_metrics().park_seconds.observe(
                parked, tags=dict(_TAGS, reason=reason))
            request._rt_park_total = parked + \
                getattr(request, "_rt_park_total", 0.0)  # type: ignore
            request._rt_park_ts = None  # type: ignore
            if getattr(request, "_resume_tokens", None):
                reqtrace.record(request.request_id, reqtrace.RESUMED,
                                reason=reason,
                                parked_s=round(parked, 6))
        return getattr(request, "_rt_park_total", 0.0)

    def _admit_continuous(self):
        self._drain_pending()
        for index, seq in enumerate(self.seqs):
            if seq.request is not None:
                continue
            if not self._parked:
                return
            entry = self._parked.popleft()
            prefilled = isinstance(entry, tuple)
            request = entry[0] if prefilled else entry
            try:
                if prefilled:
                    # disaggregated prefill: the KV arrives whole, so
                    # this admission reserves pages up front (legacy
                    # budget), prefix machinery still rides the radix
                    need = self._pages_needed(request)
                    if self.pool.num_free() < need and \
                            self.radix is not None:
                        self.radix.evict_pages(
                            need - self.pool.num_free())
                    if self.pool.num_free() < need:
                        self._park_note(request, "no_pages")
                        self._parked.appendleft(entry)
                        return
                    self._admit_prefilled(index, request, entry[1],
                                          entry[2])
                elif not self._begin_prefill(index, request):
                    self._park_note(request, "no_pages")
                    self._parked.appendleft(entry)
                    return
            except Exception as e:  # noqa: BLE001
                llm_metrics().requests_finished.inc(
                    tags=dict(_TAGS, outcome="error"))
                reqtrace.record(request.request_id, reqtrace.FAILED,
                                error=type(e).__name__)
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, e)

    def _begin_prefill(self, index: int,
                       request: GenerationRequest) -> bool:
        """Admit a request into the prefill phase: radix-match the
        longest cached prefix (mapped copy-on-write into the block
        table), allocate only the tail prompt pages, and gather the
        shared span into the dense chunk cache so the tail attends over
        it without recomputing. Returns False when pages are short even
        after pressure eviction (caller re-parks the request)."""
        cfg = self.config
        ps = cfg.page_size
        resume = list(getattr(request, "_resume_tokens", []))
        prompt = list(request.prompt_tokens) + resume
        shared = self._match_prefix(prompt)
        n_prompt_pages = -(-len(prompt) // ps)
        tail_pages = n_prompt_pages - len(shared)
        if self.pool.num_free() < tail_pages:
            if self.radix is not None:
                self.radix.evict_pages(
                    tail_pages - self.pool.num_free())
            if self.pool.num_free() < tail_pages:
                for page in shared:
                    self.pool.decref(page)
                return False
        new_ids = []
        for _ in range(tail_pages):
            page = self.pool.alloc()
            assert page is not None, "budget checked above"
            new_ids.append(page)
        with self._mesh_scope():
            dense = self._dense_zero_caches()
            if shared:
                pad = np.zeros(cfg.pages_per_seq, np.int32)
                pad[:len(shared)] = shared
                dense = self._gather_pages(self.k_pages, self.v_pages,
                                           dense, jnp.asarray(pad))
        seq = self.seqs[index]
        seq.request = request
        seq.prompt = prompt
        seq.resume = resume
        seq.phase = "prefill"
        seq.pages = shared + new_ids
        seq.own_from = len(shared)
        seq.length = 0
        seq.generated = []
        seq.last_token = 0
        seq.cancelled = False
        seq.prefill_off = len(shared) * ps
        seq.dense_caches = dense
        seq.last_logits = None
        seq.admit_at = self._next_admit_id()
        self._by_id[request.request_id] = seq
        self._unpark_note(request)
        reqtrace.record(request.request_id, reqtrace.ADMITTED,
                        shared_pages=len(shared),
                        tail_pages=tail_pages,
                        resume_tokens=len(resume) or None)
        return True

    def _prefill_tick(self, finished: List):
        """Advance at most `prefill_decode_ratio` prefill chunks,
        round-robin across prefilling sequences in admission order, so
        a long prompt never stalls the decode batch for more than one
        bounded chunk per tick."""
        budget = max(1, int(self.config.prefill_decode_ratio))
        order = sorted(
            (i for i, s in enumerate(self.seqs)
             if s.request is not None and s.phase == "prefill"
             and not s.cancelled),
            key=lambda i: self.seqs[i].admit_at)
        if not any(s.request is not None and s.phase == "decode"
                   for s in self.seqs):
            # nothing decoding → no decode latency to protect; drain
            # the prefill backlog at full speed (cold-start ramp)
            budget = max(budget, len(order))
        while budget > 0 and order:
            i = order.pop(0)
            seq = self.seqs[i]
            self._prefill_chunk(seq)
            budget -= 1
            if seq.prefill_off >= len(seq.prompt):
                self._finish_prefill(i, finished)
            else:
                order.append(i)

    def _prefill_chunk(self, seq: _Seq):
        """One bucket-rounded chunk of `seq`'s remaining prompt into its
        dense cache (same program as the legacy inline prefill — one
        compiled shape per bucket)."""
        cfg = self.config
        prompt = seq.prompt
        largest = cfg.prefill_buckets[-1]
        off = seq.prefill_off
        rem = len(prompt) - off
        chunk = self._bucket(min(rem, largest))
        take = min(rem, chunk)
        tokens = np.zeros((1, chunk), np.int32)
        tokens[0, :take] = prompt[off:off + take]
        positions = np.minimum(
            np.arange(off, off + chunk, dtype=np.int32),
            cfg.model.max_seq_len - 1)[None, :]
        trace = not reqtrace.reqtrace_disabled()
        if trace:
            chunk_t0 = time.monotonic()
            compile_t0 = self._compile_total()
        with self._mesh_scope():
            logits, seq.dense_caches = self._chunk_prefill(
                self.params, jnp.asarray(tokens), jnp.asarray(positions),
                seq.dense_caches, jnp.asarray(off, jnp.int32))
        if off + take == len(prompt):
            seq.last_logits = np.asarray(logits[0, take - 1], np.float64)
        seq.prefill_off = off + take
        if trace and seq.request is not None:
            reqtrace.record(
                seq.request.request_id, reqtrace.PREFILL_CHUNK,
                tokens=take, bucket=chunk,
                dur_s=round(time.monotonic() - chunk_t0, 6),
                compile_s=round(
                    self._compile_total() - compile_t0, 6) or None)
        # counts COMPUTED tokens only — a radix-shared span costs zero
        # here, which is exactly the prefill-FLOPs win the A/B measures
        llm_metrics().prefill_tokens.inc(take, tags=_TAGS)

    def _write_owned_pages(self, dense_caches, write_ids, start_page):
        """Commit owned prompt pages from a dense prefill cache to the
        pools. The id list is padded to pages_per_seq with the null
        page so `_write_pages` keeps one compiled shape per dense-cache
        length instead of one per tail size."""
        cfg = self.config
        ids = list(write_ids) + [0] * (cfg.pages_per_seq
                                       - len(write_ids))
        with self._mesh_scope():
            self.k_pages, self.v_pages = self._write_pages(
                self.k_pages, self.v_pages, dense_caches,
                jnp.asarray(ids, jnp.int32),
                jnp.asarray(start_page * cfg.page_size, jnp.int32))

    def _finish_prefill(self, index: int, finished: List):
        """Prompt fully cached: write the owned tail pages, commit full
        pages to the radix, sample the first token from the prefill
        logits, and move the sequence to the decode phase."""
        cfg = self.config
        ps = cfg.page_size
        seq = self.seqs[index]
        request = seq.request
        prompt = seq.prompt
        write_ids = seq.pages[seq.own_from:]
        if write_ids:
            self._write_owned_pages(seq.dense_caches, write_ids,
                                    seq.own_from)
        seq.dense_caches = None
        self._register_prefix(prompt, seq.pages)
        first_token = self._first_token(request, seq.last_logits)
        seq.last_logits = None
        seq.phase = "decode"
        seq.length = len(prompt)
        seq.generated = [first_token]
        seq.last_token = first_token
        self._tokens_generated += 1
        metrics = llm_metrics()
        submit_ts = getattr(request, "_submit_ts", None)
        park_s = getattr(request, "_rt_park_total", 0.0)
        if submit_ts is not None and not seq.resume:
            ttft = time.monotonic() - submit_ts
            metrics.ttft.observe(ttft, tags=_TAGS)
            self._recent_ttfts.append(ttft)
            # the DECODE stamp splits a parked request's TTFT: park_s
            # is the admission-blocked share, the rest is real prefill
            reqtrace.record(request.request_id, reqtrace.DECODE,
                            ttft_s=round(ttft, 6),
                            park_s=round(park_s, 6) or None)
        else:
            reqtrace.record(request.request_id, reqtrace.DECODE,
                            resumed=True,
                            park_s=round(park_s, 6) or None)
        self._emit_token(seq, first_token)
        if seq.resume:
            # a resumed sequence may hit its budget/eos on the token the
            # tail prefill just produced — apply the decode-tick finish
            # conditions here so resume never overshoots the unpreempted
            # run (token parity)
            hit_eos = (cfg.eos_token is not None
                       and first_token == cfg.eos_token)
            total = len(seq.resume) + len(seq.generated)
            if hit_eos or total >= request.max_new_tokens \
                    or seq.length >= cfg.max_len - 1:
                tokens = seq.resume + list(seq.generated)
                finished.append((request, tokens))
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, tokens)
                self._release(seq)
                self.seqs[index] = _Seq()
                metrics.requests_finished.inc(
                    tags=dict(_TAGS, outcome="done"))
                reqtrace.record(request.request_id, reqtrace.FINISHED,
                                tokens=len(tokens))
                if submit_ts is not None:
                    metrics.request_latency.observe(
                        time.monotonic() - submit_ts, tags=_TAGS)

    def _alloc_page(self) -> Optional[int]:
        """Allocate with radix pressure relief: cold unshared prefix
        pages are reclaimed before giving up."""
        page = self.pool.alloc()
        if page is None and self.radix is not None \
                and self.radix.evict_pages(1):
            page = self.pool.alloc()
        return page

    def _ensure_decode_pages(self, active: List[int]) -> List[int]:
        """Lazy page growth before the decode tick: every decoding
        sequence needs the page its next token writes into. Under pool
        exhaustion the YOUNGEST sequence is preempted (pages released,
        request parked at the queue front with its generated tokens as
        a prompt extension) until the rest fit — the continuous-batching
        answer to OOM."""
        ps = self.config.page_size
        alive = sorted(active, key=lambda i: self.seqs[i].admit_at)
        for i in list(alive):
            if i not in alive:
                continue
            seq = self.seqs[i]
            while seq.request is not None \
                    and seq.length // ps >= len(seq.pages):
                page = self._alloc_page()
                if page is not None:
                    seq.pages.append(page)
                    continue
                victims = [j for j in alive
                           if self.seqs[j].request is not None]
                victim = max(victims,
                             key=lambda j: self.seqs[j].admit_at)
                self._preempt(victim, reason="page_pressure")
                alive.remove(victim)
                if victim == i:
                    break
        return [i for i in alive if self.seqs[i].request is not None]

    def _preempt(self, index: int, reason: str):
        seq = self.seqs[index]
        request = seq.request
        # generated-so-far becomes a prompt extension; re-admission
        # radix-matches the already-registered prompt pages, so only
        # the generated span (plus the partial page) re-prefills
        request._resume_tokens = seq.resume + list(seq.generated)
        self._release(seq)
        self.seqs[index] = _Seq()
        reqtrace.record(request.request_id, reqtrace.PREEMPTED,
                        reason=reason,
                        generated=len(request._resume_tokens))
        self._park_note(request, reason)
        self._parked.appendleft(request)
        self._preemptions += 1
        llm_metrics().preemptions.inc(tags=dict(_TAGS, reason=reason))

    def _pages_needed(self, request: GenerationRequest) -> int:
        total = len(request.prompt_tokens) + request.max_new_tokens
        return -(-min(total + 1, self.config.max_len)
                 // self.config.page_size)

    def _admit(self):
        for index, seq in enumerate(self.seqs):
            if seq.request is not None:
                continue
            try:
                entry = self._pending.get_nowait()
            except queue.Empty:
                return
            # plain request (local prefill) or (request, caches, logits)
            # from submit_prefilled (disaggregated prefill)
            prefilled = isinstance(entry, tuple)
            request = entry[0] if prefilled else entry
            if self.pool.num_free() < self._pages_needed(request):
                # page budget exhausted: requeue and stop admitting —
                # decode completions will free pages
                self._pending.put(entry)
                return
            try:
                if prefilled:
                    self._admit_prefilled(index, request, entry[1],
                                          entry[2])
                else:
                    self._prefill_into(index, request)
            except Exception as e:  # noqa: BLE001
                llm_metrics().requests_finished.inc(
                    tags=dict(_TAGS, outcome="error"))
                reqtrace.record(request.request_id, reqtrace.FAILED,
                                error=type(e).__name__)
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, e)

    def _bucket(self, n: int) -> int:
        for b in self.config.prefill_buckets:
            if n <= b:
                return b
        return self.config.prefill_buckets[-1]

    def _run_chunked_prefill(self, prompt: List[int]):
        """Prefill the whole prompt in bucket-sized chunks against a dense
        per-request cache; returns (last_token_logits, dense_caches). One
        compiled program per bucket size, regardless of prompt length."""
        with self._mesh_scope():
            caches = self._dense_zero_caches()
            largest = self.config.prefill_buckets[-1]
            off = 0
            last_logits = None
            while off < len(prompt):
                rem = len(prompt) - off
                chunk = self._bucket(min(rem, largest))
                take = min(rem, chunk)
                tokens = np.zeros((1, chunk), np.int32)
                tokens[0, :take] = prompt[off:off + take]
                # pad positions clamp to the rope table; their garbage K/V
                # lands past the prompt and is never copied to pages
                positions = np.minimum(
                    np.arange(off, off + chunk, dtype=np.int32),
                    self.config.model.max_seq_len - 1)[None, :]
                logits, caches = self._chunk_prefill(
                    self.params, jnp.asarray(tokens),
                    jnp.asarray(positions), caches,
                    jnp.asarray(off, jnp.int32))
                if off + take == len(prompt):
                    last_logits = np.asarray(  # host-sync ok: once per prompt, scoring path
                        logits[0, take - 1], np.float64)
                off += take
            return last_logits, caches

    def prefill_only(self, prompt: List[int]):
        """Run chunked prefill WITHOUT admitting a sequence: returns
        (last_token_logits, per-layer dense (k, v) numpy pairs) trimmed to
        whole pages. This is the prefill half of prefill/decode
        disaggregation (reference:
        llm/_internal/serve/deployments/prefill_decode_disagg/) — the KV
        ships to a decode engine's `submit_prefilled`."""
        last_logits, caches = self._run_chunked_prefill(prompt)
        n_tok = -(-len(prompt) // self.config.page_size) * \
            self.config.page_size
        out = [(np.asarray(k[:, :, :n_tok]), np.asarray(v[:, :, :n_tok]))
               for (k, v) in caches]
        return last_logits, out

    def _prefill_into(self, index: int, request: GenerationRequest):
        # chunked dense prefill of the whole prompt (compute), paged
        # storage — prompts run to max_len, not the largest bucket
        last_logits, dense_caches = self._run_chunked_prefill(
            request.prompt_tokens)
        self._admit_prefilled(index, request, dense_caches, last_logits)

    def _admit_prefilled(self, index: int, request: GenerationRequest,
                         dense_caches, last_logits):
        """Install an already-prefilled request: page allocation, prefix
        sharing/registration, first-token pick, sequence setup.
        `dense_caches` may be numpy (shipped from a prefill server) or
        on-device arrays (local prefill)."""
        cfg = self.config
        prompt = request.prompt_tokens
        ps = cfg.page_size
        dense_caches = [(jnp.asarray(k), jnp.asarray(v))
                        for (k, v) in dense_caches]
        # 1. prefix reuse: full pages whose token prefix is already pooled
        shared = self._match_prefix(prompt)
        n_pages = self._pages_needed(request)
        new_ids = []
        for _ in range(n_pages - len(shared)):
            page = self.pool.alloc()
            assert page is not None, "admission checked the budget"
            new_ids.append(page)
        # write only non-shared pages holding PROMPT tokens (shared ones
        # are byte-identical by construction; generation-room pages are
        # filled token-by-token at decode — and a disaggregated prefill
        # ships a cache trimmed to exactly the prompt pages)
        n_prompt_pages = -(-len(prompt) // ps)
        write_ids = new_ids[:max(0, n_prompt_pages - len(shared))]
        if write_ids:
            self._write_owned_pages(dense_caches, write_ids, len(shared))
        pages = shared + new_ids
        # 3. register newly-complete full-page prefixes for reuse
        self._register_prefix(prompt, pages)
        # 4. first token from the prefill logits
        first_token = self._first_token(request, last_logits)
        seq = self.seqs[index]
        seq.request = request
        seq.prompt = list(prompt)
        seq.resume = []
        seq.phase = "decode"
        seq.pages = pages
        seq.own_from = len(shared)
        seq.length = len(prompt)
        seq.generated = [first_token]
        seq.last_token = first_token
        seq.cancelled = False
        seq.admit_at = self._next_admit_id()
        self._by_id[request.request_id] = seq
        self._tokens_generated += 1
        park_s = self._unpark_note(request)
        reqtrace.record(request.request_id, reqtrace.ADMITTED,
                        shared_pages=len(shared),
                        tail_pages=len(new_ids))
        metrics = llm_metrics()
        metrics.prefill_tokens.inc(len(prompt), tags=_TAGS)
        submit_ts = getattr(request, "_submit_ts", None)
        if submit_ts is not None:
            ttft = time.monotonic() - submit_ts
            metrics.ttft.observe(ttft, tags=_TAGS)
            self._recent_ttfts.append(ttft)
            reqtrace.record(request.request_id, reqtrace.DECODE,
                            ttft_s=round(ttft, 6),
                            park_s=round(park_s, 6) or None)
        self._emit_token(seq, first_token)

    def _first_token(self, request: GenerationRequest,
                     last_logits) -> int:
        """First token from prefill logits (sampled when the request
        asks for temperature > 0, mirroring the slot engine's branch —
        engine.py:195-204 — so the two engines agree beyond greedy)."""
        temp = request.temperature if request.temperature is not None \
            else self.config.temperature
        if temp > 0:
            self._rng, key = jax.random.split(self._rng)
            scaled = last_logits / max(temp, 1e-6)
            # shared host-side filter (sampling.filter_logits) so the
            # FIRST token honors the request's top_k/top_p too
            from .sampling import filter_logits
            scaled = filter_logits(
                scaled, top_k=getattr(request, "top_k", None) or 0,
                top_p=getattr(request, "top_p", None))
            probs = np.exp(scaled - scaled.max())
            probs /= probs.sum()
            return int(np.random.default_rng(
                int(jax.random.randint(key, (), 0, 2**31 - 1))
            ).choice(len(probs), p=probs))
        return int(np.argmax(last_logits))

    def _match_prefix(self, prompt: List[int]) -> List[int]:
        """Longest cached full-page prefix of `prompt`: refcounted page
        ids the caller maps copy-on-write into its block table (radix
        walk in continuous mode, token-tuple LRU on the legacy arm)."""
        ps = self.config.page_size
        n_full = len(prompt) // ps
        if self.radix is not None:
            shared = self.radix.match(prompt)
            if shared:
                self._prefix_hits += 1
                llm_metrics().prefix_hits.inc(tags=_TAGS)
            elif n_full:
                self._prefix_misses += 1
                llm_metrics().prefix_misses.inc(tags=_TAGS)
            return shared
        shared: List[int] = []
        for k in range(n_full, 0, -1):
            key = tuple(prompt[:k * ps])
            hit = self.prefix_pages.get(key)
            if hit is not None:
                # incref every layer-0 page id (ids shared across layers)
                for page in hit:
                    self.pool.incref(page)
                shared = list(hit)
                # a hit refreshes recency — hot prefixes (system
                # prompts) must not age out while they're being
                # reused. Ancestor keys (shorter prefixes of the hit,
                # whose pages this hit shares) refresh too, so
                # eviction order never inverts the sharing hierarchy.
                for j in range(1, k + 1):
                    akey = tuple(prompt[:j * ps])
                    if akey in self._prefix_lru:
                        self._prefix_lru.move_to_end(akey)
                self._prefix_hits += 1
                llm_metrics().prefix_hits.inc(tags=_TAGS)
                break
        else:
            if n_full:
                self._prefix_misses += 1
                llm_metrics().prefix_misses.inc(tags=_TAGS)
        return shared

    def _register_prefix(self, prompt: List[int], pages: List[int]):
        """Commit the full prompt pages for reuse, then enforce the
        entry budget (`RTPU_PREFIX_CACHE_ENTRIES`)."""
        ps = self.config.page_size
        n_full = len(prompt) // ps
        if self.radix is not None:
            # re-read the flag so tests / live reconfig take effect
            self.radix.max_entries = int(CONFIG.prefix_cache_entries)
            if n_full:
                self.radix.insert(prompt, pages[:n_full])
            llm_metrics().prefix_entries.set(self.radix.entries,
                                             tags=_GAUGE_TAGS)
            return
        for k in range(1, n_full + 1):
            key = tuple(prompt[:k * ps])
            if key not in self.prefix_pages:
                for page in pages[:k]:
                    self.pool.incref(page)
                self.prefix_pages[key] = pages[:k]
                self._prefix_lru[key] = None
        self._evict_prefixes()

    def _evict_prefixes(self, max_entries: Optional[int] = None):
        if max_entries is None:
            max_entries = int(CONFIG.prefix_cache_entries)
        if self.radix is not None:
            self.radix.evict(max_entries)
            llm_metrics().prefix_entries.set(self.radix.entries,
                                             tags=_GAUGE_TAGS)
            return
        while len(self._prefix_lru) > max_entries:
            key, _ = self._prefix_lru.popitem(last=False)  # oldest first
            pages = self.prefix_pages.pop(key, None)
            if pages:
                for page in pages:
                    self.pool.decref(page)
        llm_metrics().prefix_entries.set(len(self._prefix_lru),
                                         tags=_GAUGE_TAGS)

    def prefix_pinned_pages(self) -> set:
        """Distinct physical pages the prefix store holds a reference
        on (radix nodes or legacy LRU entries)."""
        if self.radix is not None:
            return set(self.radix.pages())
        return {p for pages in self.prefix_pages.values() for p in pages}

    def release_prefix_cache(self) -> int:
        """Evict every unshared prefix entry (pages mapped by live
        sequences stay pinned until they release). Returns pages
        freed back to the pool."""
        before = self.pool.num_free()
        self._evict_prefixes(max_entries=0)
        return self.pool.num_free() - before

    def page_leak_check(self) -> int:
        """Pool-balance audit: recompute every page's expected refcount
        from live sequences plus the prefix store and compare against
        the allocator. Returns the number of inconsistent pages (0 =
        balanced); call between steps — completions, cancels, preempts
        and evictions must all keep this at zero."""
        expected = np.zeros(self.config.num_pages, np.int64)
        expected[0] = 1  # the null pad page
        for seq in self.seqs:
            for page in seq.pages:
                expected[page] += 1
        if self.radix is not None:
            for page in self.radix.pages():
                expected[page] += 1
        else:
            for pages in self.prefix_pages.values():
                for page in pages:
                    expected[page] += 1
        bad = int(np.sum(expected != self.pool.refs))
        # the free list must hold exactly the zero-ref pages
        if len(self.pool._free) != int(np.sum(self.pool.refs[1:] == 0)):
            bad += 1
        return bad

    def autoscaling_metrics(self) -> Dict[str, Any]:
        """Signals for the serve autoscaler's closed loop (the replica's
        get_metrics() forwards them to the controller): waiting work,
        recent median TTFT, and KV page occupancy."""
        ttfts = sorted(self._recent_ttfts)
        usable = max(1, self.config.num_pages - 1)
        out: Dict[str, Any] = {
            "queued": self._waiting_count(),
            "kv_occupancy": 1.0 - self.pool.num_free() / usable,
        }
        if ttfts:
            out["ttft_s"] = ttfts[len(ttfts) // 2]
        return out

    def _emit_token(self, seq: _Seq, token: int):
        callback = getattr(seq.request, "_token_callback", None)
        if callback is not None:
            callback(seq.request, token)

    def _release(self, seq: _Seq):
        for page in seq.pages:
            self.pool.decref(page)
        self._by_id.pop(seq.request.request_id, None)

    def _decode_tick(self, active: List[int]):
        tick_start = time.monotonic()
        cfg = self.config
        B = cfg.max_batch
        # cancelled sequences release before the step
        finished = []
        for i in list(active):
            seq = self.seqs[i]
            if seq.cancelled:
                request = seq.request
                self._release(seq)
                self.seqs[i] = _Seq()
                active.remove(i)
                llm_metrics().requests_finished.inc(
                    tags=dict(_TAGS, outcome="cancelled"))
                reqtrace.record(request.request_id, reqtrace.CANCELLED,
                                where="decode")
                callback = getattr(request, "_done_callback", None)
                if callback is not None:
                    callback(request, None)  # None = cancelled
        if self._continuous and active:
            # lazy page growth (+ preemption under pressure) replaces
            # the legacy upfront prompt+max_new reservation
            active = self._ensure_decode_pages(active)
        if not active:
            return finished
        trace = not reqtrace.reqtrace_disabled()
        if trace:
            # snapshot ids now: finished slots are reset before the
            # compile delta is attributed below
            trace_rids = [self.seqs[i].request.request_id
                          for i in active]
            compile_t0 = self._compile_total()
        block_tables = np.zeros((B, cfg.pages_per_seq), np.int32)
        lengths = np.zeros((B,), np.int32)
        tokens = np.zeros((B, 1), np.int32)
        temps = np.zeros((B,), np.float32)
        top_ks = np.zeros((B,), np.int32)
        top_ps = np.ones((B,), np.float32)
        for i in active:
            seq = self.seqs[i]
            block_tables[i, :len(seq.pages)] = seq.pages
            lengths[i] = seq.length
            tokens[i, 0] = seq.last_token
            temp = seq.request.temperature
            temps[i] = temp if temp is not None else cfg.temperature
            req_k = getattr(seq.request, "top_k", None)
            top_ks[i] = req_k if req_k else 0
            req_p = getattr(seq.request, "top_p", None)
            top_ps[i] = req_p if req_p is not None else 1.0
        self._rng, key = jax.random.split(self._rng)
        accel = self._accel
        timer = accel.StepTimer(
            "decode", tokens=len(active),
            flops=2.0 * self._num_params * len(active),
            sink=self._step_accum) \
            if accel is not None else None
        with timer if timer is not None else contextlib.nullcontext():
            with self._mesh_scope():
                with (timer.device() if timer is not None
                      else contextlib.nullcontext()):
                    out, self.k_pages, self.v_pages = self._decode(
                        self.params, self.k_pages, self.v_pages,
                        jnp.asarray(block_tables), jnp.asarray(lengths),
                        jnp.asarray(tokens), key, jnp.asarray(temps),
                        jnp.asarray(top_ks), jnp.asarray(top_ps))
                    out = np.asarray(out)  # fences the dispatch
            if trace:
                compile_s = self._compile_total() - compile_t0
                if compile_s > 1e-6:
                    # every active request's wall clock contained the
                    # stall — charge it to each (why_slow's compile
                    # bucket, subtracted from its decode span)
                    for rid in trace_rids:
                        reqtrace.record(rid, reqtrace.COMPILE,
                                        compile_s=round(compile_s, 6),
                                        phase="decode")
            for i in active:
                seq = self.seqs[i]
                token = int(out[i])
                seq.generated.append(token)
                seq.last_token = token
                seq.length += 1
                self._tokens_generated += 1
                self._emit_token(seq, token)
                request = seq.request
                hit_eos = (cfg.eos_token is not None
                           and token == cfg.eos_token)
                # total includes tokens generated before a preemption
                # (empty resume on the legacy arm and fresh sequences)
                total_gen = len(seq.resume) + len(seq.generated)
                capacity = len(seq.pages) * cfg.page_size
                at_capacity = (not self._continuous
                               and seq.length + 1 >= capacity)
                if hit_eos \
                        or total_gen >= request.max_new_tokens \
                        or at_capacity \
                        or seq.length >= cfg.max_len - 1:
                    tokens = seq.resume + list(seq.generated)
                    finished.append((request, tokens))
                    callback = getattr(request, "_done_callback", None)
                    if callback is not None:
                        callback(request, tokens)
                    self._release(seq)
                    self.seqs[i] = _Seq()
            metrics = llm_metrics()
            metrics.token_latency.observe(time.monotonic() - tick_start,
                                          tags=_TAGS)
            metrics.decode_tokens.inc(len(active), tags=_TAGS)
            for request, _tokens in finished:
                metrics.requests_finished.inc(
                    tags=dict(_TAGS, outcome="done"))
                reqtrace.record(request.request_id, reqtrace.FINISHED,
                                tokens=len(_tokens))
                submit_ts = getattr(request, "_submit_ts", None)
                if submit_ts is not None:
                    metrics.request_latency.observe(
                        time.monotonic() - submit_ts, tags=_TAGS)
        return finished

    # -- conveniences ------------------------------------------------------

    def generate(self, prompts: List[List[int]],
                 max_new_tokens: int = 32,
                 timeout_s: float = 300.0) -> List[List[int]]:
        results: Dict[int, List[int]] = {}
        for i, prompt in enumerate(prompts):
            self.submit(GenerationRequest(
                prompt_tokens=prompt, max_new_tokens=max_new_tokens,
                request_id=str(i)))
        deadline = time.monotonic() + timeout_s
        while len(results) < len(prompts):
            if time.monotonic() > deadline:
                raise TimeoutError("generation timed out")
            for request, tokens in self.step():
                results[int(request.request_id)] = tokens
        return [results[i] for i in range(len(prompts))]

    def stats(self) -> Dict[str, Any]:
        if self._step_accum is not None:
            self._step_accum.flush()  # surfaces the partial window
        cache_bytes = (2 * self.config.model.num_layers *
                       int(np.prod(self.k_pages[0].shape)) *
                       self.k_pages[0].dtype.itemsize)
        param_bytes = sum(
            int(np.prod(p.shape)) * p.dtype.itemsize
            for p in jax.tree_util.tree_leaves(self.params))
        return {
            "steps": self._steps,
            "tokens_generated": self._tokens_generated,
            "active": sum(1 for s in self.seqs if s.request is not None),
            "pending": self._waiting_count(),
            "free_pages": self.pool.num_free(),
            "prefix_entries": (self.radix.entries
                               if self.radix is not None
                               else len(self.prefix_pages)),
            "prefix_hits": self._prefix_hits,
            "prefix_misses": self._prefix_misses,
            "preemptions": self._preemptions,
            "continuous": self._continuous,
            "tp": self._tp,
            "hbm_cache_bytes": cache_bytes,
            # per-chip residency: pages shard on kv_heads, params on
            # their logical axes — both divide by the tensor degree (the
            # fsdp/replicated leaves make this a ceiling for params)
            "hbm_cache_bytes_per_device": cache_bytes // self._tp,
            "hbm_param_bytes": param_bytes,
            "hbm_param_bytes_per_device": self._param_bytes_per_device(),
        }

    def _param_bytes_per_device(self) -> int:
        """Actual per-device parameter residency: sums each leaf's
        addressable shard size on device 0 (exact, not estimated)."""
        total = 0
        for p in jax.tree_util.tree_leaves(self.params):
            if hasattr(p, "sharding") and hasattr(p, "addressable_shards"):
                shard = p.addressable_shards[0]
                total += int(np.prod(shard.data.shape)) * p.dtype.itemsize
            else:
                total += int(np.prod(p.shape)) * p.dtype.itemsize
        return total
