"""Radix tree over KV pages: prefix sharing for the paged engine
(reference: SGLang RadixAttention / vLLM automatic prefix caching — the
prefix store is a tree keyed by page-sized token runs, each node owning
one refcounted physical page, so lookup cost scales with the match
length and eviction can drop cold leaves without touching hot ancestor
pages).

The tree holds a reference (via ``PagePool.incref``) on every page it
caches. ``match`` walks the tree for the longest cached prefix of a
prompt and hands the caller refcounted page ids — the caller maps them
into a block table copy-on-write style (the engine never writes a page
it does not own, so no copy is ever actually needed). ``insert`` commits
the full prompt pages of an admitted sequence. Eviction removes only
refcount-1 leaves (pages nothing else maps), oldest ``last_use`` first,
so an entry disappears only when both cold and unshared.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class _Node:
    __slots__ = ("key", "page", "parent", "children", "last_use")

    def __init__(self, key: Tuple[int, ...], page: int,
                 parent: Optional["_Node"]):
        self.key = key
        self.page = page
        self.parent = parent
        self.children: Dict[Tuple[int, ...], _Node] = {}
        self.last_use = 0


class RadixPrefixCache:
    """Prefix store over a :class:`PagePool`.

    One node = one full page of prompt tokens = one physical page id
    (ids are shared across layers, exactly like sequence block tables).
    ``max_entries`` is the node budget enforced after each insert;
    ``evict_pages`` frees pages on demand under pool pressure.
    """

    def __init__(self, pool, page_size: int, max_entries: int = 128):
        self._pool = pool
        self._page_size = page_size
        self.max_entries = max_entries
        self._root = _Node((), -1, None)
        self._clock = 0
        self.entries = 0
        self.hits = 0
        self.misses = 0

    # -- lookup / commit ---------------------------------------------------

    def _max_match_pages(self, tokens: List[int]) -> int:
        # Cap the match one token short of the prompt: at least one tail
        # token must prefill so the sequence has last-position logits to
        # sample its first token from (and the engine always owns the
        # page decode first writes into).
        return max(0, (len(tokens) - 1) // self._page_size)

    def match(self, tokens: List[int]) -> List[int]:
        """Longest cached prefix of ``tokens`` in whole pages. Returns
        the page ids with ONE REFERENCE EACH taken for the caller (drop
        with ``release`` if the caller cannot admit after all). Every
        node on the match path has its recency refreshed."""
        ps = self._page_size
        self._clock += 1
        node = self._root
        pages: List[int] = []
        for i in range(self._max_match_pages(tokens)):
            child = node.children.get(tuple(tokens[i * ps:(i + 1) * ps]))
            if child is None:
                break
            child.last_use = self._clock
            pages.append(child.page)
            node = child
        if pages:
            self.hits += 1
            for page in pages:
                self._pool.incref(page)
        elif len(tokens) // ps:
            # only a prompt with at least one full page can miss — a
            # short prompt has nothing the tree could have held
            self.misses += 1
        return pages

    def release(self, pages: List[int]):
        """Return references handed out by ``match``."""
        for page in pages:
            self._pool.decref(page)

    def insert(self, tokens: List[int], pages: List[int]) -> int:
        """Commit the full prompt pages of ``tokens`` (physical ids
        ``pages``, one per full page). Nodes already present keep their
        existing page (byte-identical by construction); new nodes take a
        reference on theirs. Returns the number of new nodes."""
        ps = self._page_size
        self._clock += 1
        node = self._root
        added = 0
        for i in range(len(tokens) // ps):
            key = tuple(tokens[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                child = _Node(key, pages[i], node)
                node.children[key] = child
                self._pool.incref(pages[i])
                self.entries += 1
                added += 1
            child.last_use = self._clock
            node = child
        self.evict(self.max_entries)
        return added

    # -- eviction ----------------------------------------------------------

    def _evictable_leaves(self) -> List[_Node]:
        out = []
        stack = [self._root]
        while stack:
            node = stack.pop()
            stack.extend(node.children.values())
            if node is not self._root and not node.children \
                    and self._pool.refs[node.page] == 1:
                out.append(node)
        return out

    def _drop(self, node: _Node):
        del node.parent.children[node.key]
        self._pool.decref(node.page)
        self.entries -= 1

    def evict(self, max_entries: Optional[int] = None) -> int:
        """Evict LRU refcount-1 leaves until at most ``max_entries``
        nodes remain (pinned/shared pages never move). Returns pages
        freed."""
        if max_entries is None:
            max_entries = self.max_entries
        freed = 0
        while self.entries > max_entries:
            leaves = self._evictable_leaves()
            if not leaves:
                break  # everything left is shared with a live sequence
            victim = min(leaves, key=lambda n: n.last_use)
            self._drop(victim)
            freed += 1
        return freed

    def evict_pages(self, want: int) -> int:
        """Pool-pressure path: free up to ``want`` pages by evicting LRU
        refcount-1 leaves regardless of the entry budget. Returns pages
        freed."""
        freed = 0
        while freed < want:
            leaves = self._evictable_leaves()
            if not leaves:
                break
            self._drop(min(leaves, key=lambda n: n.last_use))
            freed += 1
        return freed

    def clear(self) -> int:
        """Drop every unshared entry (pages mapped by live sequences
        stay). Returns pages freed."""
        return self.evict_pages(self.entries)

    # -- introspection -----------------------------------------------------

    def pages(self) -> List[int]:
        out = []
        stack = list(self._root.children.values())
        while stack:
            node = stack.pop()
            out.append(node.page)
            stack.extend(node.children.values())
        return out

    def shared_pages(self) -> int:
        """Cached pages currently also mapped by at least one live
        sequence (refcount above the tree's own reference)."""
        return sum(1 for p in self.pages() if self._pool.refs[p] > 1)
