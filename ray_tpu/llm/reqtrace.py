"""Per-request lifecycle tracing through the serve plane (the
serve-plane request observatory's recording layer — the twin of the
train-plane ``train/steptrace.py`` flight deck).

Every serving process (proxy, replica/engine) stamps bounded
per-request lifecycle events on the host-shared ``time.monotonic()``
clock into a per-process ring:

    QUEUED -> ADMITTED -> PREFILL_CHUNK* -> DECODE
           -> PREEMPTED/PARKED -> RESUMED -> ...
           -> FINISHED | CANCELLED | FAILED

plus ROUTED (proxy-side replica choice) and COMPILE (XLA compile stall
attributed to every request whose wall clock contained it, via the
accel-plane compile-seconds tracker delta). Events carry the request id
the proxy accepts or generates (``X-RTPU-Request-Id``, echoed back on
ndjson/SSE streams) and the optional tenant/route labels threaded down
through router -> replica -> engine.

Rings flush piggyback on the metrics flusher into the GCS KV
(ns ``reqtrace``, the steptrace pattern); the driver folds every
process's events into:

- a chrome-trace serve timeline (``state.serve_timeline()`` /
  ``cli timeline --serve`` / the dashboard Serve tab) — one row per
  request, spans for queue/prefill/park/decode with chunk and compile
  spans nested inside;
- ``why_slow(request_id)`` — TTFT and e2e latency decomposed into
  queue / prefill-compute / park / decode / XLA-compile / other
  buckets;
- per-tenant / per-route percentile folds (``cli requests
  --by-tenant``).

Kill switch: ``RTPU_NO_REQTRACE=1`` — ``record()`` degrades to one
flag check, no ring is ever constructed, nothing is flushed;
exact-legacy behavior.

This module is import-light on purpose (stdlib + config only): the
proxy and the dashboard fold requests without pulling jax.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from .._internal.config import CONFIG

logger = logging.getLogger(__name__)

REQTRACE_KV_NS = "reqtrace"

# lifecycle event names (the engine's request state machine)
QUEUED = "QUEUED"
ROUTED = "ROUTED"
ADMITTED = "ADMITTED"
PREFILL_CHUNK = "PREFILL_CHUNK"
DECODE = "DECODE"
PREEMPTED = "PREEMPTED"
PARKED = "PARKED"
RESUMED = "RESUMED"
FINISHED = "FINISHED"
CANCELLED = "CANCELLED"
FAILED = "FAILED"
COMPILE = "COMPILE"

TERMINAL = frozenset({FINISHED, CANCELLED, FAILED})

REQUEST_ID_HEADER = "x-rtpu-request-id"
TENANT_HEADER = "x-rtpu-tenant"
ROUTE_HEADER = "x-rtpu-route"


def reqtrace_disabled() -> bool:
    return bool(CONFIG.no_reqtrace)


# ---------------------------------------------------------------------------
# recorder
# ---------------------------------------------------------------------------


class _Recorder:
    """Bounded per-process lifecycle-event ring. An event is
    ``(request_id, event, ts, args)`` on the shared monotonic clock;
    overflow drops the oldest — steady-state serving keeps the tail."""

    def __init__(self):
        self._lock = threading.Lock()
        self._events: deque = deque(
            maxlen=int(CONFIG.reqtrace_max_events))

    def record(self, request_id: str, event: str, ts: float,
               args: Dict[str, Any]):
        with self._lock:
            self._events.append((request_id, event, float(ts), args))

    def events(self) -> List[tuple]:
        with self._lock:
            return list(self._events)

    def payload(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "pid": os.getpid(),
                "events": [[r, e, t, a] for r, e, t, a in self._events],
            }

    def clear(self):
        with self._lock:
            self._events.clear()


# Lazy singleton: under the kill switch record() returns before ever
# touching this, so a disabled process holds ZERO rings (what the
# kill-switch subprocess test asserts).
_RECORDER: Optional[_Recorder] = None
_recorder_lock = threading.Lock()


def _recorder() -> _Recorder:
    global _RECORDER
    if _RECORDER is None:
        with _recorder_lock:
            if _RECORDER is None:
                _RECORDER = _Recorder()
    return _RECORDER


def record(request_id: Optional[str], event: str, **args) -> None:
    """Stamp one lifecycle event (shared monotonic clock). Args must be
    JSON-serializable scalars; None values are dropped. One flag check
    and nothing else under the kill switch."""
    if reqtrace_disabled() or not request_id:
        return
    _recorder().record(
        str(request_id), event, time.monotonic(),
        {k: v for k, v in args.items() if v is not None})


def events() -> List[tuple]:
    """This process's recorded events (empty if the ring was never
    constructed)."""
    if _RECORDER is None:
        return []
    return _RECORDER.events()


def clear():
    if _RECORDER is not None:
        _RECORDER.clear()


# ---------------------------------------------------------------------------
# flush / collect (the steptrace GCS-KV pattern)
# ---------------------------------------------------------------------------


def flush(gcs=None, key: Optional[str] = None) -> bool:
    """Push this process's event ring into the GCS KV (ns ``reqtrace``)
    under a per-process key. Called piggyback from the metrics flusher
    (util/metrics.flush_now); best-effort, returns False when disabled,
    empty, or no GCS is reachable."""
    if reqtrace_disabled() or _RECORDER is None:
        return False
    try:
        import json
        if gcs is None:
            from .._internal.core_worker import try_get_core_worker
            worker = try_get_core_worker()
            if worker is None:
                return False
            gcs = worker.gcs
        if key is None:
            key = str(os.getpid())
        gcs.put(REQTRACE_KV_NS, key,
                json.dumps(_RECORDER.payload()).encode())
        return True
    except Exception:  # noqa: BLE001 — observability is best-effort
        logger.debug("reqtrace flush failed", exc_info=True)
        return False


def collect(gcs) -> List[Dict[str, Any]]:
    """Every process's flushed payload from the GCS KV (driver side)."""
    import json
    out = []
    for key in gcs.keys(REQTRACE_KV_NS, ""):
        raw = gcs.get(REQTRACE_KV_NS, key)
        if raw:
            try:
                out.append(json.loads(raw.decode()))
            except ValueError:
                pass
    return out


# ---------------------------------------------------------------------------
# folds: per-request lifecycle -> spans / buckets / percentiles
# ---------------------------------------------------------------------------


def request_events(payloads: List[Dict[str, Any]]
                   ) -> Dict[str, List[Dict[str, Any]]]:
    """request id -> time-ordered event dicts (cross-process merge: a
    request's ROUTED event comes from the proxy's ring, the rest from
    the engine's)."""
    out: Dict[str, List[Dict[str, Any]]] = {}
    for payload in payloads:
        pid = payload.get("pid")
        for row in payload.get("events", []):
            rid, event, ts, args = row
            out.setdefault(str(rid), []).append(
                {"event": event, "ts": float(ts), "pid": pid,
                 "args": args or {}})
    for rows in out.values():
        rows.sort(key=lambda r: r["ts"])
    return out


def _clip(t0: float, t1: float, hi: Optional[float]) -> float:
    """Length of [t0, t1] clipped to end at hi (None = no clip)."""
    if hi is not None:
        t1 = min(t1, hi)
    return max(0.0, t1 - t0)


def _buckets(rows: List[Dict[str, Any]], end: float,
             hi: Optional[float] = None) -> Dict[str, float]:
    """Decompose one request's wall clock over [QUEUED, min(end, hi)]
    into queue / prefill_compute / park / decode / compile / other.
    ``hi=first_token_ts`` gives the TTFT decomposition; ``hi=None`` the
    e2e one. Invariant: buckets sum to the clipped wall clock (other
    absorbs scheduler gaps between prefill chunks and unmatched
    intervals)."""
    out = {"queue": 0.0, "prefill_compute": 0.0, "park": 0.0,
           "decode": 0.0, "compile": 0.0, "other": 0.0}
    queued_ts = rows[0]["ts"]
    state = "queue"          # queue | park | prefill | decode
    state_t0 = queued_ts
    window_total = 0.0       # prefill-window time (ADMITTED -> DECODE)

    def close(until: float):
        nonlocal window_total
        span = _clip(state_t0, until, hi)
        if state == "queue":
            out["queue"] += span
        elif state == "park":
            out["park"] += span
        elif state == "decode":
            out["decode"] += span
        elif state == "prefill":
            window_total += span

    for row in rows:
        event, ts = row["event"], row["ts"]
        args = row["args"]
        if event in (ADMITTED,):
            close(ts)
            state, state_t0 = "prefill", ts
        elif event == PARKED:
            close(ts)
            state, state_t0 = "park", ts
        elif event == DECODE:
            close(ts)
            state, state_t0 = "decode", ts
        elif event in TERMINAL:
            close(ts)
            state, state_t0 = "done", ts
        elif event == PREFILL_CHUNK:
            dur = float(args.get("dur_s", 0.0))
            comp = float(args.get("compile_s", 0.0))
            # clip chunk work to the window: a chunk straddling hi
            # charges only its pre-hi share
            t0 = ts - dur
            frac = _clip(t0, ts, hi) / dur if dur > 0 else 0.0
            out["prefill_compute"] += max(0.0, (dur - comp)) * frac
            out["compile"] += comp * frac
        elif event == COMPILE:
            dur = float(args.get("compile_s", 0.0))
            t0 = ts - dur
            covered = _clip(t0, ts, hi)
            out["compile"] += covered
            # decode-phase compile stalls sit inside the decode span
            out["decode"] -= min(out["decode"], covered)
    if state not in ("done",):
        close(end)
    # prefill-window time not spent computing or compiling is scheduler
    # interleave (decode ticks of OTHER requests sharing the engine)
    out["other"] += max(
        0.0, window_total - out["prefill_compute"] - out["compile"])
    for k in out:
        out[k] = round(out[k], 6)
    return out


def lifecycle(rows: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Fold one request's ordered events into the report both the
    timeline and ``why_slow`` build on."""
    # Anchor at the EARLIEST observed event, not the engine's QUEUED:
    # when the proxy's ROUTED precedes it, the routing gap is real
    # client-perceived latency and must land in the queue bucket —
    # otherwise the bucket sums drift from ttft_s/e2e_s by that gap.
    queued_ts = rows[0]["ts"]
    labels = {}
    outcome = None
    end_ts = rows[-1]["ts"]
    first_token_ts = None
    preemptions = 0
    prefill_tokens = 0
    shared_pages = 0
    for row in rows:
        event, args = row["event"], row["args"]
        if event == QUEUED:
            for k in ("tenant", "route"):
                if args.get(k):
                    labels[k] = args[k]
        elif event == ROUTED and args.get("route") and \
                "route" not in labels:
            labels["route"] = args["route"]
        elif event == DECODE and first_token_ts is None:
            first_token_ts = row["ts"]
        elif event == PREEMPTED:
            preemptions += 1
        elif event == PREFILL_CHUNK:
            prefill_tokens += int(args.get("tokens", 0))
        elif event == ADMITTED:
            shared_pages = max(shared_pages,
                               int(args.get("shared_pages", 0)))
        if event in TERMINAL:
            outcome = event
            end_ts = row["ts"]
    report: Dict[str, Any] = {
        "queued_ts": queued_ts,
        "end_ts": end_ts,
        "outcome": outcome,
        "tenant": labels.get("tenant"),
        "route": labels.get("route"),
        "preemptions": preemptions,
        "prefill_tokens": prefill_tokens,
        "shared_pages": shared_pages,
        "e2e_s": round(end_ts - queued_ts, 6),
        "e2e_buckets": _buckets(rows, end_ts),
    }
    if first_token_ts is not None:
        report["ttft_s"] = round(first_token_ts - queued_ts, 6)
        report["ttft_buckets"] = _buckets(rows, end_ts,
                                          hi=first_token_ts)
    return report


def to_chrome_trace(payloads: List[Dict[str, Any]]
                    ) -> List[Dict[str, Any]]:
    """The serve timeline: chrome-trace rows (ph:"X", ts/dur in µs on
    the shared monotonic clock), pid = "serve", one tid per request id
    — queue/park/prefill/decode state spans with prefill-chunk and
    compile spans nested by time containment, PREEMPTED/ROUTED as
    instant events."""
    rows: List[Dict[str, Any]] = []
    for rid, evs in sorted(request_events(payloads).items()):
        state = None
        state_t0 = None
        state_args: Dict[str, Any] = {}

        def emit(name, t0, t1, args=None):
            rows.append({
                "name": name, "cat": "reqtrace", "ph": "X",
                "ts": t0 * 1e6, "dur": max(0.0, t1 - t0) * 1e6,
                "pid": "serve", "tid": rid,
                "args": dict(args or {}, request=rid),
            })

        for row in evs:
            event, ts, args = row["event"], row["ts"], row["args"]
            transition = {QUEUED: "queue", ADMITTED: "prefill",
                          PARKED: "park", DECODE: "decode"}.get(event)
            if transition is not None or event in TERMINAL:
                if state is not None:
                    emit(state, state_t0, ts, state_args)
                state = transition  # None on terminal
                state_t0 = ts
                state_args = args
            if event == PREFILL_CHUNK:
                dur = float(args.get("dur_s", 0.0))
                emit("prefill_chunk", ts - dur, ts, args)
            elif event == COMPILE:
                dur = float(args.get("compile_s", 0.0))
                emit("xla_compile", ts - dur, ts, args)
            elif event in (PREEMPTED, RESUMED, ROUTED) \
                    or event in TERMINAL:
                rows.append({
                    "name": event.lower(), "cat": "reqtrace",
                    "ph": "i", "ts": ts * 1e6, "s": "t",
                    "pid": "serve", "tid": rid,
                    "args": dict(args, request=rid),
                })
    rows.sort(key=lambda r: (str(r["tid"]), r["ts"]))
    return rows


def why_slow(request_id: str,
             payloads: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Latency attribution for one request: TTFT and e2e decomposed
    into queue / prefill-compute / park / decode / compile / other
    seconds, next to the raw lifecycle events. A request-id PREFIX is
    accepted when unambiguous."""
    by_rid = request_events(payloads)
    rows = by_rid.get(str(request_id))
    if rows is None:
        matches = [r for r in by_rid if r.startswith(str(request_id))]
        if len(matches) != 1:
            return {"error": f"request {request_id!r} matched "
                             f"{len(matches)} traced requests"}
        request_id = matches[0]
        rows = by_rid[request_id]
    report = lifecycle(rows)
    report["request_id"] = request_id
    report["events"] = [
        {"event": r["event"],
         "t_s": round(r["ts"] - report["queued_ts"], 6),
         **({k: v for k, v in r["args"].items()})}
        for r in rows]
    return report


def _percentile(values: List[float], q: float) -> Optional[float]:
    if not values:
        return None
    ordered = sorted(values)
    return round(ordered[min(len(ordered) - 1,
                             int(q * len(ordered)))], 6)


def fold_requests(payloads: List[Dict[str, Any]],
                  by: Optional[str] = None) -> Dict[str, Any]:
    """Percentile fold over every traced request, optionally grouped
    ``by`` "tenant" or "route" (unlabeled requests fold under "-").
    The ``cli requests`` / dashboard Serve-tab surface."""
    groups: Dict[str, List[Dict[str, Any]]] = {}
    for rid, rows in request_events(payloads).items():
        report = lifecycle(rows)
        report["request_id"] = rid
        key = "-"
        if by in ("tenant", "route"):
            key = report.get(by) or "-"
        groups.setdefault(key, []).append(report)
    out: Dict[str, Any] = {"by": by or "all", "groups": {}}
    for key, reports in sorted(groups.items()):
        ttfts = [r["ttft_s"] for r in reports if "ttft_s" in r]
        e2es = [r["e2e_s"] for r in reports
                if r["outcome"] == FINISHED]
        park = sum(r["e2e_buckets"]["park"] for r in reports)
        out["groups"][key] = {
            "requests": len(reports),
            "finished": sum(1 for r in reports
                            if r["outcome"] == FINISHED),
            "cancelled": sum(1 for r in reports
                             if r["outcome"] == CANCELLED),
            "failed": sum(1 for r in reports
                          if r["outcome"] == FAILED),
            "in_flight": sum(1 for r in reports
                             if r["outcome"] is None),
            "preemptions": sum(r["preemptions"] for r in reports),
            "park_s_total": round(park, 6),
            "ttft_p50_s": _percentile(ttfts, 0.5),
            "ttft_p95_s": _percentile(ttfts, 0.95),
            "e2e_p50_s": _percentile(e2es, 0.5),
            "e2e_p95_s": _percentile(e2es, 0.95),
        }
    return out
