"""Token sampling: temperature + top-k + nucleus (top-p), fully inside
jit (reference role: vLLM's sampler — the reference delegates serving
to vLLM, whose Sampler applies temperature/top_k/top_p per sequence;
here the same contract as ONE vectorized XLA program over the batch).

TPU notes: per-slot parameters arrive as [B] arrays so one compiled
program serves heterogeneous requests (no per-request recompiles).
The top-p mask needs a descending sort of the vocab — O(V log V) on
rows of 32k is microseconds on the VPU next to the decode matmuls."""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample_tokens(rng, logits, temperature, top_k, top_p):
    """One token per row.

    logits: [B, V] float32. temperature/top_k/top_p: [B] — per slot:
    temperature <= 0 means greedy (top_k/top_p ignored); top_k <= 0
    disables the k filter; top_p >= 1 disables the nucleus filter.
    Filters compose the standard way: restrict to the top-k set, then
    to the smallest prefix of the (sorted) distribution whose mass
    reaches top_p, renormalize implicitly via categorical."""
    greedy = jnp.argmax(logits, axis=-1)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]

    sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]
    V = scaled.shape[-1]

    # top-k threshold: value of the k-th largest entry (k<=0 -> -inf)
    k = jnp.clip(top_k.astype(jnp.int32), 0, V)
    k_idx = jnp.maximum(k - 1, 0)
    k_thresh = jnp.take_along_axis(sorted_desc, k_idx[:, None],
                                   axis=-1)[:, 0]
    k_thresh = jnp.where(k > 0, k_thresh, NEG_INF)

    # top-p threshold: smallest sorted value still inside the nucleus.
    # A position belongs to the nucleus while the mass of STRICTLY
    # higher-ranked tokens is < p (so the token crossing p is included,
    # matching the usual implementation).
    probs_sorted = jax.nn.softmax(sorted_desc, axis=-1)
    cum_before = jnp.cumsum(probs_sorted, axis=-1) - probs_sorted
    # clip away from 0: cum_before[0] == 0 < p keeps the top token even
    # for top_p=0 (every standard sampler keeps at least one token)
    in_nucleus = cum_before < jnp.clip(top_p, 1e-6, 1.0)[:, None]
    p_thresh = jnp.min(jnp.where(in_nucleus, sorted_desc, jnp.inf),
                       axis=-1)
    p_thresh = jnp.where(top_p >= 1.0, NEG_INF, p_thresh)

    thresh = jnp.maximum(k_thresh, p_thresh)
    masked = jnp.where(scaled >= thresh[:, None], scaled, NEG_INF)
    sampled = jax.random.categorical(rng, masked)
    return jnp.where(temperature > 0, sampled, greedy)


def filter_logits(logits, top_k=0, top_p=None):
    """Host-side (numpy) mirror of sample_tokens' top-k/top-p filters —
    the single implementation both engines' prefill first-token sampling
    uses, so host and jit paths stay in lockstep. top_p <= 0 keeps the
    top token (never an empty nucleus)."""
    import numpy as np
    scaled = np.asarray(logits, np.float64)
    sorted_desc = np.sort(scaled)[::-1]
    thresh = -np.inf
    if top_k and top_k > 0:
        thresh = max(thresh,
                     sorted_desc[min(int(top_k), len(sorted_desc)) - 1])
    if top_p is not None and top_p < 1.0:
        p = max(float(top_p), 1e-6)
        sp = np.exp(sorted_desc - sorted_desc.max())
        sp /= sp.sum()
        cum_before = np.cumsum(sp) - sp
        nucleus = sorted_desc[cum_before < p]  # cum_before[0]=0 < p
        thresh = max(thresh, nucleus[-1])
    return np.where(scaled >= thresh, scaled, -1e30)
