"""LLM serving deployment
(reference: llm/_internal/serve/deployments/llm/ — the vLLM server class;
builders serve/llm/__init__.py:92 build_llm_deployment. Here the engine
is in-process and TPU-native instead of a vLLM subprocess.)

The deployment's asyncio loop drives the engine: requests enqueue into
the engine's scheduler and await completion futures; one background task
steps the engine whenever work is pending — iteration-level (continuous)
batching across concurrent HTTP/handle requests."""

from __future__ import annotations

import asyncio
import logging
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class LLMServer:
    """The replica callable (wrapped by serve.deployment)."""

    def __init__(self, engine_config, params=None):
        from .engine import LLMEngine
        self._engine = LLMEngine(engine_config, params=params)
        self._loop_task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None

    def _ensure_loop(self):
        if self._loop_task is None or self._loop_task.done():
            self._wake = asyncio.Event()
            self._loop_task = asyncio.ensure_future(self._drive())

    async def _drive(self):
        loop = asyncio.get_running_loop()
        while True:
            if not self._engine.has_work():
                self._wake.clear()
                await self._wake.wait()
            # One engine tick off-loop (it blocks on device compute).
            try:
                await loop.run_in_executor(None, self._engine.step)
            except Exception:  # noqa: BLE001 — keep serving other requests
                logger.exception("engine step failed")
                await asyncio.sleep(0.1)

    async def generate(self, prompt_tokens: List[int],
                       max_new_tokens: int = 32) -> Dict[str, Any]:
        from .engine import GenerationRequest
        self._ensure_loop()
        loop = asyncio.get_running_loop()
        future = loop.create_future()

        def on_done(request, tokens):
            def _resolve():
                if future.done():
                    return
                if isinstance(tokens, Exception):
                    future.set_exception(tokens)
                else:
                    future.set_result(tokens)
            loop.call_soon_threadsafe(_resolve)

        request = GenerationRequest(prompt_tokens=list(prompt_tokens),
                                    max_new_tokens=max_new_tokens)
        self._engine.submit(request, done_callback=on_done)
        self._wake.set()
        tokens = await future
        return {"tokens": tokens, "num_generated": len(tokens)}

    async def __call__(self, http_request) -> Dict[str, Any]:
        body = http_request.json()
        return await self.generate(
            body["prompt_tokens"],
            max_new_tokens=int(body.get("max_new_tokens", 32)))

    def engine_stats(self) -> Dict[str, Any]:
        return self._engine.stats()


def build_llm_deployment(engine_config, *, name: str = "LLMServer",
                         num_replicas: int = 1, params=None,
                         max_ongoing_requests: int = 64):
    """Serve application for the engine
    (reference: serve/llm/__init__.py:92 build_llm_deployment)."""
    from .. import serve
    deployment = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests)
    return deployment.bind(engine_config, params)
