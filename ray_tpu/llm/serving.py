"""LLM serving deployment
(reference: llm/_internal/serve/deployments/llm/ — the vLLM server class;
builders llm/_internal/serve/builders/application_builders.py:19,60 →
public serve/llm/__init__.py:92 build_llm_deployment, :168 build_openai_app.
Here the engine is in-process and TPU-native instead of a vLLM subprocess.)

The deployment's asyncio loop drives the engine: requests enqueue into
the engine's scheduler and await completion futures; one background task
steps the engine whenever work is pending — iteration-level (continuous)
batching across concurrent HTTP/handle requests.

Streaming: tokens are pushed from the engine's token callbacks into
per-request stream buffers; the HTTP proxy long-polls `stream_next` on
the SAME replica and relays chunked HTTP (reference streams via ASGI
from the replica; the long-poll hop keeps the data plane on the actor
RPC plane with batched token delivery)."""

from __future__ import annotations

import asyncio
import logging
import uuid
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)


class _Stream:
    __slots__ = ("tokens", "event", "done", "error", "request_id")

    def __init__(self, request_id: str):
        self.tokens: List[int] = []
        self.event = asyncio.Event()
        self.done = False
        self.error: Optional[str] = None
        self.request_id = request_id


class LLMServer:
    """The replica callable (wrapped by serve.deployment).

    `engine_config` picks the engine: a `PagedEngineConfig` runs the
    paged-KV continuous-batching engine (the default TPU serving path —
    prefix page sharing, chunked prefill to max_len); an `EngineConfig`
    runs the static-slot engine.

    `mesh_config` (a `parallel.MeshConfig`, e.g. tensor=4) shards the
    paged engine's params + KV pages over the replica's chips — the
    tensor-parallel analog of the reference's TP×PP engine-worker
    bundles (vllm_models.py:169-178,251)."""

    def __init__(self, engine_config, params=None, mesh_config=None):
        from .engine import EngineConfig, LLMEngine
        from .paged import PagedEngineConfig, PagedLLMEngine
        mesh = None
        if mesh_config is not None:
            if not isinstance(engine_config, PagedEngineConfig):
                raise ValueError(
                    "mesh_config requires the paged engine "
                    "(PagedEngineConfig) — the static-slot engine does "
                    "not shard")
            mesh = self._build_mesh(mesh_config)
        if isinstance(engine_config, PagedEngineConfig):
            self._engine = PagedLLMEngine(engine_config, params=params,
                                          mesh=mesh)
            self._paged = True
        elif isinstance(engine_config, EngineConfig):
            self._engine = LLMEngine(engine_config, params=params)
            self._paged = False
        else:
            raise TypeError(
                f"engine_config must be PagedEngineConfig or EngineConfig, "
                f"got {type(engine_config).__name__}")
        self._loop_task: Optional[asyncio.Task] = None
        self._wake: Optional[asyncio.Event] = None
        self._streams: Dict[str, _Stream] = {}

    @staticmethod
    def _build_mesh(mesh_config):
        """Build the replica's device mesh: exactly the devices the
        config's fixed axes need (a replica may own a subset of the
        host's chips). A wildcard axis (the MeshConfig default data=-1)
        is pinned to 1 — an engine replica must not silently absorb
        every visible chip into a data axis it would only replicate
        over; scale-out across chips-beyond-TP belongs to
        num_replicas."""
        import dataclasses as _dc
        import math as _math
        import jax
        sizes = {"data": mesh_config.data, "fsdp": mesh_config.fsdp,
                 "tensor": mesh_config.tensor,
                 "sequence": mesh_config.sequence,
                 "pipeline": mesh_config.pipeline,
                 "expert": mesh_config.expert}
        wild = [k for k, v in sizes.items() if v == -1]
        if wild:
            mesh_config = _dc.replace(mesh_config,
                                      **{k: 1 for k in wild})
            for k in wild:
                sizes[k] = 1
        needed = _math.prod(sizes.values())
        devices = jax.devices()
        if len(devices) < needed:
            raise ValueError(
                f"mesh needs {needed} devices, replica sees "
                f"{len(devices)}")
        return mesh_config.build(devices[:needed])

    # -- engine drive ------------------------------------------------------

    def _ensure_loop(self):
        if self._loop_task is None or self._loop_task.done():
            self._wake = asyncio.Event()
            self._loop_task = asyncio.ensure_future(self._drive())

    async def _drive(self):
        loop = asyncio.get_running_loop()
        while True:
            if not self._engine.has_work():
                self._wake.clear()
                await self._wake.wait()
            # One engine tick off-loop (it blocks on device compute).
            try:
                await loop.run_in_executor(None, self._engine.step)
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                # Fail the in-flight requests LOUDLY: a deterministic step
                # failure (bad kernel shape, OOM) would otherwise spin
                # here forever while callers hang on their futures.
                logger.exception("engine step failed")
                try:
                    self._engine.fail_all(e)
                except Exception:  # noqa: BLE001
                    logger.debug("fail_all after engine step failure "
                                 "raised", exc_info=True)
                await asyncio.sleep(0.1)

    @staticmethod
    def _context():
        """Proxy-stamped request context (request id + tenant/route
        labels) of the serve call being handled — empty off-replica."""
        from ..serve.context import get_request_context
        return get_request_context()

    @classmethod
    def _context_request_id(cls) -> str:
        return cls._context().request_id

    async def _submit(self, request, done_callback, token_callback=None):
        # async so subclasses can do remote work first (PD-disagg fetches
        # the prefilled KV from the prefill deployment here)
        self._ensure_loop()
        if self._paged:
            self._engine.submit(request, done_callback=done_callback,
                                token_callback=token_callback)
        else:
            self._engine.submit(request, done_callback=done_callback)
        self._wake.set()

    # -- one-shot generation ----------------------------------------------

    async def generate(self, prompt_tokens: List[int],
                       max_new_tokens: int = 32,
                       temperature: Optional[float] = None,
                       top_k: Optional[int] = None,
                       top_p: Optional[float] = None,
                       request_id: Optional[str] = None,
                       tenant: Optional[str] = None,
                       route: Optional[str] = None) -> Dict[str, Any]:
        from .engine import GenerationRequest
        loop = asyncio.get_running_loop()
        future = loop.create_future()

        def on_done(request, tokens):
            def _resolve():
                if future.done():
                    return
                if isinstance(tokens, Exception):
                    future.set_exception(tokens)
                elif tokens is None:  # cancelled
                    future.set_result(None)
                else:
                    future.set_result(tokens)
            loop.call_soon_threadsafe(_resolve)

        request = GenerationRequest(
            prompt_tokens=list(prompt_tokens),
            max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            request_id=request_id or self._context_request_id()
            or uuid.uuid4().hex,
            tenant=tenant or self._context().tenant,
            route=route or self._context().route)
        from ._metrics import llm_metrics
        await self._submit(request, on_done)
        try:
            tokens = await future
        except Exception:
            llm_metrics().server_requests.inc(
                tags={"entry": "generate", "outcome": "error"})
            raise
        llm_metrics().server_requests.inc(
            tags={"entry": "generate",
                  "outcome": "cancelled" if tokens is None else "ok"})
        if tokens is None:
            return {"tokens": [], "num_generated": 0, "cancelled": True}
        return {"tokens": tokens, "num_generated": len(tokens)}

    # -- streaming ---------------------------------------------------------

    async def generate_stream_start(
            self, prompt_tokens: List[int], max_new_tokens: int = 32,
            temperature: Optional[float] = None,
            top_k: Optional[int] = None,
            top_p: Optional[float] = None,
            request_id: Optional[str] = None,
            tenant: Optional[str] = None,
            route: Optional[str] = None) -> str:
        """Begin a streamed generation; returns a stream id the caller
        polls with `stream_next` (the proxy relays it as chunked HTTP)."""
        from .engine import GenerationRequest
        if not self._paged:
            raise RuntimeError("streaming requires the paged engine")
        loop = asyncio.get_running_loop()
        request_id = request_id or self._context_request_id() \
            or uuid.uuid4().hex
        stream_id = uuid.uuid4().hex
        stream = _Stream(request_id)
        self._streams[stream_id] = stream

        def on_token(request, token):
            def _push():
                stream.tokens.append(int(token))
                stream.event.set()
            loop.call_soon_threadsafe(_push)

        def on_done(request, tokens):
            def _finish():
                # outcome counted at COMPLETION, not submit — a stream
                # that errors or is cancelled must not read as "ok"
                from ._metrics import llm_metrics
                if isinstance(tokens, Exception):
                    stream.error = str(tokens)
                    outcome = "error"
                elif tokens is None:
                    outcome = "cancelled"
                else:
                    outcome = "ok"
                llm_metrics().server_requests.inc(
                    tags={"entry": "stream", "outcome": outcome})
                stream.done = True
                stream.event.set()
            loop.call_soon_threadsafe(_finish)

        request = GenerationRequest(
            prompt_tokens=list(prompt_tokens),
            max_new_tokens=max_new_tokens,
            temperature=temperature, top_k=top_k, top_p=top_p,
            request_id=request_id,
            tenant=tenant or self._context().tenant,
            route=route or self._context().route)
        await self._submit(request, on_done, token_callback=on_token)
        return stream_id

    async def stream_next(self, stream_id: str,
                          timeout_s: float = 10.0) -> Dict[str, Any]:
        """Long-poll: the next batch of tokens (whatever has accumulated
        since the last call), plus the done flag. Empty batch on timeout."""
        stream = self._streams.get(stream_id)
        if stream is None:
            return {"tokens": [], "done": True, "error": "unknown stream"}
        if not stream.tokens and not stream.done:
            stream.event.clear()
            try:
                await asyncio.wait_for(stream.event.wait(), timeout_s)
            except asyncio.TimeoutError:
                pass
        tokens, stream.tokens = stream.tokens, []
        done = stream.done and not stream.tokens
        # every batch echoes the request id so clients can correlate
        # chunks (and why_slow the request) mid-stream
        out = {"tokens": tokens, "done": done,
               "request_id": stream.request_id}
        if stream.error:
            out["error"] = stream.error
        if done:
            self._streams.pop(stream_id, None)
        return out

    async def cancel_stream(self, stream_id: str) -> bool:
        stream = self._streams.pop(stream_id, None)
        if stream is None:
            return False
        return await self.cancel(stream.request_id)

    async def cancel(self, request_id: str) -> bool:
        """Abort a running or queued request (paged engine only)."""
        if not self._paged:
            return False
        ok = self._engine.cancel(request_id)
        if self._wake is not None:
            self._wake.set()
        return ok

    # -- HTTP entry --------------------------------------------------------

    async def __call__(self, http_request) -> Dict[str, Any]:
        body = http_request.json()
        prompt = body.get("prompt_tokens")
        if prompt is None:
            raise ValueError("body must contain prompt_tokens")
        max_new = int(body.get("max_new_tokens", 32))
        temp = body.get("temperature")
        headers = getattr(http_request, "headers", None) or {}
        request_id = body.get("request_id") \
            or headers.get("x-rtpu-request-id")
        tenant = body.get("tenant") or headers.get("x-rtpu-tenant")
        route = headers.get("x-rtpu-route")
        if body.get("stream"):
            stream_id = await self.generate_stream_start(
                prompt, max_new_tokens=max_new, temperature=temp,
                request_id=request_id, tenant=tenant, route=route)
            # The proxy recognises this marker and relays stream_next
            # batches as chunked HTTP on the same replica.
            return {"__rtpu_stream__": stream_id}
        return await self.generate(
            prompt, max_new_tokens=max_new, temperature=temp,
            request_id=request_id, tenant=tenant, route=route)

    def engine_stats(self) -> Dict[str, Any]:
        return self._engine.stats()

    def autoscaling_metrics(self) -> Dict[str, Any]:
        """Replica autoscaling hook (replica.get_metrics() folds this
        into the controller's closed loop): the engine's waiting-queue
        depth, median TTFT, and KV page occupancy."""
        hook = getattr(self._engine, "autoscaling_metrics", None)
        if hook is None:
            return {}
        return dict(hook())


def build_llm_deployment(engine_config, *, name: str = "LLMServer",
                         num_replicas: int = 1, params=None,
                         max_ongoing_requests: int = 64,
                         mesh_config=None):
    """Serve application for the engine
    (reference: serve/llm/__init__.py:92 build_llm_deployment)."""
    from .. import serve
    deployment = serve.deployment(
        LLMServer, name=name, num_replicas=num_replicas,
        max_ongoing_requests=max_ongoing_requests)
    return deployment.bind(engine_config, params, mesh_config)
