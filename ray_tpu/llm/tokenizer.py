"""Native BPE tokenizer: load real checkpoints' `tokenizer.json` without
any network or external runtime.

Reference analog: the reference delegates tokenization to the HF
tokenizers runtime inside vLLM
(llm/_internal/serve/deployments/llm/vllm/vllm_engine.py); here the
serving path owns a dependency-free BPE so a checkpoint directory
(weights + tokenizer.json) serves verbatim even in stripped-down worker
images. When `transformers`/`tokenizers` are importable they can be used
instead via `get_tokenizer` — same duck-typed encode/decode surface.

Two pre-tokenization schemes cover the common checkpoint families:

- ``byte_level`` (GPT-2 / Llama-3 style): text is regex-split into
  words, each word's UTF-8 bytes are mapped through the GPT-2
  byte→unicode table, and BPE merges run per word. NOTE: the split
  pattern approximates ``\\p{L}``/``\\p{N}`` with Python's ``re``
  unicode classes — exact for ASCII and common scripts, may diverge on
  exotic numerals (Roman numerals, superscripts).
- ``metaspace`` (SentencePiece-BPE / Llama-2 style): whitespace becomes
  the ``▁`` marker, BPE merges run per whitespace-delimited chunk, and
  characters absent from the vocab fall back to ``<0xNN>`` byte tokens.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, Iterable, List, Optional, Tuple

__all__ = ["BPETokenizer", "ByteTokenizer", "get_tokenizer"]

_METASPACE = "▁"  # ▁


class ByteTokenizer:
    """Dependency-free fallback: UTF-8 bytes as token ids (vocab 256)."""

    vocab_size = 256

    def encode(self, text: str) -> List[int]:
        return list(text.encode("utf-8"))

    def decode(self, tokens: Iterable[int]) -> str:
        return bytes(t for t in tokens if 0 <= t < 256).decode(
            "utf-8", "replace")


def _bytes_to_unicode() -> Dict[int, str]:
    """GPT-2's reversible byte→printable-unicode table: printable bytes
    map to themselves, the rest shift into U+0100+."""
    bs = (list(range(ord("!"), ord("~") + 1)) +
          list(range(0xA1, 0xAD)) + list(range(0xAE, 0x100)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


_BYTE_ENC = _bytes_to_unicode()
_BYTE_DEC = {v: k for k, v in _BYTE_ENC.items()}

# GPT-2 word-split pattern, with \p{L} ~ [^\W\d_] and \p{N} ~ \d.
# Underscore is folded into the punctuation branch so no char is dropped.
_BYTE_LEVEL_PAT = re.compile(
    r"'s|'t|'re|'ve|'m|'ll|'d"
    r"| ?[^\W\d_]+"
    r"| ?\d+"
    r"| ?(?:[^\s\w]|_)+"
    r"|\s+(?!\S)|\s+")

_BYTE_FALLBACK_PAT = re.compile(r"<0x([0-9A-Fa-f]{2})>")


class BPETokenizer:
    """Greedy rank-ordered BPE over a fixed vocab + merge table."""

    def __init__(self, vocab: Dict[str, int],
                 merges: List[Tuple[str, str]],
                 scheme: str = "byte_level",
                 special_tokens: Optional[Dict[str, int]] = None,
                 add_prefix_space: bool = True,
                 unk_token: Optional[str] = None,
                 non_special_added: Optional[Dict[str, int]] = None,
                 prepend_scheme: str = "always"):
        if scheme not in ("byte_level", "metaspace"):
            raise ValueError(f"unknown scheme {scheme!r}")
        self.vocab = vocab
        self.scheme = scheme
        self.ranks = {pair: i for i, pair in enumerate(merges)}
        # `special` drives BOTH the encode-side split and decode-side
        # skipping; non-special added tokens (special:false in
        # added_tokens — e.g. domain vocab additions) split on encode
        # like HF does but are KEPT by decode.
        self.special = dict(special_tokens or {})
        self.non_special_added = dict(non_special_added or {})
        self.add_prefix_space = add_prefix_space
        # "always" | "first" | "never" — how ▁ is prepended across
        # special-token-delimited chunks (metaspace scheme only)
        self.prepend_scheme = prepend_scheme
        self.unk_token = unk_token
        self.id_to_token = {i: t for t, i in vocab.items()}
        for tok, i in {**self.special, **self.non_special_added}.items():
            self.id_to_token.setdefault(i, tok)
        self._cache: Dict[str, List[str]] = {}
        self._special_pat = None
        self._added = {**self.non_special_added, **self.special}
        if self._added:
            alts = sorted(self._added, key=len, reverse=True)
            self._special_pat = re.compile(
                "(" + "|".join(re.escape(t) for t in alts) + ")")
        self.bos_token_id = next(
            (i for t, i in self.special.items()
             if t in ("<s>", "<|begin_of_text|>", "<bos>")), None)
        self.eos_token_id = next(
            (i for t, i in self.special.items()
             if t in ("</s>", "<|end_of_text|>", "<eos>",
                      "<|endoftext|>")), None)

    @property
    def vocab_size(self) -> int:
        return max(len(self.vocab),
                   1 + max(self.special.values(), default=0),
                   1 + max(self.non_special_added.values(), default=0))

    # -- loading ---------------------------------------------------------

    @classmethod
    def from_file(cls, path: str) -> "BPETokenizer":
        """Load a HF-format `tokenizer.json` (model.type == "BPE")."""
        with open(path) as f:
            spec = json.load(f)
        model = spec.get("model", {})
        if model.get("type") != "BPE":
            raise ValueError(
                f"unsupported tokenizer model {model.get('type')!r} "
                "(only BPE)")
        vocab = model["vocab"]
        merges: List[Tuple[str, str]] = []
        for m in model.get("merges", []):
            if isinstance(m, str):
                a, _, b = m.partition(" ")
            else:
                a, b = m
            merges.append((a, b))
        special = {t["content"]: t["id"]
                   for t in spec.get("added_tokens", [])
                   if t.get("special", True)}
        non_special = {t["content"]: t["id"]
                       for t in spec.get("added_tokens", [])
                       if not t.get("special", True)}
        scheme, add_prefix, prepend = cls._sniff_pre_tokenizer(spec)
        return cls(vocab, merges, scheme=scheme, special_tokens=special,
                   add_prefix_space=add_prefix,
                   unk_token=model.get("unk_token"),
                   non_special_added=non_special,
                   prepend_scheme=prepend)

    @staticmethod
    def _sniff_pre_tokenizer(spec: Dict[str, Any]) \
            -> Tuple[str, bool, str]:
        """-> (scheme, add_prefix_space, prepend_scheme). Handles the
        three common layouts: ByteLevel pre_tokenizer (GPT-2/Llama-3),
        Metaspace pre_tokenizer (modern SP conversions), and the legacy
        Llama-2 conversion with NO pre_tokenizer — a normalizer
        Sequence of Prepend('▁') + Replace(' '->'▁')."""
        def walk(node) -> Optional[Tuple[str, bool, str]]:
            if not isinstance(node, dict):
                return None
            t = node.get("type")
            if t == "ByteLevel":
                return ("byte_level", bool(node.get("add_prefix_space")),
                        "never")
            if t == "Metaspace":
                scheme = node.get("prepend_scheme", "always")
                return "metaspace", scheme != "never", scheme
            if t == "Prepend" and node.get("prepend") == _METASPACE:
                return "metaspace", True, "first"
            if t == "Replace":
                pat = node.get("pattern")
                if isinstance(pat, dict):
                    pat = pat.get("String") or pat.get("Regex")
                if pat == " " and node.get("content") == _METASPACE:
                    return "metaspace", True, "first"
            if t == "Sequence":
                for sub in (node.get("pretokenizers") or
                            node.get("normalizers") or
                            node.get("decoders") or []):
                    r = walk(sub)
                    if r is not None:
                        return r
            return None
        for key in ("pre_tokenizer", "normalizer", "decoder"):
            r = walk(spec.get(key))
            if r is not None:
                return r
        return "byte_level", False, "never"

    # -- BPE core --------------------------------------------------------

    def _bpe(self, word: str) -> List[str]:
        cached = self._cache.get(word)
        if cached is not None:
            return cached
        symbols = list(word)
        if len(symbols) > 1:
            while True:
                best_rank = None
                best_i = -1
                for i in range(len(symbols) - 1):
                    r = self.ranks.get((symbols[i], symbols[i + 1]))
                    if r is not None and (best_rank is None or
                                          r < best_rank):
                        best_rank, best_i = r, i
                if best_rank is None:
                    break
                merged = symbols[best_i] + symbols[best_i + 1]
                symbols[best_i:best_i + 2] = [merged]
        if len(self._cache) < 65536:
            self._cache[word] = symbols
        return symbols

    def _symbol_ids(self, symbols: List[str], out: List[int]):
        for sym in symbols:
            tid = self.vocab.get(sym)
            if tid is not None:
                out.append(tid)
                continue
            # byte fallback (<0xNN> tokens), then unk, then skip
            emitted = False
            for b in sym.encode("utf-8"):
                btok = self.vocab.get(f"<0x{b:02X}>")
                if btok is not None:
                    out.append(btok)
                    emitted = True
            if not emitted and self.unk_token is not None:
                uid = self.vocab.get(self.unk_token)
                if uid is not None:
                    out.append(uid)

    # -- public surface --------------------------------------------------

    def encode(self, text: str,
               add_special_tokens: bool = False) -> List[int]:
        ids: List[int] = []
        if add_special_tokens and self.bos_token_id is not None:
            ids.append(self.bos_token_id)
        chunks = (self._special_pat.split(text)
                  if self._special_pat else [text])
        first_text_chunk = True
        for chunk in chunks:
            if not chunk:
                continue
            sid = self._added.get(chunk)
            if sid is not None:
                ids.append(sid)
            elif self.scheme == "byte_level":
                self._encode_byte_level(chunk, ids, first_text_chunk)
                first_text_chunk = False
            else:
                # prepend_scheme "first": only the first text chunk of
                # the whole input gets the ▁ prefix; "always": every
                # chunk (post-special-split) does.
                prefix = self.add_prefix_space and (
                    self.prepend_scheme != "first" or first_text_chunk)
                self._encode_metaspace(chunk, ids, prefix)
                first_text_chunk = False
        return ids

    def _encode_byte_level(self, text: str, ids: List[int],
                           first_chunk: bool = True):
        if self.add_prefix_space and first_chunk and text and \
                text[0] != " ":
            # ByteLevel(add_prefix_space=true) checkpoints (RoBERTa/BART
            # conversions) tokenize " hello" for a leading "hello".
            # HF checks for the exact space char — "\thello" still gets
            # the prefix.
            text = " " + text
        for word in _BYTE_LEVEL_PAT.findall(text):
            mapped = "".join(_BYTE_ENC[b] for b in word.encode("utf-8"))
            self._symbol_ids(self._bpe(mapped), ids)

    def _encode_metaspace(self, text: str, ids: List[int],
                          prefix: bool = True):
        if prefix and not text.startswith((" ", _METASPACE)):
            text = " " + text
        text = text.replace(" ", _METASPACE)
        # chunks keep their leading ▁ (pieces like "▁the")
        for word in re.findall(_METASPACE + r"[^" + _METASPACE + r"]*|" +
                               r"[^" + _METASPACE + r"]+", text):
            self._symbol_ids(self._bpe(word), ids)

    def decode(self, tokens: Iterable[int],
               skip_special_tokens: bool = True) -> str:
        # only TRUE specials are skipped; non-special added tokens are
        # model-visible vocabulary and must survive decode
        special_ids = set(self.special.values())
        parts: List[str] = []
        for t in tokens:
            if skip_special_tokens and t in special_ids:
                continue
            tok = self.id_to_token.get(int(t))
            if tok is not None:
                parts.append(tok)
        joined = "".join(parts)
        if self.scheme == "byte_level":
            data = bytes(_BYTE_DEC[c] for c in joined if c in _BYTE_DEC)
            return data.decode("utf-8", "replace")
        # metaspace: expand byte-fallback tokens, then ▁ -> space
        out: List[bytes] = []
        pos = 0
        for m in _BYTE_FALLBACK_PAT.finditer(joined):
            out.append(joined[pos:m.start()].encode("utf-8"))
            out.append(bytes([int(m.group(1), 16)]))
            pos = m.end()
        out.append(joined[pos:].encode("utf-8"))
        text = b"".join(out).decode("utf-8", "replace")
        text = text.replace(_METASPACE, " ")
        return text[1:] if text.startswith(" ") else text


class _HFAdapter:
    """Wrap a `tokenizers.Tokenizer` or `transformers` tokenizer into the
    encode/decode surface the serving layer expects."""

    def __init__(self, tok: Any):
        self._tok = tok

    def encode(self, text: str) -> List[int]:
        enc = self._tok.encode(text)
        ids = getattr(enc, "ids", enc)  # Encoding vs plain list
        return list(ids)

    def decode(self, tokens: Iterable[int]) -> str:
        return self._tok.decode(list(tokens))


def get_tokenizer(spec: Any = None) -> Any:
    """Resolve a tokenizer: None → ByteTokenizer; a path → native BPE
    from `tokenizer.json` (or a checkpoint dir containing one), falling
    back to `transformers.AutoTokenizer` (local only); an object with
    encode/decode → wrapped/as-is."""
    if spec is None:
        return ByteTokenizer()
    if isinstance(spec, str):
        import os
        path = spec
        if os.path.isdir(path):
            candidate = os.path.join(path, "tokenizer.json")
            if os.path.exists(candidate):
                return BPETokenizer.from_file(candidate)
            try:
                from transformers import AutoTokenizer
                return _HFAdapter(AutoTokenizer.from_pretrained(
                    path, local_files_only=True))
            except Exception as e:
                raise ValueError(
                    f"no tokenizer.json under {path} and transformers "
                    f"could not load it: {e}") from e
        return BPETokenizer.from_file(path)
    if hasattr(spec, "encode") and hasattr(spec, "decode"):
        probe = spec.encode("x")
        if hasattr(probe, "ids"):
            return _HFAdapter(spec)
        return spec
    raise TypeError(f"cannot build a tokenizer from {type(spec)}")
