from .llama import (LlamaConfig, LlamaModel, cross_entropy_loss,
                    init_kv_caches)

__all__ = ["LlamaConfig", "LlamaModel", "cross_entropy_loss",
           "init_kv_caches"]
