from .llama import (LlamaConfig, LlamaModel, cross_entropy_loss,
                    init_kv_caches)
from .lora import (lora_optimizer, merge_lora, num_lora_params,
                   split_lora)

__all__ = ["LlamaConfig", "LlamaModel", "cross_entropy_loss",
           "init_kv_caches", "lora_optimizer", "merge_lora",
           "split_lora", "num_lora_params"]
