"""Llama-family causal LM, TPU-first.

The flagship model of the framework (the reference delegates modeling to
torch/vLLM; here it is native): flax.linen with logical-axis partitioning on
every parameter and activation, so one definition serves every parallelism
mix — DP/FSDP/TP/SP via `ray_tpu.parallel.MeshConfig`, and the mesh decides
the collectives.

Design notes for the MXU/HBM:
- all matmuls in bf16 with fp32 accumulation (`preferred_element_type`)
- attention via ops.attention.flash_attention (Pallas on TPU)
- per-block jax.checkpoint with dots-saveable policy for rematerialization
- RoPE applied in fp32; RMSNorm in fp32 then cast back
- decode path keeps a KV cache laid out [batch, kv_heads, max_seq, head_dim]

Parity map (reference models live outside Ray; shapes follow the public
Llama-2/3 configs).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.attention import flash_attention

Dtype = Any


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 32
    head_dim: Optional[int] = None
    max_seq_len: int = 4096
    rope_theta: float = 10000.0
    rms_norm_eps: float = 1e-5
    tie_embeddings: bool = False
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    remat: bool = True
    use_flash: bool = True
    # "flash" (pallas fwd + chunked bwd), "chunked", or "reference"
    # (full-logits, XLA-fused — fastest backward at moderate seq lengths).
    attention_impl: str = "flash"
    # LoRA (Hu et al. 2021; reference workload: BASELINE config_3's
    # Llama-2-7B LoRA fine-tune). rank 0 = disabled. Each target
    # projection W gains (alpha/rank) * A @ B with B zero-initialized,
    # so enabling LoRA never changes the initial forward. Train only
    # the adapters with models.lora.lora_optimizer; fold them for
    # serving with models.lora.merge_lora.
    lora_rank: int = 0
    lora_alpha: float = 16.0
    lora_targets: Tuple[str, ...] = ("q_proj", "k_proj", "v_proj",
                                     "o_proj")

    @property
    def head_dim_(self) -> int:
        return self.head_dim or self.hidden_size // self.num_heads

    # ---- presets ----
    @staticmethod
    def tiny_test():
        """4-layer toy for tests / graft entry compile checks."""
        return LlamaConfig(vocab_size=256, hidden_size=128,
                           intermediate_size=352, num_layers=4, num_heads=4,
                           num_kv_heads=2, max_seq_len=256, remat=False)

    @staticmethod
    def llama2_7b():
        return LlamaConfig()  # the defaults above are llama-2-7b

    @staticmethod
    def llama3_8b():
        return LlamaConfig(vocab_size=128256, hidden_size=4096,
                           intermediate_size=14336, num_layers=32,
                           num_heads=32, num_kv_heads=8, max_seq_len=8192,
                           rope_theta=500000.0)

    @staticmethod
    def bench_350m():
        """~350M-param config sized for a single v5e chip benchmark.

        8 heads of head_dim=128 (not 16x64): the MXU is a 128x128 systolic
        array, so a 128-deep attention contraction keeps it full — measured
        57.9% vs 38.0% MFU on v5e for the same parameter count.
        """
        return LlamaConfig(vocab_size=32000, hidden_size=1024,
                           intermediate_size=2816, num_layers=24,
                           num_heads=8, num_kv_heads=8, max_seq_len=2048)

    def num_params(self) -> int:
        d, v = self.hidden_size, self.vocab_size
        hd = self.head_dim_
        attn = d * (self.num_heads * hd) + 2 * d * (self.num_kv_heads * hd) \
            + (self.num_heads * hd) * d
        mlp = 3 * d * self.intermediate_size
        per_layer = attn + mlp + 2 * d
        embed = v * d * (1 if self.tie_embeddings else 2)
        return self.num_layers * per_layer + embed + d


def _partitioned(init, names):
    return nn.with_logical_partitioning(init, names)


def _lora_delta(x, feats, in_names, out_names, name, cfg,
                axis=-1):
    """(x @ A) @ B * (alpha/rank): the LoRA low-rank path, computed
    WITHOUT materializing the dense delta (the x@A bottleneck is [.., r]
    — at rank 8-64 this is bandwidth-free next to the base matmul).
    B is zero-init, so the adapted model starts exactly at the base
    model. The 'lora' logical axis has no mesh rule -> adapters
    replicate (they are KBs; the base weights stay sharded)."""
    r = cfg.lora_rank
    a = nn.DenseGeneral(
        r, axis=axis, use_bias=False, dtype=cfg.dtype,
        param_dtype=cfg.param_dtype, name=f"{name}_lora_a",
        kernel_init=_partitioned(nn.initializers.lecun_normal(),
                                 in_names + ("lora",)))(x)
    b = nn.DenseGeneral(
        feats, axis=-1, use_bias=False, dtype=cfg.dtype,
        param_dtype=cfg.param_dtype, name=f"{name}_lora_b",
        kernel_init=_partitioned(nn.initializers.zeros_init(),
                                 ("lora",) + out_names))(a)
    return b * (cfg.lora_alpha / r)


def _maybe_lora(x, y, feats, in_names, out_names, name, cfg, axis=-1):
    """y = base_projection(x); adds the LoRA path when enabled."""
    if cfg.lora_rank and name in cfg.lora_targets:
        return y + _lora_delta(x, feats, in_names, out_names, name, cfg,
                               axis=axis)
    return y


class RMSNorm(nn.Module):
    eps: float = 1e-5
    dtype: Dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        scale = self.param("scale", _partitioned(nn.initializers.ones,
                                                 ("embed",)), (x.shape[-1],),
                           jnp.float32)
        x32 = x.astype(jnp.float32)
        normed = x32 * jax.lax.rsqrt(
            jnp.mean(x32 * x32, axis=-1, keepdims=True) + self.eps)
        return (normed * scale).astype(self.dtype)


def rope_frequencies(head_dim: int, max_seq: int, theta: float):
    exponents = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta ** exponents)
    positions = jnp.arange(max_seq, dtype=jnp.float32)
    angles = jnp.outer(positions, inv_freq)  # [seq, head_dim/2]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x, cos, sin, positions):
    """x: [b, heads, seq, head_dim]; positions: [b, seq]"""
    cos_p = cos[positions][:, None, :, :]      # [b, 1, seq, hd/2]
    sin_p = sin[positions][:, None, :, :]
    x32 = x.astype(jnp.float32)
    x1, x2 = jnp.split(x32, 2, axis=-1)
    rotated = jnp.concatenate(
        [x1 * cos_p - x2 * sin_p, x2 * cos_p + x1 * sin_p], axis=-1)
    return rotated.astype(x.dtype)


class Attention(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, kv_cache=None, cache_index=None):
        cfg = self.config
        hd = cfg.head_dim_
        dense = lambda feats, names, name: nn.DenseGeneral(  # noqa: E731
            feats, axis=-1, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name=name,
            kernel_init=_partitioned(
                nn.initializers.lecun_normal(), names))
        q = dense((cfg.num_heads, hd), ("embed", "heads", "head_dim"),
                  "q_proj")(x)
        q = _maybe_lora(x, q, (cfg.num_heads, hd), ("embed",),
                        ("heads", "head_dim"), "q_proj", cfg)
        k = dense((cfg.num_kv_heads, hd), ("embed", "kv_heads", "head_dim"),
                  "k_proj")(x)
        k = _maybe_lora(x, k, (cfg.num_kv_heads, hd), ("embed",),
                        ("kv_heads", "head_dim"), "k_proj", cfg)
        v = dense((cfg.num_kv_heads, hd), ("embed", "kv_heads", "head_dim"),
                  "v_proj")(x)
        v = _maybe_lora(x, v, (cfg.num_kv_heads, hd), ("embed",),
                        ("kv_heads", "head_dim"), "v_proj", cfg)
        # [b, s, h, d] -> [b, h, s, d]
        q = jnp.transpose(q, (0, 2, 1, 3))
        k = jnp.transpose(k, (0, 2, 1, 3))
        v = jnp.transpose(v, (0, 2, 1, 3))
        cos, sin = rope_frequencies(hd, cfg.max_seq_len, cfg.rope_theta)
        q = apply_rope(q, cos, sin, positions)
        k = apply_rope(k, cos, sin, positions)

        new_cache = None
        if isinstance(kv_cache, dict):
            # Paged decode (q_len == 1): the cache is a page pool
            #   k/v: [kv_heads, num_pages, page_size, head_dim]
            #   block_tables: [B, pages_per_seq] physical page ids
            #   lengths: [B] tokens already cached (this token's position)
            # Write lands at (table[len//ps], len%ps); attention runs the
            # Pallas paged kernel on TPU (jax.experimental.pallas.ops.tpu.
            # paged_attention) or a gather fallback elsewhere.
            kp, vp = kv_cache["k"], kv_cache["v"]
            block_tables = kv_cache["block_tables"]
            lengths = kv_cache["lengths"]
            page_size = kp.shape[2]
            B = q.shape[0]
            rows = jnp.arange(B)
            page_of = block_tables[rows, lengths // page_size]
            offset = lengths % page_size
            # k,v are [B, kvh, 1, hd] -> write [kvh, B, hd] rows
            k_rows = jnp.transpose(k[:, :, 0, :], (1, 0, 2)).astype(kp.dtype)
            v_rows = jnp.transpose(v[:, :, 0, :], (1, 0, 2)).astype(vp.dtype)
            kp = kp.at[:, page_of, offset, :].set(k_rows)
            vp = vp.at[:, page_of, offset, :].set(v_rows)
            new_cache = dict(kv_cache, k=kp, v=vp)
            q1 = q[:, :, 0, :]  # [B, heads, hd]

            def paged_kernel(q_, kp_, vp_, lengths_, tables_):
                """Per-shard paged attention: q_ holds LOCAL heads,
                kp_/vp_ LOCAL kv heads (head-parallel — no collectives
                needed). Runs unsharded when there is no tensor axis."""
                # Pallas kernel only when asked for (attention_impl)
                # AND the shapes meet its tiling floor — tiny test/CI
                # configs (head_dim < 128) must take the gather path
                # even on real TPU hardware.
                if (jax.default_backend() == "tpu"
                        and cfg.attention_impl != "reference"
                        and hd % 128 == 0):
                    from jax.experimental.pallas.ops.tpu.paged_attention \
                        .paged_attention_kernel import paged_attention
                    n_pages = tables_.shape[1]
                    # kernel requires pages_per_sequence % block == 0
                    ppcb = next(d for d in range(min(8, n_pages), 0, -1)
                                if n_pages % d == 0)
                    return paged_attention(
                        (q_ * hd ** -0.5).astype(kp_.dtype), kp_, vp_,
                        lengths_ + 1, tables_,
                        pages_per_compute_block=ppcb)
                # Gather fallback: materialize each row's pages densely.
                # [B, pages_per_seq, kvh, ps, hd] -> [B, kvh, L, hd]
                B_ = q_.shape[0]
                gk = jnp.transpose(kp_, (1, 0, 2, 3))[tables_]
                gv = jnp.transpose(vp_, (1, 0, 2, 3))[tables_]
                L = tables_.shape[1] * page_size
                gk = jnp.transpose(gk, (0, 2, 1, 3, 4)).reshape(
                    B_, kp_.shape[0], L, hd)
                gv = jnp.transpose(gv, (0, 2, 1, 3, 4)).reshape(
                    B_, vp_.shape[0], L, hd)
                groups_ = q_.shape[1] // kp_.shape[0]
                gk = jnp.repeat(gk, groups_, axis=1)
                gv = jnp.repeat(gv, groups_, axis=1)
                logits = jnp.einsum(
                    "bhd,bhkd->bhk", q_.astype(jnp.float32),
                    gk.astype(jnp.float32)) * (hd ** -0.5)
                kv_pos = jnp.arange(L)[None, :]
                mask = kv_pos <= lengths_[:, None]
                logits = jnp.where(mask[:, None, :], logits, -1e30)
                probs = jax.nn.softmax(logits, axis=-1)
                return jnp.einsum("bhk,bhkd->bhd", probs,
                                  gv.astype(jnp.float32))

            # Tensor-parallel serving: when tracing under a serving mesh
            # whose `tensor` axis is >1, run the kernel per-shard via
            # shard_map (heads/kv_heads sharded, attention is
            # head-parallel so no collectives). GSPMD cannot partition
            # the Pallas custom call itself, hence the explicit map
            # (reference places TP engine workers via
            # vllm_models.py:169-178; here TP is a mesh axis).
            from ..parallel.mesh import current_serving_mesh
            pm = current_serving_mesh()
            tp = int(pm.shape.get("tensor", 1)) if pm is not None else 1
            if tp > 1:
                from jax.sharding import PartitionSpec as _P
                from ..parallel._compat import shard_map as _shard_map
                out1 = _shard_map(
                    paged_kernel, mesh=pm,
                    in_specs=(_P(None, "tensor", None), _P("tensor"),
                              _P("tensor"), _P(None), _P(None, None)),
                    out_specs=_P(None, "tensor", None))(
                        q1, kp, vp, lengths, block_tables)
            else:
                out1 = paged_kernel(q1, kp, vp, lengths, block_tables)
            out = out1[:, :, None, :].astype(cfg.dtype)
        elif kv_cache is not None:
            # Decode: write new K/V at cache_index, attend over the cache.
            # cache_index may be a scalar (whole batch at one position —
            # single-sequence decode / prefill) or a [batch] vector (each
            # slot at its own position — continuous batching, where the
            # write is a per-row one-hot blend; q_len is 1 there).
            ck, cv = kv_cache
            idx = jnp.asarray(cache_index)
            if idx.ndim == 0:
                ck = jax.lax.dynamic_update_slice_in_dim(
                    ck, k.astype(ck.dtype), cache_index, axis=2)
                cv = jax.lax.dynamic_update_slice_in_dim(
                    cv, v.astype(cv.dtype), cache_index, axis=2)
            else:
                onehot = jax.nn.one_hot(idx, ck.shape[2],
                                        dtype=ck.dtype)[:, None, :, None]
                ck = ck * (1 - onehot) + k.astype(ck.dtype) * onehot
                cv = cv * (1 - onehot) + v.astype(cv.dtype) * onehot
            new_cache = (ck, cv)
            groups = cfg.num_heads // cfg.num_kv_heads
            kk = jnp.repeat(ck, groups, axis=1)
            vv = jnp.repeat(cv, groups, axis=1)
            scale = hd ** -0.5
            logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                                kk.astype(jnp.float32)) * scale
            kv_pos = jnp.arange(kk.shape[2])[None, :]
            q_pos = positions[:, :, None] if positions.ndim == 2 \
                else positions[None, :, None]
            mask = kv_pos[:, None, :] <= q_pos  # [b, q, k]
            logits = jnp.where(mask[:, None, :, :], logits, -1e30)
            probs = jax.nn.softmax(logits, axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", probs,
                             vv.astype(jnp.float32)).astype(cfg.dtype)
        else:
            impl = cfg.attention_impl if cfg.use_flash else "chunked"
            if impl == "reference":
                from ..ops.attention import attention_reference
                out = attention_reference(q, k, v, True)
            elif impl == "chunked":
                from ..ops.attention import attention_chunked
                out = attention_chunked(q, k, v, True)
            else:
                out = flash_attention(q, k, v, True, None)
        out = jnp.transpose(out, (0, 2, 1, 3))  # [b, s, h, d]
        proj = nn.DenseGeneral(
            cfg.hidden_size, axis=(-2, -1), use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="o_proj",
            kernel_init=_partitioned(nn.initializers.lecun_normal(),
                                     ("heads", "head_dim", "embed")))(out)
        proj = _maybe_lora(out, proj, cfg.hidden_size,
                           ("heads", "head_dim"), ("embed",), "o_proj",
                           cfg, axis=(-2, -1))
        return proj, new_cache


class MLP(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.config
        gate = nn.DenseGeneral(
            cfg.intermediate_size, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="gate_proj",
            kernel_init=_partitioned(nn.initializers.lecun_normal(),
                                     ("embed", "mlp")))(x)
        gate = _maybe_lora(x, gate, cfg.intermediate_size, ("embed",),
                           ("mlp",), "gate_proj", cfg)
        up = nn.DenseGeneral(
            cfg.intermediate_size, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="up_proj",
            kernel_init=_partitioned(nn.initializers.lecun_normal(),
                                     ("embed", "mlp")))(x)
        up = _maybe_lora(x, up, cfg.intermediate_size, ("embed",),
                         ("mlp",), "up_proj", cfg)
        hidden = nn.silu(gate) * up
        down = nn.DenseGeneral(
            cfg.hidden_size, use_bias=False, dtype=cfg.dtype,
            param_dtype=cfg.param_dtype, name="down_proj",
            kernel_init=_partitioned(nn.initializers.lecun_normal(),
                                     ("mlp", "embed")))(hidden)
        return _maybe_lora(hidden, down, cfg.hidden_size, ("mlp",),
                           ("embed",), "down_proj", cfg)


class DecoderBlock(nn.Module):
    config: LlamaConfig

    @nn.compact
    def __call__(self, x, positions, kv_cache=None, cache_index=None):
        cfg = self.config
        attn_out, new_cache = Attention(cfg, name="attn")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="attn_norm")(x),
            positions, kv_cache, cache_index)
        x = x + attn_out
        x = x + MLP(cfg, name="mlp")(
            RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="mlp_norm")(x))
        return x, new_cache


class LlamaModel(nn.Module):
    """Causal LM: tokens -> logits. `kv_caches` enables decode mode."""
    config: LlamaConfig

    @nn.compact
    def __call__(self, tokens, positions=None, kv_caches=None,
                 cache_index=None):
        cfg = self.config
        if positions is None:
            positions = jnp.broadcast_to(
                jnp.arange(tokens.shape[1])[None, :], tokens.shape)
        embed = self.param(
            "embed", _partitioned(nn.initializers.normal(0.02),
                                  ("vocab", "embed")),
            (cfg.vocab_size, cfg.hidden_size), cfg.param_dtype)
        x = jnp.take(embed, tokens, axis=0).astype(cfg.dtype)
        x = nn.with_logical_constraint(
            x, ("activation_batch", "activation_seq", "activation_embed"))

        block = DecoderBlock
        if cfg.remat and kv_caches is None:
            block = nn.remat(
                DecoderBlock, policy=jax.checkpoint_policies.
                checkpoint_dots_with_no_batch_dims, static_argnums=(3,))
        new_caches = []
        for layer in range(cfg.num_layers):
            cache = kv_caches[layer] if kv_caches is not None else None
            x, new_cache = block(cfg, name=f"layer_{layer}")(
                x, positions, cache, cache_index)
            new_caches.append(new_cache)
            x = nn.with_logical_constraint(
                x, ("activation_batch", "activation_seq",
                    "activation_embed"))
        x = RMSNorm(cfg.rms_norm_eps, cfg.dtype, name="final_norm")(x)
        if cfg.tie_embeddings:
            logits = jnp.einsum("bsd,vd->bsv", x,
                                embed.astype(cfg.dtype))
        else:
            logits = nn.DenseGeneral(
                cfg.vocab_size, use_bias=False, dtype=cfg.dtype,
                param_dtype=cfg.param_dtype, name="lm_head",
                kernel_init=_partitioned(nn.initializers.lecun_normal(),
                                         ("embed", "vocab")))(x)
        logits = nn.with_logical_constraint(
            logits, ("activation_batch", "activation_seq", None))
        if kv_caches is not None:
            return logits, new_caches
        return logits


def init_kv_caches(config: LlamaConfig, batch: int, max_len: int,
                   dtype=None):
    dtype = dtype or config.dtype
    shape = (batch, config.num_kv_heads, max_len, config.head_dim_)
    return [(jnp.zeros(shape, dtype), jnp.zeros(shape, dtype))
            for _ in range(config.num_layers)]


def cross_entropy_loss(logits, targets, mask=None, z_loss: float = 0.0):
    """Causal LM loss with optional z-loss regularizer."""
    logits = logits.astype(jnp.float32)
    log_z = jax.scipy.special.logsumexp(logits, axis=-1)
    target_logits = jnp.take_along_axis(
        logits, targets[..., None], axis=-1)[..., 0]
    losses = log_z - target_logits
    if z_loss:
        losses = losses + z_loss * log_z ** 2
    if mask is not None:
        losses = losses * mask
        return losses.sum() / jnp.maximum(mask.sum(), 1)
    return losses.mean()
