"""LoRA utilities: adapter-only optimization + merge-for-serving
(Hu et al. 2021; reference workload: BASELINE config_3 "Llama-2-7B LoRA
fine-tune" — the reference delegates the technique to HF peft inside
its TorchTrainer example; here it is first-class in the model:
LlamaConfig(lora_rank=...) adds zero-initialized (alpha/r)·A@B paths to
the target projections, llama.py _lora_delta).

TPU notes: adapters carry no mesh rule ('lora' axis) so they replicate
— KBs per layer — while base weights keep their fsdp/tensor sharding;
the frozen base gets optax.set_to_zero() updates, so Adam never
allocates first/second-moment buffers' worth of useful state for the
7B base tree (multi_transform initializes per-partition state)."""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
import optax


def is_lora_path(path) -> bool:
    """True for leaves under a *_lora_a / *_lora_b module."""
    return any(getattr(k, "key", str(k)).endswith(("_lora_a", "_lora_b"))
               for k in path)


def lora_labels(params) -> Any:
    """'lora' / 'frozen' label tree for optax.multi_transform."""
    return jax.tree_util.tree_map_with_path(
        lambda path, _leaf: "lora" if is_lora_path(path) else "frozen",
        params)


def lora_optimizer(inner_tx: optax.GradientTransformation
                   ) -> optax.GradientTransformation:
    """Train ONLY the adapters: `inner_tx` on lora leaves, set_to_zero
    on the frozen base (reference analog: peft marks base params
    requires_grad=False)."""
    def label_fn(params):
        return lora_labels(params)
    return optax.multi_transform(
        {"lora": inner_tx, "frozen": optax.set_to_zero()}, label_fn)


def split_lora(params):
    """(base_tree, lora_tree) — lora_tree keeps only adapter leaves
    (checkpoint just this; it is the whole fine-tune)."""
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    base, lora = {}, {}
    for path, leaf in flat:
        keys = [getattr(k, "key", str(k)) for k in path]
        target = lora if is_lora_path(path) else base
        node = target
        for k in keys[:-1]:
            node = node.setdefault(k, {})
        node[keys[-1]] = leaf
    return base, lora


def merge_lora(params, config):
    """Fold every adapter into its base kernel and DROP the adapter
    leaves: W' = W + (alpha/r) * A @ B (tensordot over the rank axis
    generalizes to the (heads, head_dim) in-axes of o_proj). The merged
    tree is a plain base-model tree — serve it with lora_rank=0.

    Precision note: the fold is exact in the weights, but on TPU the
    MXU's default bf16 multiply passes make x@(W + sAB) differ from
    x@W + s(x@A)@B by O(1e-2) absolute in fp32 activations — that is
    matmul rounding between two equivalent contraction orders, not a
    merge error (on the CPU backend the two paths agree to ~1e-6).
    Compare merged-vs-adapted outputs with TPU-sized tolerances or
    jax.default_matmul_precision('float32')."""
    scale = config.lora_alpha / config.lora_rank

    def walk(node):
        if not isinstance(node, dict):
            return node
        out = {}
        lora_mods = {k[:-len("_lora_a")] for k in node
                     if k.endswith("_lora_a")}
        for key, child in node.items():
            if key.endswith(("_lora_a", "_lora_b")):
                continue
            if key in lora_mods:
                a = node[f"{key}_lora_a"]["kernel"]
                b = node[f"{key}_lora_b"]["kernel"]
                kernel = child["kernel"]
                delta = jnp.tensordot(a, b, axes=[[-1], [0]])
                out[key] = dict(child)
                out[key]["kernel"] = (
                    kernel + scale * delta.astype(kernel.dtype))
            else:
                out[key] = walk(child)
        return out

    return walk(params)


def num_lora_params(params) -> int:
    _, lora = split_lora(params)
    return sum(int(x.size) for x in jax.tree_util.tree_leaves(lora))
