"""Mixture-of-Experts layer with expert parallelism.

TPU-native design (SURVEY §2d requires EP first-class; the reference
delegates it to vLLM engine kwargs — vllm_models.py:234): GShard/Switch
dense dispatch. Routing produces a dispatch mask [tokens, E, capacity] and
combine weights; einsums move tokens to per-expert buffers laid out on the
`expert` mesh axis (GSPMD lowers the dispatch/combine einsums to
all-to-alls over ICI), experts run batched on the MXU, outputs combine
back. Top-k routing with capacity dropping + load-balance aux loss
(Switch Transformer §2.2)."""

from __future__ import annotations

from typing import Any, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from .llama import _partitioned


def _top_k_routing(logits, k: int):
    """Per-token top-k expert choice with renormalized weights."""
    weights = jax.nn.softmax(logits, axis=-1)  # [T, E]
    top_w, top_idx = jax.lax.top_k(weights, k)  # [T, k]
    top_w = top_w / jnp.clip(top_w.sum(-1, keepdims=True), 1e-9)
    return weights, top_w, top_idx


class MoELayer(nn.Module):
    """Drop-in FFN replacement: route tokens to num_experts expert MLPs.

    capacity = capacity_factor * tokens * k / num_experts per expert;
    overflow tokens are dropped (their combine weight is zero and the
    residual path carries them — standard Switch behavior)."""
    num_experts: int
    embed_dim: int
    mlp_dim: int
    num_experts_per_token: int = 2
    capacity_factor: float = 1.25
    dtype: Any = jnp.float32
    router_aux_weight: float = 0.01

    @nn.compact
    def __call__(self, x) -> Tuple[jax.Array, jax.Array]:
        # x: [batch, seq, embed] -> flatten tokens
        B, S, D = x.shape
        E, K = self.num_experts, self.num_experts_per_token
        T = B * S
        tokens = x.reshape(T, D)

        router_kernel = self.param(
            "router", _partitioned(nn.initializers.normal(0.02),
                                   ("embed", "expert")),
            (D, E), jnp.float32)
        logits = tokens.astype(jnp.float32) @ router_kernel  # [T, E]
        weights, top_w, top_idx = _top_k_routing(logits, K)

        capacity = max(1, int(self.capacity_factor * T * K / E))

        # Position of each (token, choice) in its expert's buffer: the
        # cumulative count of earlier assignments to the same expert.
        # one-hot: [T, K, E]
        assign = jax.nn.one_hot(top_idx, E, dtype=jnp.int32)
        flat_assign = assign.reshape(T * K, E)
        positions = (jnp.cumsum(flat_assign, axis=0) - 1).reshape(T, K, E)
        position_in_expert = (positions * assign).sum(-1)  # [T, K]
        kept = ((position_in_expert < capacity) &
                (assign.sum(-1) > 0)).astype(x.dtype)  # [T, K]

        # dispatch[t, e, c] = 1 where token t sits in slot c of expert e
        slot_onehot = jax.nn.one_hot(position_in_expert, capacity,
                                     dtype=x.dtype)  # [T, K, C]
        dispatch = jnp.einsum("tke,tkc->tec",
                              assign.astype(x.dtype) *
                              kept[..., None], slot_onehot)
        combine = jnp.einsum("tke,tkc->tec",
                             (assign.astype(x.dtype) *
                              (top_w * kept)[..., None]), slot_onehot)

        # To expert buffers: [E, C, D] (sharded on the expert mesh axis —
        # GSPMD turns this einsum into the all-to-all dispatch).
        expert_in = jnp.einsum("tec,td->ecd", dispatch, tokens)
        expert_in = nn.with_logical_constraint(
            expert_in, ("expert", None, "embed"))

        init = nn.initializers.normal(0.02)
        wi_gate = self.param("wi_gate",
                             _partitioned(init, ("expert", "embed", "mlp")),
                             (E, D, self.mlp_dim), self.dtype)
        wi_up = self.param("wi_up",
                           _partitioned(init, ("expert", "embed", "mlp")),
                           (E, D, self.mlp_dim), self.dtype)
        wo = self.param("wo",
                        _partitioned(init, ("expert", "mlp", "embed")),
                        (E, self.mlp_dim, D), self.dtype)
        h = jax.nn.silu(jnp.einsum("ecd,edm->ecm", expert_in, wi_gate)) * \
            jnp.einsum("ecd,edm->ecm", expert_in, wi_up)
        expert_out = jnp.einsum("ecm,emd->ecd", h, wo)
        expert_out = nn.with_logical_constraint(
            expert_out, ("expert", None, "embed"))

        out = jnp.einsum("tec,ecd->td", combine, expert_out)

        # Load-balance aux loss (Switch §2.2): E * sum_e f_e * P_e where
        # f_e = fraction of tokens routed (top-1) to e, P_e = mean router
        # probability for e.
        f = jnp.mean(jax.nn.one_hot(top_idx[:, 0], E, dtype=jnp.float32),
                     axis=0)
        p = jnp.mean(weights, axis=0)
        aux_loss = self.router_aux_weight * E * jnp.sum(f * p)

        return out.reshape(B, S, D), aux_loss
