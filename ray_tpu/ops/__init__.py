from .attention import (attention_chunked, attention_reference,
                        flash_attention)

__all__ = ["flash_attention", "attention_chunked", "attention_reference"]
