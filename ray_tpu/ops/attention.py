"""Attention kernels.

The hot op of every transformer in the framework. Three tiers:

1. `attention_reference` — naive O(S^2)-memory jnp implementation; the
   numerical ground truth for tests.
2. `attention_chunked` — blockwise online-softmax attention via lax.scan
   (memory-efficient attention): O(S * chunk) memory, fully differentiable,
   runs on any backend. Used as the backward pass everywhere and as the
   forward on non-TPU backends.
3. `_flash_fwd_tpu` — Pallas TPU kernel: tiled online softmax, fp32
   accumulators in VMEM scratch, causal block skipping, GQA via kv-head
   index mapping. Forward-only; `flash_attention` wires it into a
   custom_vjp whose backward recomputes through (2) (flash-style
   recompute — no S^2 residuals are ever materialized).

All functions take q/k/v as [batch, heads, seq, head_dim] (BHSD) in bf16 or
f32, with GQA expressed as k/v having fewer heads (num_q_heads must be a
multiple of num_kv_heads). `q_offset`/`kv_offset` shift the causal mask for
sequence-parallel callers (ring attention passes the rotating chunk offset).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

NEG_INF = -1e30


def _validate(q, k, v):
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("q/k/v must be [batch, heads, seq, head_dim]")
    if q.shape[1] % k.shape[1] != 0:
        raise ValueError(
            f"q heads {q.shape[1]} not a multiple of kv heads {k.shape[1]}")


def _expand_kv(q, k, v):
    """Repeat kv heads up to q heads for the non-kernel paths."""
    groups = q.shape[1] // k.shape[1]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    return k, v


def attention_reference(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None,
                        q_offset: int = 0, kv_offset: int = 0):
    _validate(q, k, v)
    k, v = _expand_kv(q, k, v)
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[2])[:, None]
        k_pos = kv_offset + jnp.arange(k.shape[2])[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights,
                      v.astype(jnp.float32)).astype(q.dtype)


def attention_chunked(q, k, v, causal: bool = True,
                      sm_scale: Optional[float] = None,
                      q_offset: int = 0, kv_offset: int = 0,
                      chunk_size: int = 512):
    """Blockwise attention: scan over KV chunks with running (m, l, acc)."""
    _validate(q, k, v)
    k, v = _expand_kv(q, k, v)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    chunk = min(chunk_size, sk)
    if sk % chunk != 0:
        # Fall back: odd kv lengths take the reference path.
        return attention_reference(q, k, v, causal, sm_scale, q_offset,
                                   kv_offset)
    n_chunks = sk // chunk
    kc = k.reshape(b, h, n_chunks, chunk, d)
    vc = v.reshape(b, h, n_chunks, chunk, d)
    qf = q.astype(jnp.float32)
    q_pos = q_offset + jnp.arange(sq)

    def step(carry, inputs):
        m, l, acc = carry
        idx, k_blk, v_blk = inputs
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf,
                            k_blk.astype(jnp.float32)) * scale
        if causal:
            k_pos = kv_offset + idx * chunk + jnp.arange(chunk)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(logits - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    init = (jnp.full((b, h, sq), NEG_INF, jnp.float32),
            jnp.zeros((b, h, sq), jnp.float32),
            jnp.zeros((b, h, sq, d), jnp.float32))
    kc_t = jnp.moveaxis(kc, 2, 0)
    vc_t = jnp.moveaxis(vc, 2, 0)
    (m, l, acc), _ = jax.lax.scan(
        step, init, (jnp.arange(n_chunks), kc_t, vc_t))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU forward kernel
# ---------------------------------------------------------------------------

def _flash_kernel(q_ref, k_ref, v_ref, o_ref, m_scratch, l_scratch,
                  acc_scratch, *, sm_scale: float, causal: bool,
                  block_q: int, block_k: int, kv_len: int):
    """Grid: (batch*q_heads, num_q_blocks, num_k_blocks); the k dimension is
    the innermost 'arbitrary' axis we accumulate over."""
    kb = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kb == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [block_q, d]
        k = k_ref[0].astype(jnp.float32)          # [block_k, d]
        v = v_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            qb = pl.program_id(1)
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        m_prev = m_scratch[:]                      # [block_q, 1]
        m_blk = jnp.max(logits, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(logits - m_new)
        correction = jnp.exp(m_prev - m_new)
        m_scratch[:] = m_new
        l_scratch[:] = l_scratch[:] * correction + jnp.sum(
            p, axis=-1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * correction + jax.lax.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32)

    if causal:
        # Skip fully-masked kv blocks (k start beyond q end).
        qb = pl.program_id(1)

        @pl.when(kb * block_k <= qb * block_q + block_q - 1)
        def _go():
            _compute()
    else:
        _compute()

    @pl.when(kb == nk - 1)
    def _finalize():
        o_ref[0] = (acc_scratch[:] /
                    jnp.maximum(l_scratch[:], 1e-30)).astype(o_ref.dtype)


def _flash_fwd_tpu(q, k, v, causal: bool, sm_scale: float,
                   block_q: int = 256, block_k: int = 512):
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    groups = h // hk
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError("seq lengths must divide the block sizes")
    grid = (b * h, sq // block_q, sk // block_k)

    def q_index(bh, qb, kb):
        return (bh, qb, 0)

    def kv_index(bh, qb, kb):
        # GQA: query head bh%h maps to kv head (bh%h)//groups.
        batch = bh // h
        kv_head = (bh % h) // groups
        return (batch * hk + kv_head, kb, 0)

    out = pl.pallas_call(
        functools.partial(_flash_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k, kv_len=sk),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), q_index),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
    )(q.reshape(b * h, sq, d), k.reshape(b * hk, sk, d),
      v.reshape(b * hk, sk, d))
    return out.reshape(b, h, sq, d)


def _on_tpu() -> bool:
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    q_offset: int = 0, kv_offset: int = 0):
    """Dispatching flash attention; differentiable everywhere (backward
    recomputes through the chunked path — no S^2 residuals)."""
    return _flash_forward(q, k, v, causal, sm_scale, q_offset, kv_offset)


def _flash_forward(q, k, v, causal, sm_scale, q_offset, kv_offset):
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    if (_on_tpu() and q_offset == 0 and kv_offset == 0
            and q.shape[2] >= 128 and q.shape[2] % 128 == 0
            and k.shape[2] % 128 == 0 and q.shape[3] in (64, 128, 256)):
        try:
            return _flash_fwd_tpu(q, k, v, causal, scale)
        except Exception:
            pass
    return attention_chunked(q, k, v, causal, scale, q_offset, kv_offset)


def _flash_fwd_rule(q, k, v, causal, sm_scale, q_offset, kv_offset):
    out = _flash_forward(q, k, v, causal, sm_scale, q_offset, kv_offset)
    return out, (q, k, v)


def _flash_bwd_rule(causal, sm_scale, q_offset, kv_offset, residuals, g):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_chunked(
            q_, k_, v_, causal, sm_scale, q_offset, kv_offset), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
