"""Attention kernels.

The hot op of every transformer in the framework. Tiers:

1. `attention_reference` — naive O(S^2)-memory jnp implementation; the
   numerical ground truth for tests.
2. `attention_chunked` — blockwise online-softmax attention via lax.scan
   (memory-efficient attention): O(S * chunk) memory, differentiable,
   runs on any backend.
3. Pallas TPU flash attention, forward AND backward:
   - forward: tiled online softmax, fp32 accumulators in VMEM scratch,
     causal block skipping, GQA via kv-head index mapping; emits the
     per-row logsumexp (LSE) residual.
   - backward: two-pass flash backward — kernel A recomputes P per tile and
     accumulates dK/dV over the query blocks; kernel B accumulates dQ over
     the kv blocks. No S^2 tensor is ever materialized.
   On non-TPU backends the same kernels run in Pallas interpret mode for
   tests; `flash_attention` dispatches to (2) when shapes don't fit the
   kernel constraints or offsets are used (ring attention's rotating chunks
   handle their own masking).

All functions take q/k/v as [batch, heads, seq, head_dim] (BHSD), GQA as
fewer kv heads (num_q_heads % num_kv_heads == 0). `q_offset`/`kv_offset`
shift the causal mask for sequence-parallel callers.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

if pltpu is not None and not hasattr(pltpu, "CompilerParams"):
    # jax < 0.5 names it TPUCompilerParams; alias so the kernels below
    # track the current spelling while older toolchains keep working.
    pltpu.CompilerParams = pltpu.TPUCompilerParams

NEG_INF = -1e30

# Mosaic requires the last dim of every block to be a multiple of the 128-lane
# vector register (or equal the array dim). Per-row statistics (m, l, lse,
# delta) are therefore carried lane-padded as [rows, 128] with all lanes equal
# — the same convention as jax.experimental.pallas.ops.tpu.flash_attention.
NUM_LANES = 128


def _lane_tile(x128, width: int):
    """Expand an all-lanes-equal [rows, 128] stat to [rows, width]."""
    if width % NUM_LANES == 0:
        reps = width // NUM_LANES
        return x128 if reps == 1 else jnp.tile(x128, (1, reps))
    if width < NUM_LANES:
        return x128[:, :width]
    raise NotImplementedError(f"width {width} not a multiple of {NUM_LANES}")


def _validate(q, k, v):
    if q.ndim != 4 or k.ndim != 4 or v.ndim != 4:
        raise ValueError("q/k/v must be [batch, heads, seq, head_dim]")
    if q.shape[1] % k.shape[1] != 0:
        raise ValueError(
            f"q heads {q.shape[1]} not a multiple of kv heads {k.shape[1]}")


def _expand_kv(q, k, v):
    groups = q.shape[1] // k.shape[1]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    return k, v


def attention_reference(q, k, v, causal: bool = True,
                        sm_scale: Optional[float] = None,
                        q_offset: int = 0, kv_offset: int = 0):
    _validate(q, k, v)
    k, v = _expand_kv(q, k, v)
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[2])[:, None]
        k_pos = kv_offset + jnp.arange(k.shape[2])[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    weights = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights,
                      v.astype(jnp.float32)).astype(q.dtype)


def attention_chunked(q, k, v, causal: bool = True,
                      sm_scale: Optional[float] = None,
                      q_offset: int = 0, kv_offset: int = 0,
                      chunk_size: int = 512):
    """Blockwise attention: scan over KV chunks with running (m, l, acc)."""
    _validate(q, k, v)
    k, v = _expand_kv(q, k, v)
    b, h, sq, d = q.shape
    sk = k.shape[2]
    scale = sm_scale if sm_scale is not None else d ** -0.5
    chunk = min(chunk_size, sk)
    if sk % chunk != 0:
        return attention_reference(q, k, v, causal, sm_scale, q_offset,
                                   kv_offset)
    n_chunks = sk // chunk
    kc = k.reshape(b, h, n_chunks, chunk, d)
    vc = v.reshape(b, h, n_chunks, chunk, d)
    qf = q.astype(jnp.float32)
    q_pos = q_offset + jnp.arange(sq)

    def step(carry, inputs):
        m, l, acc = carry
        idx, k_blk, v_blk = inputs
        logits = jnp.einsum("bhqd,bhkd->bhqk", qf,
                            k_blk.astype(jnp.float32)) * scale
        if causal:
            k_pos = kv_offset + idx * chunk + jnp.arange(chunk)
            mask = q_pos[:, None] >= k_pos[None, :]
            logits = jnp.where(mask, logits, NEG_INF)
        m_blk = jnp.max(logits, axis=-1)
        m_new = jnp.maximum(m, m_blk)
        p = jnp.exp(logits - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l_new = l * correction + jnp.sum(p, axis=-1)
        acc_new = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    init = (jnp.full((b, h, sq), NEG_INF, jnp.float32),
            jnp.zeros((b, h, sq), jnp.float32),
            jnp.zeros((b, h, sq, d), jnp.float32))
    kc_t = jnp.moveaxis(kc, 2, 0)
    vc_t = jnp.moveaxis(vc, 2, 0)
    (m, l, acc), _ = jax.lax.scan(
        step, init, (jnp.arange(n_chunks), kc_t, vc_t))
    return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas TPU kernels
# ---------------------------------------------------------------------------

DEFAULT_BLOCK_Q = 256
DEFAULT_BLOCK_K = 512


def _interpret():
    """Pallas `interpret=` argument: off on real TPU, TPU-interpreter off-TPU.

    The plain HLO interpreter (`interpret=True`) cannot lower `program_id` on
    CPU in this JAX version; `pltpu.InterpretParams` simulates the Mosaic
    grid/DMA semantics on any backend and is the supported test path.
    """
    if jax.default_backend() == "tpu":
        return False
    if pltpu is None or not hasattr(pltpu, "InterpretParams"):
        return True
    return pltpu.InterpretParams()


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, m_scratch, l_scratch,
                acc_scratch, *, sm_scale, causal, block_q, block_k):
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    # program_id must be bound at kernel top level: inside a pl.when
    # branch the interpret-mode cond jaxpr keeps the raw primitive,
    # which has no CPU lowering (jax < 0.5).
    qb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        m_scratch[:] = jnp.full_like(m_scratch, NEG_INF)
        l_scratch[:] = jnp.zeros_like(l_scratch)
        acc_scratch[:] = jnp.zeros_like(acc_scratch)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        logits = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
        d = v.shape[-1]
        m_prev = m_scratch[:]                               # [bq, 128]
        m_blk = jnp.max(logits, axis=-1, keepdims=True)     # [bq, 1]
        m_new = jnp.maximum(m_prev, m_blk)                  # [bq, 128]
        p = jnp.exp(logits - _lane_tile(m_new, block_k))
        correction = jnp.exp(m_prev - m_new)                # [bq, 128]
        m_scratch[:] = m_new
        l_scratch[:] = l_scratch[:] * correction + jnp.sum(
            p, axis=-1, keepdims=True)
        acc_scratch[:] = acc_scratch[:] * _lane_tile(correction, d) + \
            jax.lax.dot(p, v, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kb * block_k <= qb * block_q + block_q - 1)
        def _go():
            _compute()
    else:
        _compute()

    @pl.when(kb == nk - 1)
    def _finalize():
        d = o_ref.shape[-1]
        l_final = jnp.maximum(l_scratch[:], 1e-30)          # [bq, 128]
        o_ref[0] = (acc_scratch[:] / _lane_tile(l_final, d)).astype(
            o_ref.dtype)
        lse_ref[0] = m_scratch[:] + jnp.log(l_final)        # [bq, 128]


def _bwd_kv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                   dk_ref, dv_ref, dk_scratch, dv_scratch,
                   *, sm_scale, causal, block_q, block_k):
    """Grid (bh, nk, nq): for one kv tile, accumulate dK/dV over q tiles."""
    qb = pl.program_id(2)
    nq = pl.num_programs(2)

    kb = pl.program_id(1)

    @pl.when(qb == 0)
    def _init():
        dk_scratch[:] = jnp.zeros_like(dk_scratch)
        dv_scratch[:] = jnp.zeros_like(dv_scratch)

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # [bq, d]
        k = k_ref[0].astype(jnp.float32)          # [bk, d]
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)        # [bq, d]
        lse = _lane_tile(lse_ref[0], block_k)     # [bq, bk]
        delta = _lane_tile(delta_ref[0], block_k)  # [bq, bk]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale   # [bq, bk]
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                       # [bq, bk]
        dv_scratch[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # p^T do -> [bk, d]
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)    # [bq, bk]
        ds = p * (dp - delta) * sm_scale
        dk_scratch[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)    # ds^T q -> [bk, d]

    if causal:
        @pl.when(qb * block_q + block_q - 1 >= kb * block_k)
        def _go():
            _compute()
    else:
        _compute()

    @pl.when(qb == nq - 1)
    def _finalize():
        dk_ref[0] = dk_scratch[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_scratch[:].astype(dv_ref.dtype)


def _bwd_q_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                  dq_ref, dq_scratch, *, sm_scale, causal, block_q, block_k):
    """Grid (bh, nq, nk): for one q tile, accumulate dQ over kv tiles."""
    kb = pl.program_id(2)
    nk = pl.num_programs(2)
    qb = pl.program_id(1)

    @pl.when(kb == 0)
    def _init():
        dq_scratch[:] = jnp.zeros_like(dq_scratch)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = _lane_tile(lse_ref[0], block_k)
        delta = _lane_tile(delta_ref[0], block_k)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * sm_scale
        if causal:
            q_pos = qb * block_q + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = kb * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * sm_scale
        dq_scratch[:] += jax.lax.dot(
            ds, k, preferred_element_type=jnp.float32)

    if causal:
        @pl.when(kb * block_k <= qb * block_q + block_q - 1)
        def _go():
            _compute()
    else:
        _compute()

    @pl.when(kb == nk - 1)
    def _finalize():
        dq_ref[0] = dq_scratch[:].astype(dq_ref.dtype)


def _kernel_params(sq: int, sk: int, d: int):
    block_q = min(DEFAULT_BLOCK_Q, sq)
    block_k = min(DEFAULT_BLOCK_K, sk)
    return block_q, block_k


def _pallas_ok(q, k) -> bool:
    if pltpu is None:
        return False
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q, block_k = _kernel_params(sq, sk, d)
    return (sq % block_q == 0 and sk % block_k == 0
            and block_q % 128 == 0 and block_k % 128 == 0
            and (d % NUM_LANES == 0 or (d < NUM_LANES and d % 8 == 0)))


def _flash_fwd_pallas(q, k, v, causal, sm_scale
                      ) -> Tuple[jax.Array, jax.Array]:
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    groups = h // hk
    block_q, block_k = _kernel_params(sq, sk, d)
    grid = (b * h, sq // block_q, sk // block_k)

    def q_index(bh, qb, kb):
        return (bh, qb, 0)

    def kv_index(bh, qb, kb):
        return ((bh // h) * hk + (bh % h) // groups, kb, 0)

    def lse_index(bh, qb, kb):
        return (bh, qb, 0)

    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, sm_scale=sm_scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_k, d), kv_index),
            pl.BlockSpec((1, block_k, d), kv_index),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), q_index),
            pl.BlockSpec((1, block_q, NUM_LANES), lse_index),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, sq, NUM_LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, NUM_LANES), jnp.float32),
            pltpu.VMEM((block_q, NUM_LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=_interpret(),
    )(q.reshape(b * h, sq, d), k.reshape(b * hk, sk, d),
      v.reshape(b * hk, sk, d))
    return out.reshape(b, h, sq, d), lse[..., 0]


def _flash_bwd_pallas(q, k, v, out, lse, g, causal, sm_scale):
    b, h, sq, d = q.shape
    hk, sk = k.shape[1], k.shape[2]
    groups = h // hk
    block_q, block_k = _kernel_params(sq, sk, d)
    qf = q.reshape(b * h, sq, d)
    kf = k.reshape(b * hk, sk, d)
    vf = v.reshape(b * hk, sk, d)
    dof = g.reshape(b * h, sq, d)
    of = out.reshape(b * h, sq, d)
    delta = jnp.sum(dof.astype(jnp.float32) * of.astype(jnp.float32),
                    axis=-1)  # [bh, sq]
    # Lane-pad per-row stats to [bh, sq, 128] for legal Mosaic block tiles.
    lse = jnp.broadcast_to(lse[..., None], (b * h, sq, NUM_LANES))
    delta = jnp.broadcast_to(delta[..., None], (b * h, sq, NUM_LANES))

    def q_index(bh, a, c):
        return (bh, a if _Q_MAJOR else c, 0)

    # -- dK/dV pass: grid (bh, nk, nq) ----------------------------------
    def kv_pass():
        def qi(bh, kb, qb):
            return (bh, qb, 0)

        def kvi(bh, kb, qb):
            return ((bh // h) * hk + (bh % h) // groups, kb, 0)

        def li(bh, kb, qb):
            return (bh, qb, 0)

        def dkvi(bh, kb, qb):
            return (bh, kb, 0)

        dk, dv = pl.pallas_call(
            functools.partial(_bwd_kv_kernel, sm_scale=sm_scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k),
            grid=(b * h, sk // block_k, sq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, d), qi),
                pl.BlockSpec((1, block_k, d), kvi),
                pl.BlockSpec((1, block_k, d), kvi),
                pl.BlockSpec((1, block_q, d), qi),
                pl.BlockSpec((1, block_q, NUM_LANES), li),
                pl.BlockSpec((1, block_q, NUM_LANES), li),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), dkvi),
                pl.BlockSpec((1, block_k, d), dkvi),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((b * h, sk, d), jnp.float32),
                jax.ShapeDtypeStruct((b * h, sk, d), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_interpret(),
        )(qf, kf, vf, dof, lse, delta)
        return dk, dv

    # -- dQ pass: grid (bh, nq, nk) -------------------------------------
    def q_pass():
        def qi(bh, qb, kb):
            return (bh, qb, 0)

        def kvi(bh, qb, kb):
            return ((bh // h) * hk + (bh % h) // groups, kb, 0)

        def li(bh, qb, kb):
            return (bh, qb, 0)

        dq = pl.pallas_call(
            functools.partial(_bwd_q_kernel, sm_scale=sm_scale,
                              causal=causal, block_q=block_q,
                              block_k=block_k),
            grid=(b * h, sq // block_q, sk // block_k),
            in_specs=[
                pl.BlockSpec((1, block_q, d), qi),
                pl.BlockSpec((1, block_k, d), kvi),
                pl.BlockSpec((1, block_k, d), kvi),
                pl.BlockSpec((1, block_q, d), qi),
                pl.BlockSpec((1, block_q, NUM_LANES), li),
                pl.BlockSpec((1, block_q, NUM_LANES), li),
            ],
            out_specs=pl.BlockSpec((1, block_q, d), qi),
            out_shape=jax.ShapeDtypeStruct((b * h, sq, d), jnp.float32),
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
            ],
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")),
            interpret=_interpret(),
        )(qf, kf, vf, dof, lse, delta)
        return dq

    dk, dv = kv_pass()
    dq = q_pass()
    dq = dq.reshape(b, h, sq, d).astype(q.dtype)
    # GQA: per-q-head dK/dV reduce over the group.
    dk = dk.reshape(b, hk, groups, sk, d).sum(axis=2).astype(k.dtype)
    dv = dv.reshape(b, hk, groups, sk, d).sum(axis=2).astype(v.dtype)
    return dq, dk, dv


_Q_MAJOR = True  # documentation aid for q_index above


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def _flash_tpu(q, k, v, causal: bool, sm_scale: float):
    out, _ = _flash_fwd_pallas(q, k, v, causal, sm_scale)
    return out


def _flash_tpu_fwd(q, k, v, causal, sm_scale):
    out, lse = _flash_fwd_pallas(q, k, v, causal, sm_scale)
    return out, (q, k, v, out, lse)


def _flash_tpu_bwd(causal, sm_scale, residuals, g):
    q, k, v, out, lse = residuals
    return _flash_bwd_pallas(q, k, v, out, lse, g, causal, sm_scale)


_flash_tpu.defvjp(_flash_tpu_fwd, _flash_tpu_bwd)


def flash_attention(q, k, v, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    q_offset: int = 0, kv_offset: int = 0,
                    force_pallas: bool = False):
    """Dispatching flash attention, differentiable everywhere."""
    _validate(q, k, v)
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    if (q_offset == 0 and kv_offset == 0
            and (force_pallas or not _interpret()) and _pallas_ok(q, k)):
        return _flash_tpu(q, k, v, causal, scale)
    return attention_chunked(q, k, v, causal, scale, q_offset, kv_offset)
