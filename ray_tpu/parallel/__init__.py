from .mesh import (DEFAULT_LOGICAL_AXIS_RULES, MeshConfig, dp_rules,
                   named_sharding, params_shardings, shard_logical, unbox)
from .spmd import (TrainState, Zero1Hyper, Zero1State, create_train_state,
                   create_zero1_state, default_optimizer, make_grad_step,
                   make_train_step, make_zero1_apply_step,
                   make_zero1_train_step, opt_state_bytes_per_device)

__all__ = [
    "MeshConfig", "DEFAULT_LOGICAL_AXIS_RULES", "named_sharding",
    "shard_logical", "params_shardings", "unbox", "dp_rules", "TrainState",
    "create_train_state", "make_train_step", "default_optimizer",
    "Zero1Hyper", "Zero1State", "create_zero1_state",
    "make_zero1_train_step", "make_zero1_apply_step", "make_grad_step",
    "opt_state_bytes_per_device",
]
