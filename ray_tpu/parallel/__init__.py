from .mesh import (DEFAULT_LOGICAL_AXIS_RULES, MeshConfig, named_sharding,
                   params_shardings, shard_logical, unbox)
from .spmd import (TrainState, create_train_state, default_optimizer,
                   make_train_step)

__all__ = [
    "MeshConfig", "DEFAULT_LOGICAL_AXIS_RULES", "named_sharding",
    "shard_logical", "params_shardings", "unbox", "TrainState",
    "create_train_state", "make_train_step", "default_optimizer",
]
