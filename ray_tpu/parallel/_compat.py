"""jax version compatibility for shard_map.

jax 0.8 moved shard_map out of experimental and renamed check_rep ->
check_vma. CHECK_KW is the right "replication checking off" kwarg for the
installed version (ppermute/collective results are device-varying)."""

from __future__ import annotations

import inspect

try:
    from jax import shard_map  # jax >= 0.8
except ImportError:  # pragma: no cover — older jax
    from jax.experimental.shard_map import shard_map

CHECK_KW = ({"check_vma": False}
            if "check_vma" in inspect.signature(shard_map).parameters
            else {"check_rep": False})

__all__ = ["CHECK_KW", "shard_map"]
