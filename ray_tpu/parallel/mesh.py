"""Device meshes and logical-axis sharding.

The TPU-native answer to the reference's parallelism delegation (SURVEY §2d):
instead of handing TP/PP/SP to an external engine, parallelism here is a
property of a named device mesh. Pick a MeshConfig, annotate arrays with
logical axis names, and GSPMD inserts the collectives (allreduce /
all-gather / reduce-scatter over ICI, DCN axes across slices).

Axis vocabulary (sizes of 1 are legal and erased at trace time):
  data      — pure data parallelism (batch sharding, gradient allreduce)
  fsdp      — data parallelism with parameter/optimizer sharding (ZeRO-3:
              params all-gathered per layer, grads reduce-scattered)
  tensor    — tensor parallelism (megatron-style head/mlp sharding)
  sequence  — sequence/context parallelism (ring attention, Ulysses)
  expert    — expert parallelism for MoE
  pipeline  — pipeline stages (microbatched shard_map loop)

Logical axis names used by the model libraries are mapped to mesh axes by
LOGICAL_AXIS_RULES (t5x-style), overridable per MeshConfig.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The mesh a serving engine is currently tracing/executing under.
# Model code (e.g. the paged-attention kernel dispatch) reads this to
# decide whether to shard_map over a tensor axis — our own channel, no
# dependency on jax's legacy thread-resources internals.
_SERVING_MESH: contextvars.ContextVar[Optional[Mesh]] = \
    contextvars.ContextVar("rtpu_serving_mesh", default=None)


@contextlib.contextmanager
def serving_mesh(mesh: Optional[Mesh]):
    """Mark `mesh` active for model-side sharding decisions (trace-time:
    wrap every jit call whose trace should see it)."""
    token = _SERVING_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _SERVING_MESH.reset(token)


def current_serving_mesh() -> Optional[Mesh]:
    return _SERVING_MESH.get()

AXIS_ORDER = ("data", "fsdp", "expert", "pipeline", "sequence", "tensor")

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicated)
DEFAULT_LOGICAL_AXIS_RULES: Tuple[Tuple[str, object], ...] = (
    ("batch", ("data", "fsdp")),
    ("activation_batch", ("data", "fsdp")),
    ("activation_seq", "sequence"),
    ("activation_embed", None),
    ("activation_heads", "tensor"),
    ("activation_kv", None),
    ("activation_mlp", "tensor"),
    ("embed", "fsdp"),
    ("vocab", "tensor"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("head_dim", None),
    ("mlp", "tensor"),
    ("expert", "expert"),
    ("layers", None),
    ("stage", "pipeline"),
    ("seq", "sequence"),
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. Unset axes default to 1; `data=-1` absorbs
    whatever devices remain (like a reshape wildcard)."""
    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    pipeline: int = 1
    expert: int = 1
    # Axes that cross slice boundaries ride DCN, not ICI; list them here so
    # multi-slice topologies lay out correctly (reference for the concept:
    # jax multi-slice `dcn_mesh_shape`).
    dcn_axes: Tuple[str, ...] = ()
    logical_axis_rules: Tuple[Tuple[str, object], ...] = \
        DEFAULT_LOGICAL_AXIS_RULES

    def axis_sizes(self, num_devices: int) -> Dict[str, int]:
        sizes = {
            "data": self.data, "fsdp": self.fsdp, "tensor": self.tensor,
            "sequence": self.sequence, "pipeline": self.pipeline,
            "expert": self.expert,
        }
        fixed = math.prod(v for v in sizes.values() if v != -1)
        wildcard = [k for k, v in sizes.items() if v == -1]
        if len(wildcard) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if wildcard:
            if num_devices % fixed != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wildcard[0]] = num_devices // fixed
        elif fixed != num_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {num_devices}")
        return sizes

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.axis_sizes(len(devices))
        shape = tuple(sizes[a] for a in AXIS_ORDER)
        dev_array = np.asarray(devices).reshape(shape)
        return Mesh(dev_array, AXIS_ORDER)

    def rules_dict(self) -> Dict[str, object]:
        return dict(self.logical_axis_rules)


def logical_to_mesh_axes(logical_axes: Sequence[Optional[str]],
                         rules: Dict[str, object]) -> P:
    """Map ('batch','seq','embed') -> PartitionSpec(('data','fsdp'),...)"""
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
        else:
            out.append(rules.get(name))
    return P(*out)


def named_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                   rules: Optional[Dict[str, object]] = None) -> NamedSharding:
    rules = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)
    return NamedSharding(mesh, logical_to_mesh_axes(logical_axes, rules))


def shard_logical(x, mesh: Mesh, logical_axes: Sequence[Optional[str]],
                  rules: Optional[Dict[str, object]] = None):
    """In-jit sharding constraint by logical axis names."""
    spec = logical_to_mesh_axes(
        logical_axes, rules if rules is not None
        else dict(DEFAULT_LOGICAL_AXIS_RULES))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def params_shardings(params, mesh: Mesh,
                     rules: Optional[Dict[str, object]] = None):
    """Build a pytree of NamedShardings from flax logical-axis metadata
    (nn.with_logical_partitioning names on each param)."""
    import flax.linen as nn
    rules_d = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)

    def one(leaf):
        if isinstance(leaf, nn.Partitioned):
            return named_sharding(mesh, leaf.names, rules_d)
        return NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        one, params, is_leaf=lambda x: isinstance(x, nn.Partitioned))


def unbox(params):
    """Strip flax Partitioned boxes to raw arrays."""
    import flax.linen as nn
    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, nn.Partitioned) else x, params,
        is_leaf=lambda x: isinstance(x, nn.Partitioned))


def mesh_info(mesh: Mesh) -> Dict[str, int]:
    return {axis: int(size) for axis, size in mesh.shape.items()}
