"""Device meshes and logical-axis sharding.

The TPU-native answer to the reference's parallelism delegation (SURVEY §2d):
instead of handing TP/PP/SP to an external engine, parallelism here is a
property of a named device mesh. Pick a MeshConfig, annotate arrays with
logical axis names, and GSPMD inserts the collectives (allreduce /
all-gather / reduce-scatter over ICI, DCN axes across slices).

Axis vocabulary (sizes of 1 are legal and erased at trace time):
  data      — pure data parallelism (batch sharding, gradient allreduce)
  fsdp      — data parallelism with parameter/optimizer sharding (ZeRO-3:
              params all-gathered per layer, grads reduce-scattered)
  tensor    — tensor parallelism (megatron-style head/mlp sharding)
  sequence  — sequence/context parallelism (ring attention, Ulysses)
  expert    — expert parallelism for MoE
  pipeline  — pipeline stages (microbatched shard_map loop)

Logical axis names used by the model libraries are mapped to mesh axes by
LOGICAL_AXIS_RULES (t5x-style), overridable per MeshConfig.
"""

from __future__ import annotations

import contextlib
import contextvars
import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# The mesh a serving engine is currently tracing/executing under.
# Model code (e.g. the paged-attention kernel dispatch) reads this to
# decide whether to shard_map over a tensor axis — our own channel, no
# dependency on jax's legacy thread-resources internals.
_SERVING_MESH: contextvars.ContextVar[Optional[Mesh]] = \
    contextvars.ContextVar("rtpu_serving_mesh", default=None)


@contextlib.contextmanager
def serving_mesh(mesh: Optional[Mesh]):
    """Mark `mesh` active for model-side sharding decisions (trace-time:
    wrap every jit call whose trace should see it)."""
    token = _SERVING_MESH.set(mesh)
    try:
        yield mesh
    finally:
        _SERVING_MESH.reset(token)


def current_serving_mesh() -> Optional[Mesh]:
    return _SERVING_MESH.get()

AXIS_ORDER = ("data", "fsdp", "expert", "pipeline", "sequence", "tensor")

# logical axis -> mesh axis (or tuple of mesh axes, or None = replicated)
DEFAULT_LOGICAL_AXIS_RULES: Tuple[Tuple[str, object], ...] = (
    ("batch", ("data", "fsdp")),
    ("activation_batch", ("data", "fsdp")),
    ("activation_seq", "sequence"),
    ("activation_embed", None),
    ("activation_heads", "tensor"),
    ("activation_kv", None),
    ("activation_mlp", "tensor"),
    ("embed", "fsdp"),
    ("vocab", "tensor"),
    ("heads", "tensor"),
    ("kv_heads", "tensor"),
    ("head_dim", None),
    ("mlp", "tensor"),
    ("expert", "expert"),
    ("layers", None),
    ("stage", "pipeline"),
    ("seq", "sequence"),
)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Declarative mesh shape. Unset axes default to 1; `data=-1` absorbs
    whatever devices remain (like a reshape wildcard)."""
    data: int = -1
    fsdp: int = 1
    tensor: int = 1
    sequence: int = 1
    pipeline: int = 1
    expert: int = 1
    # Axes that cross slice boundaries ride DCN, not ICI; list them here
    # so multi-slice topologies lay out correctly: `build()` places each
    # DCN axis ACROSS slices (device groups) and every other axis within
    # one slice, the layout `jax.experimental.mesh_utils.
    # create_hybrid_device_mesh` produces (reference analog: multi-host
    # topology in train/v2/api/config.py:114-123). The product of the
    # DCN axes' sizes must equal the slice count.
    dcn_axes: Tuple[str, ...] = ()
    logical_axis_rules: Tuple[Tuple[str, object], ...] = \
        DEFAULT_LOGICAL_AXIS_RULES

    def axis_sizes(self, num_devices: int) -> Dict[str, int]:
        sizes = {
            "data": self.data, "fsdp": self.fsdp, "tensor": self.tensor,
            "sequence": self.sequence, "pipeline": self.pipeline,
            "expert": self.expert,
        }
        fixed = math.prod(v for v in sizes.values() if v != -1)
        wildcard = [k for k, v in sizes.items() if v == -1]
        if len(wildcard) > 1:
            raise ValueError("at most one mesh axis may be -1")
        if wildcard:
            if num_devices % fixed != 0:
                raise ValueError(
                    f"{num_devices} devices not divisible by fixed axes "
                    f"product {fixed}")
            sizes[wildcard[0]] = num_devices // fixed
        elif fixed != num_devices:
            raise ValueError(
                f"mesh {sizes} needs {fixed} devices, have {num_devices}")
        return sizes

    def build(self, devices: Optional[Sequence] = None,
              num_slices: Optional[int] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        sizes = self.axis_sizes(len(devices))
        if not self.dcn_axes:
            shape = tuple(sizes[a] for a in AXIS_ORDER)
            dev_array = np.asarray(devices).reshape(shape)
            return Mesh(dev_array, AXIS_ORDER)
        return self._build_hybrid(devices, sizes, num_slices)

    def _sliced_devices(self, devices: List, sizes: Dict[str, int],
                        num_slices: Optional[int]) -> Tuple[List, int]:
        """Validate + order devices for a hybrid layout: detect real
        slices via `device.slice_index` (sorted by it) or emulate
        contiguous virtual slices; check the DCN-axes product matches
        the slice count and divides the device count. Returns the
        ordered devices and the slice count."""
        for axis in self.dcn_axes:
            if axis not in sizes:
                raise ValueError(f"unknown dcn axis {axis!r}")
        dcn_total = math.prod(sizes[a] for a in self.dcn_axes)
        slice_ids = {getattr(d, "slice_index", 0) for d in devices}
        if len(slice_ids) > 1:
            if num_slices is not None and num_slices != len(slice_ids):
                raise ValueError(
                    f"num_slices={num_slices} but devices span "
                    f"{len(slice_ids)} slices")
            num_slices = len(slice_ids)
            devices = sorted(
                devices, key=lambda d: (getattr(d, "slice_index", 0),
                                        getattr(d, "id", 0)))
        elif num_slices is None:
            num_slices = dcn_total
        if dcn_total != num_slices:
            raise ValueError(
                f"dcn axes {self.dcn_axes} have total size {dcn_total} "
                f"but the topology has {num_slices} slices")
        if len(devices) % num_slices:
            raise ValueError(
                f"{len(devices)} devices not divisible into "
                f"{num_slices} slices")
        return devices, num_slices

    def _build_hybrid(self, devices: List, sizes: Dict[str, int],
                      num_slices: Optional[int]) -> Mesh:
        """Hybrid ICI×DCN mesh: DCN axes vary across slices, ICI axes
        within one. Real TPU slices are detected via `device.slice_index`
        (devices grouped and ordered by it); hosts without slice ids
        (CPU dryruns, single slice) emulate slices as contiguous device
        groups — pass `num_slices` or let it default to the DCN-axes
        product."""
        devices, num_slices = self._sliced_devices(devices, sizes,
                                                   num_slices)
        dcn_shape = tuple(sizes[a] if a in self.dcn_axes else 1
                          for a in AXIS_ORDER)
        ici_shape = tuple(1 if a in self.dcn_axes else sizes[a]
                          for a in AXIS_ORDER)
        # [dcn..., ici...] then interleave per axis: each final axis is
        # dcn_i * ici_i (one factor is 1), DCN major — so stepping a DCN
        # axis crosses a slice boundary, stepping an ICI axis stays in
        # the same contiguous slice group.
        arr = np.asarray(devices).reshape(dcn_shape + ici_shape)
        n = len(AXIS_ORDER)
        arr = arr.transpose([x for i in range(n) for x in (i, n + i)])
        arr = arr.reshape(tuple(sizes[a] for a in AXIS_ORDER))
        return Mesh(arr, AXIS_ORDER)

    def rules_dict(self) -> Dict[str, object]:
        return dict(self.logical_axis_rules)

    def slice_groups(self, devices: Optional[Sequence] = None,
                     num_slices: Optional[int] = None) -> List[List]:
        """Device groups per slice, in DCN-axis order — the unit for
        host-plane (out-of-program) cross-slice collectives: one leader
        per group talks over the `util.collective` ring while
        in-program collectives stay on ICI within a group."""
        devices = list(devices if devices is not None else jax.devices())
        if not self.dcn_axes:
            return [devices]
        sizes = self.axis_sizes(len(devices))
        devices, num_slices = self._sliced_devices(devices, sizes,
                                                   num_slices)
        per = len(devices) // num_slices
        return [devices[i * per:(i + 1) * per] for i in range(num_slices)]

    def host_topology(self, world_size: int):
        """Collective-backend `Topology` for a host-plane group of
        `world_size` ranks laid out like this mesh's slices: one
        contiguous rank group per slice (the `slice_groups` order), so
        the backend's algorithm selector knows which hops ride DCN.
        The DCN axes must have fixed sizes (their product is the slice
        count)."""
        from ..util.collective.topology import Topology
        return Topology.from_mesh_config(self, world_size)


def dp_rules(dp_axes: Sequence[str],
             base: Optional[Sequence[Tuple[str, object]]] = None
             ) -> Dict[str, object]:
    """Logical-axis rules for a PURE data-parallel layout over
    `dp_axes` (the ZeRO-1 sharded-update requirement: params replicated
    over the update axes). Batch-like logical axes map onto the dp
    axes; any other rule that would shard a tensor over one of them is
    dropped to replicated."""
    dp = tuple(dp_axes)
    dp_set = set(dp)
    out: Dict[str, object] = {}
    for name, target in (base if base is not None
                         else DEFAULT_LOGICAL_AXIS_RULES):
        if name in ("batch", "activation_batch"):
            out[name] = dp if len(dp) > 1 else dp[0]
            continue
        targets = target if isinstance(target, tuple) else (target,)
        if any(t in dp_set for t in targets if t is not None):
            out[name] = None
        else:
            out[name] = target
    return out


def logical_to_mesh_axes(logical_axes: Sequence[Optional[str]],
                         rules: Dict[str, object]) -> P:
    """Map ('batch','seq','embed') -> PartitionSpec(('data','fsdp'),...)"""
    out = []
    for name in logical_axes:
        if name is None:
            out.append(None)
        else:
            out.append(rules.get(name))
    return P(*out)


def named_sharding(mesh: Mesh, logical_axes: Sequence[Optional[str]],
                   rules: Optional[Dict[str, object]] = None) -> NamedSharding:
    rules = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)
    return NamedSharding(mesh, logical_to_mesh_axes(logical_axes, rules))


def shard_logical(x, mesh: Mesh, logical_axes: Sequence[Optional[str]],
                  rules: Optional[Dict[str, object]] = None):
    """In-jit sharding constraint by logical axis names."""
    spec = logical_to_mesh_axes(
        logical_axes, rules if rules is not None
        else dict(DEFAULT_LOGICAL_AXIS_RULES))
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, spec))


def params_shardings(params, mesh: Mesh,
                     rules: Optional[Dict[str, object]] = None):
    """Build a pytree of NamedShardings from flax logical-axis metadata
    (nn.with_logical_partitioning names on each param)."""
    import flax.linen as nn
    rules_d = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)

    def one(leaf):
        if isinstance(leaf, nn.Partitioned):
            return named_sharding(mesh, leaf.names, rules_d)
        return NamedSharding(mesh, P())
    return jax.tree_util.tree_map(
        one, params, is_leaf=lambda x: isinstance(x, nn.Partitioned))


def unbox(params):
    """Strip flax Partitioned boxes to raw arrays."""
    import flax.linen as nn
    return jax.tree_util.tree_map(
        lambda x: x.value if isinstance(x, nn.Partitioned) else x, params,
        is_leaf=lambda x: isinstance(x, nn.Partitioned))


def mesh_info(mesh: Mesh) -> Dict[str, int]:
    return {axis: int(size) for axis, size in mesh.shape.items()}
