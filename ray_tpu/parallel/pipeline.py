"""Pipeline parallelism: microbatched GPipe over the `pipeline` mesh axis.

TPU-native design (SURVEY §2d requires PP first-class; the reference
delegates it to vLLM — llm/_internal/serve/deployments/llm/vllm/
vllm_models.py:173): stage parameters carry a leading `stage` dimension
sharded over the `pipeline` mesh axis; one shard_map program runs the
rotating-microbatch schedule with `ppermute` moving activations stage→stage
over ICI. The schedule is written as a forward `lax.scan` only — reverse-mode
AD differentiates through the scan and ppermutes, so the backward pipeline
(activations reverse-flowing) is derived by the compiler rather than
hand-scheduled, and `jax.checkpoint` on the stage function gives 1F1B-grade
memory behavior (stash only stage inputs, recompute internals).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from ._compat import CHECK_KW as _CHECK_KW, shard_map


def stack_stage_params(per_stage_params: list) -> Any:
    """Stack S per-stage param pytrees into one tree with a leading stage
    axis (shard it on `pipeline` via the 'stage' logical axis)."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *per_stage_params)


def gpipe(stage_fn: Callable, num_stages: int, num_microbatches: int,
          mesh: Mesh, axis_name: str = "pipeline",
          remat: bool = True) -> Callable:
    """Build `fn(stacked_params, x) -> y` running the GPipe schedule.

    stage_fn(params_s, x_mb) -> y_mb applies ONE stage to ONE microbatch
    (shapes of x_mb and y_mb must match — the usual transformer-block
    contract). x has leading batch dim divisible by num_microbatches.
    """
    if remat:
        stage_fn = jax.checkpoint(stage_fn)

    def pipelined(stacked_params, x):
        mb = jnp.reshape(x, (num_microbatches, -1) + x.shape[1:])

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(P(axis_name), P()),  # params: stage-sharded; x: repl
            out_specs=P(),
            **_CHECK_KW)
        def run(params_shard, mb_all):
            # Each device holds its stage's params with leading dim 1.
            params_local = jax.tree_util.tree_map(
                lambda p: jnp.squeeze(p, 0), params_shard)
            stage = jax.lax.axis_index(axis_name)
            S, M = num_stages, num_microbatches
            total = M + S - 1
            perm = [(i, (i + 1) % S) for i in range(S)]

            def step(carry, t):
                send, acc = carry
                recv = jax.lax.ppermute(send, axis_name, perm)
                mb_index = jnp.clip(t, 0, M - 1)
                first_stage_in = jax.lax.dynamic_index_in_dim(
                    mb_all, mb_index, axis=0, keepdims=False)
                x_in = jnp.where(stage == 0, first_stage_in, recv)
                y = stage_fn(params_local, x_in)
                out_slot = t - (S - 1)
                is_output = jnp.logical_and(stage == S - 1, out_slot >= 0)
                acc = jax.lax.cond(
                    is_output,
                    lambda a: jax.lax.dynamic_update_index_in_dim(
                        a, y, jnp.clip(out_slot, 0, M - 1), axis=0),
                    lambda a: a, acc)
                return (y, acc), None

            send0 = jnp.zeros_like(mb_all[0])
            acc0 = jnp.zeros_like(mb_all)
            (_, acc), _ = jax.lax.scan(step, (send0, acc0),
                                       jnp.arange(total))
            # Only the last stage holds real outputs; broadcast them.
            acc = jnp.where(stage == S - 1, acc, jnp.zeros_like(acc))
            return jax.lax.psum(acc, axis_name)

        out = run(stacked_params, mb)
        return jnp.reshape(out, x.shape[:1] + out.shape[2:])

    return pipelined


def split_layers_into_stages(layer_params: list, num_stages: int) -> list:
    """Group L per-layer param trees into S stacked per-stage trees
    (each stage applies L/S layers sequentially)."""
    L = len(layer_params)
    if L % num_stages != 0:
        raise ValueError(f"{L} layers not divisible into {num_stages} stages")
    per = L // num_stages
    stages = []
    for s in range(num_stages):
        group = layer_params[s * per:(s + 1) * per]
        stages.append(jax.tree_util.tree_map(
            lambda *leaves: jnp.stack(leaves), *group))
    return stages


def make_stage_fn(layer_fn: Callable) -> Callable:
    """Lift layer_fn(params_l, x) -> x into a stage applying its stacked
    layers with a scan (keeps the stage a single compiled loop)."""
    def stage_fn(stage_params, x):
        def body(h, params_l):
            return layer_fn(params_l, h), None
        out, _ = jax.lax.scan(body, x, stage_params)
        return out
    return stage_fn
