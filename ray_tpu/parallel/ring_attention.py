"""Ring attention: sequence-parallel exact attention over the ICI ring.

Absent from the reference (SURVEY §2d verifies no ring/context/sequence
parallelism exists there); first-class here. Q/K/V are sharded over the
`sequence` mesh axis; each step every device attends its local Q block
against the K/V block currently in hand, accumulates with the online-softmax
merge (numerically exact), then rotates K/V to its ring neighbor with
`ppermute` — overlapping the rotation with compute is XLA's job (the
collective-permute is async on TPU and latency-hides behind the matmuls).

Memory: O(S_local) per device — sequence length scales linearly with ring
size. Causal masking uses global position offsets so the sharded result is
bit-comparable to single-device attention (tests assert this).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ._compat import CHECK_KW, shard_map

NEG_INF = -1e30


def _merge_block(q, k, v, m, l, acc, causal, q_off, kv_off, scale):
    """One online-softmax accumulation of q against the (k, v) block.
    q: [b,h,sq,d]; k/v: [b,h,sk,d]; m,l: [b,h,sq]; acc: [b,h,sq,d]."""
    logits = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    if causal:
        q_pos = q_off + jnp.arange(q.shape[2])[:, None]
        k_pos = kv_off + jnp.arange(k.shape[2])[None, :]
        logits = jnp.where(q_pos >= k_pos, logits, NEG_INF)
    m_blk = jnp.max(logits, axis=-1)
    m_new = jnp.maximum(m, m_blk)
    p = jnp.exp(logits - m_new[..., None])
    correction = jnp.exp(m - m_new)
    l_new = l * correction + jnp.sum(p, axis=-1)
    acc_new = acc * correction[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return m_new, l_new, acc_new


def ring_attention(q, k, v, mesh: Mesh, axis_name: str = "sequence",
                   causal: bool = True, sm_scale: Optional[float] = None):
    """q/k/v: [batch, heads, seq, head_dim], sharded over seq on `axis_name`.
    Returns attention output with the same sharding. GQA: pass k/v with
    fewer heads; they are expanded before the ring."""
    groups = q.shape[1] // k.shape[1]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    n = mesh.shape[axis_name]
    if n == 1:
        from ..ops.attention import attention_chunked
        return attention_chunked(q, k, v, causal, scale)

    spec = P(None, None, axis_name, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, **CHECK_KW)
    def _ring(q_blk, k_blk, v_blk):
        b, h, s_local, d = q_blk.shape
        rank = jax.lax.axis_index(axis_name)
        q_off = rank * s_local
        perm = [(j, (j + 1) % n) for j in range(n)]

        def step(i, carry):
            k_cur, v_cur, m, l, acc = carry
            # After i rotations we hold the block produced by rank - i.
            src = (rank - i) % n
            kv_off = src * s_local
            m, l, acc = _merge_block(q_blk, k_cur, v_cur, m, l, acc,
                                     causal, q_off, kv_off, scale)
            k_next = jax.lax.ppermute(k_cur, axis_name, perm)
            v_next = jax.lax.ppermute(v_cur, axis_name, perm)
            return (k_next, v_next, m, l, acc)

        init = (k_blk, v_blk,
                jnp.full((b, h, s_local), NEG_INF, jnp.float32),
                jnp.zeros((b, h, s_local), jnp.float32),
                jnp.zeros((b, h, s_local, d), jnp.float32))
        _, _, m, l, acc = jax.lax.fori_loop(0, n, step, init)
        return (acc / jnp.maximum(l, 1e-30)[..., None]).astype(q_blk.dtype)

    return _ring(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis_name: str = "sequence",
                      causal: bool = True,
                      sm_scale: Optional[float] = None):
    """Ulysses/DeepSpeed-style sequence parallelism: all-to-all swaps the
    sharded axis from sequence to heads, runs full-sequence attention
    locally, and swaps back. Two all-to-alls instead of a ring — better when
    heads >> ring size and the interconnect favors bulk all-to-all."""
    groups = q.shape[1] // k.shape[1]
    if groups > 1:
        k = jnp.repeat(k, groups, axis=1)
        v = jnp.repeat(v, groups, axis=1)
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    n = mesh.shape[axis_name]
    if n == 1:
        from ..ops.attention import attention_chunked
        return attention_chunked(q, k, v, causal, scale)
    if q.shape[1] % n != 0:
        raise ValueError(f"heads {q.shape[1]} must divide the "
                         f"{axis_name} axis size {n}")

    spec = P(None, None, axis_name, None)

    @functools.partial(
        shard_map, mesh=mesh, in_specs=(spec, spec, spec),
        out_specs=spec, **CHECK_KW)
    def _ulysses(q_blk, k_blk, v_blk):
        # [b, H, S/n, d] -> [b, H/n, S, d]
        def swap_in(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=1,
                                      concat_axis=2, tiled=True)

        def swap_out(x):
            return jax.lax.all_to_all(x, axis_name, split_axis=2,
                                      concat_axis=1, tiled=True)

        from ..ops.attention import attention_chunked
        out = attention_chunked(swap_in(q_blk), swap_in(k_blk),
                                swap_in(v_blk), causal, scale)
        return swap_out(out)

    return _ulysses(q, k, v)
