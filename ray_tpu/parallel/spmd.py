"""SPMD training: sharded state creation + pjit train step.

This is the hot path of the whole framework: one jitted function per train
step, parameters/optimizer state laid out by logical-axis rules, gradients
synchronized by GSPMD-inserted collectives over ICI (no NCCL-style explicit
allreduce — the mesh IS the communication backend; SURVEY §2d/§5).
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ._compat import CHECK_KW as _CHECK_KW, shard_map
from .mesh import (DEFAULT_LOGICAL_AXIS_RULES, logical_to_mesh_axes,
                   named_sharding, params_shardings, unbox)


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt_state = self.tx.update(grads, self.opt_state,
                                                self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state)


def logical_names_tree(model: nn.Module, rng, sample_input) -> Any:
    """Pytree of logical-axis-name tuples (or None) per param leaf."""
    boxed = jax.eval_shape(lambda r: model.init(r, sample_input), rng)
    boxed = boxed["params"]

    def one(leaf):
        if isinstance(leaf, nn.Partitioned):
            return leaf.names
        return None
    return jax.tree_util.tree_map(
        one, boxed, is_leaf=lambda x: isinstance(x, nn.Partitioned))


def shardings_tree(names_tree, mesh: Mesh, rules: Dict[str, Any]):
    def one(names):
        if names is None:
            return NamedSharding(mesh, P())
        return named_sharding(mesh, names, rules)
    return jax.tree_util.tree_map(one, names_tree,
                                  is_leaf=lambda x: x is None
                                  or isinstance(x, tuple))


def create_train_state(rng, model: nn.Module, sample_input,
                       mesh: Mesh, tx: optax.GradientTransformation,
                       rules: Optional[Dict[str, Any]] = None) -> TrainState:
    """Initialize parameters *already sharded* across the mesh: the init fn
    is jitted with sharding constraints inside so no host ever materializes
    the full parameter tree."""
    rules = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)
    names = logical_names_tree(model, rng, sample_input)
    shardings = shardings_tree(names, mesh, rules)

    def init_fn(r):
        params = unbox(model.init(r, sample_input)["params"])
        params = jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, params, shardings)
        opt_state = tx.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state, apply_fn=model.apply, tx=tx)

    with mesh:
        return jax.jit(init_fn)(rng)


def make_train_step(loss_fn: Callable, mesh: Mesh,
                    rules: Optional[Dict[str, Any]] = None,
                    batch_axes: Tuple = ("batch", "seq"),
                    donate: bool = True,
                    state: Optional[TrainState] = None):
    """Build the jitted SPMD train step.

    loss_fn(params, batch) -> scalar loss (model.apply inside). The batch is
    constrained to the data axes; everything else is GSPMD's problem.

    Pass the concrete initial `state` to pin the step's OUTPUT state to
    the initial state's shardings. Without it, GSPMD may choose output
    layouts that differ from the input's, and the SECOND call — whose
    input is the first call's output — pays a full re-compile (at 7B
    scale that is minutes of XLA time for an identical program).
    """
    # accel plane: arm XLA compile tracking before this step's (large)
    # compile so rtpu_xla_compile_seconds_total sees it
    from .._internal import accel
    accel.ensure_installed()
    rules = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)
    batch_sharding = named_sharding(mesh, batch_axes, rules)

    def step_fn(state: TrainState, batch):
        batch = jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(
                x, batch_sharding) if x.ndim == len(batch_axes) else x,
            batch)
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        new_state = state.apply_gradients(grads)
        metrics = {"loss": loss,
                   "grad_norm": optax.global_norm(grads)}
        return new_state, metrics

    kwargs: Dict[str, Any] = {}
    if state is not None:
        state_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding, state)
        # pytree-prefix: fixed shardings for the state, compiler's
        # choice (None) for the metrics dict
        kwargs["out_shardings"] = (state_shardings, None)
    return jax.jit(step_fn, donate_argnums=(0,) if donate else (),
                   **kwargs)


# ---------------------------------------------------------------------------
# ZeRO-1 sharded weight updates (cross-replica, arxiv 2004.13336)
# ---------------------------------------------------------------------------
#
# The replicated-update schedule every data-parallel rank runs is
# allreduce(grads) -> full Adam -> identical params: W copies of the
# optimizer state and 2x the reduction bytes actually needed. The
# sharded schedule partitions the FLAT optimizer state over the
# data-parallel axes: reduce-scatter the grads (each rank receives the
# reduced 1/W shard it owns), run Adam shard-local on its m/v slice,
# and allgather only the parameter DELTA — optimizer memory drops by W
# and the wire carries reduce-scatter + allgather instead of a full
# allreduce plus W redundant updates. jax.lax.psum_scatter/all_gather
# inside shard_map lower to exactly those HLO collectives (pinned by
# test_train_gspmd's HLO assertion).


@dataclasses.dataclass(frozen=True)
class Zero1Hyper:
    """AdamW hyperparameters for the sharded update (matches
    optax.chain(clip_by_global_norm, adamw) leaf for leaf so the parity
    tests can diff against the reference optimizer bit-for-bit-ish)."""
    learning_rate: Any = 3e-4      # float, or callable(step)->lr
    b1: float = 0.9
    b2: float = 0.999
    eps: float = 1e-8
    weight_decay: float = 0.0
    clip_norm: float = 0.0         # 0 = no clipping

    def lr(self, step):
        if callable(self.learning_rate):
            return self.learning_rate(step)
        return self.learning_rate


class Zero1State(flax.struct.PyTreeNode):
    """Train state whose optimizer moments live as ONE flat fp32 buffer
    each, sharded over the data-parallel mesh axes (1/W per device)."""
    step: jax.Array
    params: Any
    m: jax.Array                   # (pad_n,) fp32, P(axes)
    v: jax.Array                   # (pad_n,) fp32, P(axes)
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    hyper: Zero1Hyper = flax.struct.field(pytree_node=False)


def _flat_meta(params) -> Tuple[Any, list, int]:
    leaves, treedef = jax.tree_util.tree_flatten(params)
    sizes = [int(math.prod(l.shape)) if l.shape else 1 for l in leaves]
    return treedef, sizes, sum(sizes)


def _flatten_f32(tree) -> jax.Array:
    leaves = jax.tree_util.tree_leaves(tree)
    return jnp.concatenate(
        [l.astype(jnp.float32).reshape(-1) for l in leaves]) \
        if leaves else jnp.zeros((0,), jnp.float32)


def _unflatten_like(flat: jax.Array, params):
    leaves, treedef = jax.tree_util.tree_flatten(params)
    out, offset = [], 0
    for leaf in leaves:
        size = int(math.prod(leaf.shape)) if leaf.shape else 1
        part = jax.lax.dynamic_slice_in_dim(flat, offset, size)
        out.append(part.reshape(leaf.shape).astype(leaf.dtype))
        offset += size
    return jax.tree_util.tree_unflatten(treedef, out)


def _zero1_axes_size(mesh: Mesh, axes: Sequence[str]) -> int:
    return int(math.prod(mesh.shape[a] for a in axes))


def _check_params_replicated(shardings, axes: Sequence[str]):
    """The flat-buffer schedule slices the param vector over `axes`;
    the params must therefore be replicated over them (they may be
    sharded over OTHER axes only via size-1 — the flat concat cannot
    cross a physical shard boundary)."""
    axset = set(axes)

    def _names(spec):
        for entry in spec:
            if entry is None:
                continue
            for name in (entry if isinstance(entry, tuple) else (entry,)):
                yield name

    for sh in jax.tree_util.tree_leaves(shardings):
        spec = getattr(sh, "spec", None)
        if spec is None:
            continue
        used = set(_names(spec)) & axset
        if used:
            raise ValueError(
                f"zero-1 sharded updates over axes {tuple(axes)} require "
                f"params replicated over them, but a param is sharded "
                f"over {sorted(used)}; drop those rules (dp_rules) or "
                f"pick different update axes")


def create_zero1_state(rng, model: nn.Module, sample_input, mesh: Mesh,
                       hyper: Optional[Zero1Hyper] = None,
                       rules: Optional[Dict[str, Any]] = None,
                       axes: Sequence[str] = ("data",)) -> Zero1State:
    """Initialize params (sharded per rules, replicated over `axes`)
    plus flat m/v buffers partitioned over the data-parallel `axes`."""
    hyper = hyper or Zero1Hyper()
    rules = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)
    names = logical_names_tree(model, rng, sample_input)
    shardings = shardings_tree(names, mesh, rules)
    _check_params_replicated(shardings, axes)
    W = _zero1_axes_size(mesh, axes)

    abstract = jax.eval_shape(
        lambda r: unbox(model.init(r, sample_input)["params"]), rng)
    _, _, n = _flat_meta(abstract)
    pad_n = -(-n // W) * W
    spec = P(tuple(axes) if len(axes) > 1 else axes[0])
    opt_sharding = NamedSharding(mesh, spec)

    def init_fn(r):
        params = unbox(model.init(r, sample_input)["params"])
        params = jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, params, shardings)
        m = jax.lax.with_sharding_constraint(
            jnp.zeros((pad_n,), jnp.float32), opt_sharding)
        v = jax.lax.with_sharding_constraint(
            jnp.zeros((pad_n,), jnp.float32), opt_sharding)
        return Zero1State(step=jnp.zeros((), jnp.int32), params=params,
                          m=m, v=v, apply_fn=model.apply, hyper=hyper)

    with mesh:
        return jax.jit(init_fn)(rng)


def _adam_shard_update(g_l, p_l, m_l, v_l, t, hyper: Zero1Hyper):
    """Shard-local AdamW on the rank's 1/W slice. `t` is the 1-based
    step for bias correction. Returns (delta_l, m_l, v_l) — delta is
    what allgather rebuilds (params never leave their replicas)."""
    m_l = hyper.b1 * m_l + (1.0 - hyper.b1) * g_l
    v_l = hyper.b2 * v_l + (1.0 - hyper.b2) * g_l * g_l
    tf = t.astype(jnp.float32)
    mhat = m_l / (1.0 - hyper.b1 ** tf)
    vhat = v_l / (1.0 - hyper.b2 ** tf)
    update = mhat / (jnp.sqrt(vhat) + hyper.eps)
    if hyper.weight_decay:
        update = update + hyper.weight_decay * p_l
    delta_l = -hyper.lr(t) * update
    return delta_l, m_l, v_l


def _clip_scale(gnorm, clip_norm: float):
    if not clip_norm:
        return 1.0
    # optax.clip_by_global_norm semantics: identity below the threshold,
    # exact rescale to the threshold above it.
    return jnp.where(gnorm < clip_norm, 1.0, clip_norm / gnorm)


def make_zero1_train_step(loss_fn: Callable, mesh: Mesh,
                          state: Zero1State,
                          axes: Sequence[str] = ("data",),
                          donate: bool = True):
    """Fused ZeRO-1 step: per-shard backward on the local microbatch,
    reduce-scatter(mean) of the flat grads, shard-local AdamW,
    allgather of the param delta — one jitted program.

    loss_fn(params, batch) -> scalar loss on the LOCAL microbatch; the
    batch pytree's leading dim is split over `axes` (global batch must
    be divisible by their product). Returns step(state, batch) ->
    (state, {"loss", "grad_norm"})."""
    from .._internal import accel
    accel.ensure_installed()
    axes = tuple(axes)
    W = _zero1_axes_size(mesh, axes)
    hyper = state.hyper
    treedef, sizes, n = _flat_meta(state.params)
    pad_n = int(state.m.size)
    assert pad_n == -(-n // W) * W, (pad_n, n, W)
    shard = pad_n // W
    ax = axes if len(axes) > 1 else axes[0]
    batch_spec = P(ax)

    def step_fn(state: Zero1State, batch):
        params, m, v, step = state.params, state.m, state.v, state.step
        t = step + 1
        param_specs = jax.tree_util.tree_map(lambda _: P(), params)
        batch_specs = jax.tree_util.tree_map(lambda _: batch_spec, batch)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(param_specs, P(ax), P(ax), batch_specs),
            out_specs=(P(), P(ax), P(ax), P(), P()),
            **_CHECK_KW)
        def run(params, m_l, v_l, batch_l):
            loss, grads = jax.value_and_grad(loss_fn)(params, batch_l)
            flat = _flatten_f32(grads)
            flat = jnp.pad(flat, (0, pad_n - n))
            # reduce-scatter: each rank ends with the MEAN grad of the
            # 1/W slice it owns (psum_scatter sums the W local grads)
            g_l = jax.lax.psum_scatter(
                flat, ax, scatter_dimension=0, tiled=True) / W
            # global grad norm from the reduced shards (disjoint slices)
            gnorm = jnp.sqrt(jax.lax.psum(jnp.sum(g_l * g_l), ax))
            g_l = g_l * _clip_scale(gnorm, hyper.clip_norm)
            idx = jax.lax.axis_index(ax)
            flat_p = jnp.pad(_flatten_f32(params), (0, pad_n - n))
            p_l = jax.lax.dynamic_slice_in_dim(flat_p, idx * shard, shard)
            delta_l, m_l, v_l = _adam_shard_update(
                g_l, p_l, m_l, v_l, t, hyper)
            delta = jax.lax.all_gather(delta_l, ax, tiled=True)
            new_params = jax.tree_util.tree_map(
                lambda p, d: p + d.astype(p.dtype),
                params, _unflatten_like(delta[:pad_n], params))
            return (new_params, m_l, v_l, jax.lax.pmean(loss, ax),
                    gnorm)

        new_params, new_m, new_v, loss, gnorm = run(params, m, v, batch)
        new_state = state.replace(step=t, params=new_params,
                                  m=new_m, v=new_v)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    state_shardings = jax.tree_util.tree_map(lambda x: x.sharding, state)
    return jax.jit(step_fn, donate_argnums=(0,) if donate else (),
                   out_shardings=(state_shardings, None))


def make_zero1_apply_step(mesh: Mesh, state: Zero1State,
                          axes: Sequence[str] = ("data",),
                          donate: bool = True):
    """Apply-only half of the sharded update for groups whose gradient
    combine happens OUT of program (the cross-slice host/DCN hop via
    `train.allreduce_gradients`): grads arrive already mean-combined
    and replicated; each rank slices its 1/W shard ("scatter" without
    wire bytes), runs shard-local AdamW, and allgathers the delta.
    Returns apply(state, grads) -> state."""
    axes = tuple(axes)
    W = _zero1_axes_size(mesh, axes)
    hyper = state.hyper
    _, _, n = _flat_meta(state.params)
    pad_n = int(state.m.size)
    shard = pad_n // W
    ax = axes if len(axes) > 1 else axes[0]

    def apply_fn(state: Zero1State, grads):
        params, m, v = state.params, state.m, state.v
        t = state.step + 1
        param_specs = jax.tree_util.tree_map(lambda _: P(), params)
        grad_specs = jax.tree_util.tree_map(lambda _: P(), grads)

        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=(param_specs, grad_specs, P(ax), P(ax)),
            out_specs=(P(), P(ax), P(ax), P()),
            **_CHECK_KW)
        def run(params, grads, m_l, v_l):
            flat = jnp.pad(_flatten_f32(grads), (0, pad_n - n))
            gnorm = jnp.sqrt(jnp.sum(flat * flat))
            idx = jax.lax.axis_index(ax)
            g_l = jax.lax.dynamic_slice_in_dim(flat, idx * shard, shard)
            g_l = g_l * _clip_scale(gnorm, hyper.clip_norm)
            flat_p = jnp.pad(_flatten_f32(params), (0, pad_n - n))
            p_l = jax.lax.dynamic_slice_in_dim(flat_p, idx * shard, shard)
            delta_l, m_l, v_l = _adam_shard_update(
                g_l, p_l, m_l, v_l, t, hyper)
            delta = jax.lax.all_gather(delta_l, ax, tiled=True)
            new_params = jax.tree_util.tree_map(
                lambda p, d: p + d.astype(p.dtype),
                params, _unflatten_like(delta[:pad_n], params))
            return new_params, m_l, v_l, gnorm

        new_params, new_m, new_v, gnorm = run(params, grads, m, v)
        new_state = state.replace(step=t, params=new_params,
                                  m=new_m, v=new_v)
        return new_state, {"grad_norm": gnorm}

    state_shardings = jax.tree_util.tree_map(lambda x: x.sharding, state)
    return jax.jit(apply_fn, donate_argnums=(0,) if donate else (),
                   out_shardings=(state_shardings, None))


def make_grad_step(loss_fn: Callable, mesh: Mesh,
                   rules: Optional[Dict[str, Any]] = None,
                   batch_axes: Tuple = ("batch", "seq")):
    """Jitted (loss, grads) for the two-level schedule: in-program
    GSPMD handles intra-slice sharding, the caller moves the returned
    grads over the cross-slice (host/DCN) hop before applying."""
    rules = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)
    batch_sharding = named_sharding(mesh, batch_axes, rules)

    def grad_fn(params, batch):
        batch = jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(
                x, batch_sharding) if x.ndim == len(batch_axes) else x,
            batch)
        return jax.value_and_grad(loss_fn)(params, batch)

    return jax.jit(grad_fn)


def opt_state_bytes_per_device(state) -> int:
    """Actual per-device optimizer-state residency (device 0's
    addressable shards): the number the ZeRO-1 memory claim is gated
    on — sharded m/v report ~1/W of the replicated footprint."""
    import numpy as np
    leaves = []
    if isinstance(state, Zero1State):
        leaves = [state.m, state.v]
    else:
        leaves = jax.tree_util.tree_leaves(getattr(state, "opt_state",
                                                   state))
    total = 0
    for leaf in leaves:
        if hasattr(leaf, "addressable_shards"):
            shard = leaf.addressable_shards[0]
            total += int(np.prod(shard.data.shape)) * leaf.dtype.itemsize
        elif hasattr(leaf, "nbytes"):
            total += int(leaf.nbytes)
    return total


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10_000,
                      b1: float = 0.9, b2: float = 0.95,
                      clip_norm: float = 1.0) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )
