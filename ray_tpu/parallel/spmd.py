"""SPMD training: sharded state creation + pjit train step.

This is the hot path of the whole framework: one jitted function per train
step, parameters/optimizer state laid out by logical-axis rules, gradients
synchronized by GSPMD-inserted collectives over ICI (no NCCL-style explicit
allreduce — the mesh IS the communication backend; SURVEY §2d/§5).
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Dict, Optional, Tuple

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import (DEFAULT_LOGICAL_AXIS_RULES, logical_to_mesh_axes,
                   named_sharding, params_shardings, unbox)


class TrainState(flax.struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: Any
    apply_fn: Callable = flax.struct.field(pytree_node=False)
    tx: optax.GradientTransformation = flax.struct.field(pytree_node=False)

    def apply_gradients(self, grads):
        updates, new_opt_state = self.tx.update(grads, self.opt_state,
                                                self.params)
        new_params = optax.apply_updates(self.params, updates)
        return self.replace(step=self.step + 1, params=new_params,
                            opt_state=new_opt_state)


def logical_names_tree(model: nn.Module, rng, sample_input) -> Any:
    """Pytree of logical-axis-name tuples (or None) per param leaf."""
    boxed = jax.eval_shape(lambda r: model.init(r, sample_input), rng)
    boxed = boxed["params"]

    def one(leaf):
        if isinstance(leaf, nn.Partitioned):
            return leaf.names
        return None
    return jax.tree_util.tree_map(
        one, boxed, is_leaf=lambda x: isinstance(x, nn.Partitioned))


def shardings_tree(names_tree, mesh: Mesh, rules: Dict[str, Any]):
    def one(names):
        if names is None:
            return NamedSharding(mesh, P())
        return named_sharding(mesh, names, rules)
    return jax.tree_util.tree_map(one, names_tree,
                                  is_leaf=lambda x: x is None
                                  or isinstance(x, tuple))


def create_train_state(rng, model: nn.Module, sample_input,
                       mesh: Mesh, tx: optax.GradientTransformation,
                       rules: Optional[Dict[str, Any]] = None) -> TrainState:
    """Initialize parameters *already sharded* across the mesh: the init fn
    is jitted with sharding constraints inside so no host ever materializes
    the full parameter tree."""
    rules = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)
    names = logical_names_tree(model, rng, sample_input)
    shardings = shardings_tree(names, mesh, rules)

    def init_fn(r):
        params = unbox(model.init(r, sample_input)["params"])
        params = jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, params, shardings)
        opt_state = tx.init(params)
        return TrainState(step=jnp.zeros((), jnp.int32), params=params,
                          opt_state=opt_state, apply_fn=model.apply, tx=tx)

    with mesh:
        return jax.jit(init_fn)(rng)


def make_train_step(loss_fn: Callable, mesh: Mesh,
                    rules: Optional[Dict[str, Any]] = None,
                    batch_axes: Tuple = ("batch", "seq"),
                    donate: bool = True,
                    state: Optional[TrainState] = None):
    """Build the jitted SPMD train step.

    loss_fn(params, batch) -> scalar loss (model.apply inside). The batch is
    constrained to the data axes; everything else is GSPMD's problem.

    Pass the concrete initial `state` to pin the step's OUTPUT state to
    the initial state's shardings. Without it, GSPMD may choose output
    layouts that differ from the input's, and the SECOND call — whose
    input is the first call's output — pays a full re-compile (at 7B
    scale that is minutes of XLA time for an identical program).
    """
    # accel plane: arm XLA compile tracking before this step's (large)
    # compile so rtpu_xla_compile_seconds_total sees it
    from .._internal import accel
    accel.ensure_installed()
    rules = rules if rules is not None else dict(DEFAULT_LOGICAL_AXIS_RULES)
    batch_sharding = named_sharding(mesh, batch_axes, rules)

    def step_fn(state: TrainState, batch):
        batch = jax.tree_util.tree_map(
            lambda x: jax.lax.with_sharding_constraint(
                x, batch_sharding) if x.ndim == len(batch_axes) else x,
            batch)
        loss, grads = jax.value_and_grad(loss_fn)(state.params, batch)
        new_state = state.apply_gradients(grads)
        metrics = {"loss": loss,
                   "grad_norm": optax.global_norm(grads)}
        return new_state, metrics

    kwargs: Dict[str, Any] = {}
    if state is not None:
        state_shardings = jax.tree_util.tree_map(
            lambda x: x.sharding, state)
        # pytree-prefix: fixed shardings for the state, compiler's
        # choice (None) for the metrics dict
        kwargs["out_shardings"] = (state_shardings, None)
    return jax.jit(step_fn, donate_argnums=(0,) if donate else (),
                   **kwargs)


def default_optimizer(learning_rate: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10_000,
                      b1: float = 0.9, b2: float = 0.95,
                      clip_norm: float = 1.0) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(clip_norm),
        optax.adamw(schedule, b1=b1, b2=b2, weight_decay=weight_decay),
    )
