"""Core runtime microbenchmarks
(reference: python/ray/_private/ray_perf.py — the canonical microbenchmark
set whose published numbers are in BASELINE.md / release/perf_metrics/
microbenchmark.json).

Run: python -m ray_tpu.perf [--quick]
Prints one JSON line per metric: {"metric", "value", "unit", "baseline",
"vs_baseline"} where baseline is the reference's published number on its
own hardware (m4.16xlarge-class) — an envelope comparison, not
like-for-like hardware."""

from __future__ import annotations

import argparse
import json
import time
from typing import Any, Callable, Dict, Optional

import numpy as np

# Reference numbers: release/perf_metrics/microbenchmark.json (BASELINE.md).
BASELINES = {
    "tasks_sync_per_s": 901.0,
    "tasks_async_per_s": 7_419.0,
    "tasks_async_multi_client_per_s": 19_295.0,
    "actor_calls_sync_per_s": 1_826.0,
    "actor_calls_async_per_s": 7_926.0,
    "actor_calls_async_nn_per_s": 24_809.0,
    "put_small_per_s": 4_795.0,
    "get_small_per_s": 9_177.0,
    "put_gib_per_s": 20.35,
    "pg_create_remove_per_s": 751.0,
}

_CLIENT_SCRIPT = r"""
import faulthandler, json, os, sys, time
sys.path.insert(0, {repo!r})
# a wedged client must dump its stack and die, not hang the bench
faulthandler.dump_traceback_later(120, exit=True)
import ray_tpu

idx = int(sys.argv[1]); n = int(sys.argv[2]); out = sys.argv[3]
ray_tpu.init(address={addr!r}, log_to_driver=False)

@ray_tpu.remote
def noop():
    return None

ray_tpu.get([noop.remote() for _ in range(100)])  # warm a worker lease
ready = out + ".ready"
open(ready, "w").close()
go = os.path.join(os.path.dirname(out), "go")
while not os.path.exists(go):
    time.sleep(0.02)
# re-arm: the first timer bounded connect+warmup; the flood on a
# contended box legitimately takes minutes
faulthandler.cancel_dump_traceback_later()
faulthandler.dump_traceback_later(600, exit=True)
t0 = time.perf_counter()
ray_tpu.get([noop.remote() for _ in range(n)])
t1 = time.perf_counter()
with open(out, "w") as f:
    json.dump({{"t0": t0, "t1": t1, "n": n}}, f)
# results are on disk; a slow/hung disconnect must not stall the bench
faulthandler.cancel_dump_traceback_later()
os._exit(0)
"""


def _await_full_cpus(timeout_s: float = 60.0, stable_samples: int = 5):
    """Wait out lease reclamation. Dead benchmark drivers (the
    multi-client clients os._exit) hold their leases until the GCS
    driver-liveness sweep reclaims them (~10 s); starting the next
    bench before that measures reclamation latency — or, on a
    cold/starved cluster, hangs the client warmup outright. The calling
    driver's own live actors hold CPUs too, so "free == total" may be
    unreachable — exit when either every CPU is free OR the free count
    has STOPPED RISING for `stable_samples` seconds (reclamation
    finished; what's still held is held by live owners)."""
    from ray_tpu.util.state.api import list_nodes
    deadline = time.monotonic() + timeout_s
    last_free, stable = -1.0, 0
    while time.monotonic() < deadline:
        nodes = list_nodes()
        free = sum(n_["resources_available"].get("CPU", 0)
                   for n_ in nodes)
        total = sum(n_["resources_total"].get("CPU", 0) for n_ in nodes)
        if free >= total:
            return
        if free > last_free:
            last_free, stable = free, 0
        else:
            stable += 1
            if stable >= stable_samples:
                return
        time.sleep(1.0)


def multi_client_bench(n_clients: int = 4, n_per: int = 1000,
                       results: Optional[Dict[str, float]] = None,
                       metric: str = "tasks_async_multi_client_per_s"):
    """Aggregate async task throughput from N separate DRIVER PROCESSES
    against one cluster (reference: ray_perf.py 'tasks async (multi
    client)'; baseline 19,295/s). Assumes a cluster is already up in this
    process (main() calls it after the single-client suite).

    Always takes the round-5 cold-cluster-safe path: (1) wait for all
    leased CPUs to come back before spawning clients — a previous
    bench's dead drivers must not starve this run's warmup (the r4
    cold-cluster hang, re-trippable by any harness that runs this bench
    more than once, e.g. the --shards A/B); (2) each client warms a
    worker lease and checks in via a ready-file barrier before the
    timed flood."""
    import glob
    import os
    import subprocess
    import sys
    import tempfile

    from ray_tpu._internal.config import CONFIG
    from ray_tpu._internal.core_worker import get_core_worker
    _await_full_cpus()
    host, port = get_core_worker().gcs.address
    addr = f"{host}:{port}"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    workdir = tempfile.mkdtemp(prefix="rtpu-mc-")
    script = os.path.join(workdir, "client.py")
    with open(script, "w") as f:
        f.write(_CLIENT_SCRIPT.format(repo=repo, addr=addr))
    # Clients are their own drivers: the A/B arm under test must reach
    # them (apply_system_config doesn't cross process boundaries).
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               RTPU_OWNER_SHARDS=str(CONFIG.owner_shards))
    procs = []
    outs = []
    for i in range(n_clients):
        out = os.path.join(workdir, f"client-{i}.json")
        outs.append(out)
        with open(os.path.join(workdir, f"client-{i}.err"), "w") as err:
            procs.append(subprocess.Popen(
                [sys.executable, script, str(i), str(n_per), out],
                env=env, stdout=subprocess.DEVNULL, stderr=err))
        # the child holds its own inherited fd; ours closes immediately
    deadline = time.monotonic() + 150
    while len(glob.glob(os.path.join(workdir, "*.ready"))) < n_clients:
        if time.monotonic() > deadline:
            chunks = []
            for p in glob.glob(os.path.join(workdir, "*.err")):
                with open(p) as f:
                    chunks.append(f.read()[-2000:])
            raise TimeoutError(
                "multi-client workers failed to connect; client stderr:"
                "\n" + "\n".join(chunks))
        time.sleep(0.05)
    open(os.path.join(workdir, "go"), "w").close()
    for p in procs:
        p.wait(timeout=300)
    spans = []
    for out in outs:
        with open(out) as f:
            spans.append(json.load(f))
    total = sum(s["n"] for s in spans)
    # Clients share a monotonic-ish clock (same machine): aggregate rate
    # over the union window.
    wall = max(s["t1"] for s in spans) - min(s["t0"] for s in spans)
    rate = total / wall
    if results is not None:
        results[metric] = rate
    _report(metric, rate, "tasks/s")
    return rate


def codec_bench(n: int = 20000, results: Optional[Dict[str, float]] = None
                ) -> Dict[str, float]:
    """Flat-wire codec vs pickle on a representative no-arg actor-call
    spec: encode/decode ns per spec and wire bytes per task. Runs
    in-process (no cluster) — this is the per-call CPU the submit and
    execute hot paths actually pay."""
    import pickle

    from ray_tpu._internal import task_spec as ts
    from ray_tpu._internal.ids import ActorID, JobID, TaskID
    from ray_tpu.remote_function import pack_args

    job = JobID.from_int(1)
    spec = ts.TaskSpec(
        task_id=TaskID.of(job), job_id=job, task_type=ts.ACTOR_TASK,
        function=ts.FunctionDescriptor("bench", "Sink", ""),
        args=pack_args((), {}), num_returns=1, resources={},
        owner_address=("127.0.0.1", 50000), owner_worker_id=b"w" * 28,
        name="Sink.ping", actor_id=ActorID.of(job), method_name="ping",
        sequence_number=7)
    tmpl = ts.make_template(spec)
    delta = ts.encode_delta(spec, tmpl.method_name)
    ts.register_template(tmpl.tid, tmpl.data)
    reg = ts.lookup_template(tmpl.tid)
    pickled = pickle.dumps(spec, protocol=5)

    def _ns(fn) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            fn()
        return (time.perf_counter() - t0) / n * 1e9

    out = {
        "codec_flat_encode_ns": _ns(
            lambda: ts.encode_delta(spec, tmpl.method_name)),
        "codec_flat_decode_ns": _ns(
            lambda: ts.release_spec(ts.decode_delta(delta, reg))),
        "codec_pickle_encode_ns": _ns(
            lambda: pickle.dumps(spec, protocol=5)),
        "codec_pickle_decode_ns": _ns(lambda: pickle.loads(pickled)),
        "codec_flat_bytes_per_task": float(len(delta)),
        "codec_pickle_bytes_per_task": float(len(pickled)),
    }
    out.update(_recv_side_bench(spec, tmpl, delta, reg, n))
    for metric, value in out.items():
        _report(metric, value,
                "bytes" if metric.endswith("per_task") else
                ("ids/us" if metric.endswith("ids_per_us") else
                 ("decrs/us" if metric.endswith("decrs_per_us") else
                  "ns")))
    if results is not None:
        results.update(out)
    return out


def _recv_side_bench(spec, tmpl, delta, reg, n: int):
    """Receive-path microbench (PERF.md round 14): the in-ring C decode
    vs the Python decode it replaces, the done-stream id walk (pooled
    borrowed keys vs per-id TaskID construction), and the batched
    decref fold vs the legacy per-object handler path."""
    from ray_tpu._internal import native_decode as nd
    from ray_tpu._internal import rpc
    from ray_tpu._internal.core_worker import (ReferenceCounter,
                                               _pack_actor_batch)
    from ray_tpu._internal.ids import ObjectID, TaskID
    from ray_tpu._native import fastrpc as fp

    out = {}
    # -- C delta decode (64-delta actor batch amortizes the ctypes
    # call; the decode itself runs in the C classifier exactly as the
    # epoll thread runs it) vs the Python decode of the same frame.
    batch = 64
    payload = _pack_actor_batch(("127.0.0.1", 50123),
                                [(tmpl.tid, tmpl.data)],
                                [(tmpl.tid, delta)] * batch)
    body = rpc.pack_frame(0, rpc.FLAG_RAW, b"push_actor_tasks",
                          payload)[4:]
    decoded = fp.test_decode(body)
    if decoded is not None and decoded[0] == 4:
        import ctypes
        reuse = ctypes.create_string_buffer(len(body) + (1 << 16))
        reps = max(1, n // batch)
        t0 = time.perf_counter()
        for _ in range(reps):
            fp.test_decode(body, buf=reuse)
        out["recv_c_delta_decode_ns"] = \
            (time.perf_counter() - t0) / (reps * batch) * 1e9
        # Python consumption of the decoded records (record parse +
        # freelist fill) — the per-spec Python residue left after C.
        rec_payload = decoded[1]
        from ray_tpu._internal import task_spec as ts_fill

        def _consume():
            _done_to, _tmpls, recs = nd.parse_actor_batch_record(
                rec_payload)
            for _tid, _known, fields in recs:
                ts_fill.release_spec(
                    ts_fill.spec_from_fields(reg, *fields))
        t0 = time.perf_counter()
        for _ in range(reps):
            _consume()
        out["recv_decoded_fill_ns"] = \
            (time.perf_counter() - t0) / (reps * batch) * 1e9
    # Python-side decode of the same batch (what the A/B kill switch
    # runs): per-frame walk + decode_delta per spec.
    from ray_tpu._internal import task_spec as ts_mod
    from ray_tpu._internal.core_worker import _unpack_actor_batch

    def _py_decode():
        _done_to, _tmpls, frames = _unpack_actor_batch(payload)
        for _tid, d in frames:
            ts_mod.release_spec(ts_mod.decode_delta(d, reg))
    reps = max(1, n // batch)
    t0 = time.perf_counter()
    for _ in range(reps):
        _py_decode()
    out["recv_py_delta_decode_ns"] = \
        (time.perf_counter() - t0) / (reps * batch) * 1e9

    # -- done-stream id walk: fresh bytes + TaskID per id (pre-PR-11)
    # vs borrowed keys over the one contiguous buffer.
    n_ids = 4096
    ids = b"".join(TaskID.of(spec.job_id).binary() for _ in range(n_ids))
    table = {}
    for key in TaskID.iter_borrowed(ids):
        table[TaskID(bytes(key.binary()))] = None
    sz = TaskID.SIZE

    def _legacy_walk():
        for i in range(n_ids):
            table.get(TaskID(ids[i * sz:(i + 1) * sz]))

    def _pooled_walk():
        get = table.get
        for key in TaskID.iter_borrowed(ids):
            get(key)
    t0 = time.perf_counter()
    _legacy_walk()
    out["recv_done_legacy_ids_per_us"] = \
        n_ids / ((time.perf_counter() - t0) * 1e6)
    t0 = time.perf_counter()
    _pooled_walk()
    out["recv_done_pooled_ids_per_us"] = \
        n_ids / ((time.perf_counter() - t0) * 1e6)

    # -- decref folds: one contiguous fold through the batch handler vs
    # the legacy per-object path (hex round trip + one locked
    # decrement per id, as one borrow_decref RPC per object paid).
    class _Sink:
        rpc_address = ("127.0.0.1", 1)

        def _free_owned_object(self, *a, **k):
            pass

        def queue_borrow_decref(self, *a, **k):
            pass

        def fire_and_forget(self, *a, **k):
            pass

    n_oids = 4096
    oids = [ObjectID.from_random() for _ in range(n_oids)]
    rc = ReferenceCounter(_Sink())
    for oid in oids:
        rc.add_borrower(oid)
        rc.add_borrower(oid)  # stays alive through one decrement round
    fold = b"".join(o.binary() for o in oids)
    t0 = time.perf_counter()
    rc.remove_borrowers_fold(
        [ObjectID(b) for b in nd.iter_fold_ids(fold)])
    out["recv_fold_decrs_per_us"] = \
        n_oids / ((time.perf_counter() - t0) * 1e6)
    hexes = [o.hex() for o in oids]
    t0 = time.perf_counter()
    for h in hexes:
        rc.remove_borrower(ObjectID(bytes.fromhex(h)))
    out["recv_legacy_decrs_per_us"] = \
        n_oids / ((time.perf_counter() - t0) * 1e6)
    return out


def callsite_bench(n: int = 200_000,
                   results: Optional[Dict[str, float]] = None
                   ) -> Dict[str, float]:
    """Memory-observability callsite capture on the submit hot path:
    ns per _capture_callsite() call (warm render cache), with the
    RTPU_NO_CALLSITES=1 kill switch. The timed loop is compiled with a
    NON-package co_filename — perf.py itself lives under ray_tpu/, so a
    direct call here would classify every frame as a package frame and
    benchmark the capture-miss walk instead of the real user-frame hit
    path that put()/submit pays. Runs in-process (no cluster)."""
    from ray_tpu._internal import core_worker as cw

    src = ("def _user_bench(capture, count, perf_counter):\n"
           "    t0 = perf_counter()\n"
           "    for _ in range(count):\n"
           "        capture()\n"
           "    return (perf_counter() - t0) / count * 1e9\n")
    ns: Dict[str, Any] = {}
    exec(compile(src, "/bench/user_code.py", "exec"), ns)
    _user_bench = ns["_user_bench"]

    capture = cw._capture_callsite
    _user_bench(capture, 100, time.perf_counter)  # warm the cache
    warm = _user_bench(capture, n, time.perf_counter)
    saved = cw._NO_CALLSITES
    cw._NO_CALLSITES = True
    try:
        disabled = _user_bench(capture, n, time.perf_counter)
    finally:
        cw._NO_CALLSITES = saved
    out = {
        "callsite_capture_ns": warm,
        "callsite_disabled_ns": disabled,
        # fraction of a ~200us per-call driver submit budget (PERF.md)
        "callsite_pct_of_submit": warm / 200_000.0 * 100.0,
    }
    for metric, value in out.items():
        _report(metric, value,
                "%" if metric.endswith("of_submit") else "ns")
    if results is not None:
        results.update(out)
    return out


def rpc_bench(n: int = 2000,
              results: Optional[Dict[str, float]] = None
              ) -> Dict[str, float]:
    """Transport-observatory overhead: per-call latency of a real-socket
    loopback echo with instrumentation on vs the RTPU_NO_RPC_METRICS
    kill switch, interleaved (on/off/on/off...) so clock drift and
    allocator state cancel instead of biasing one side, plus the
    lock-free frpc_ring_stats read cost. Runs in-process (no cluster)."""
    import asyncio

    from ray_tpu._internal import rpc, rpc_metrics
    from ray_tpu._internal.config import CONFIG

    async def _run(count: int) -> float:
        server = rpc.RpcServer("perf-rpc")

        async def echo(x=0):
            return x
        server.register("echo", echo)
        await server.start("127.0.0.1", 0)
        # Defeat the in-process fast path: the observatory instruments
        # the wire, so the bench must cross it.
        with rpc._local_servers_lock:
            rpc._local_servers.pop(server.address, None)
        client = rpc.RpcClient(server.address)
        for i in range(100):
            await client.call("echo", x=i)  # warm
        t0 = time.perf_counter()
        for i in range(count):
            await client.call("echo", x=i)
        per_call = (time.perf_counter() - t0) / count
        await client.close()
        await server.stop()
        return per_call * 1e6

    def _with_switch(disabled: bool) -> float:
        saved = CONFIG.no_rpc_metrics
        CONFIG.no_rpc_metrics = disabled
        rpc_metrics._reset_for_tests()
        try:
            return asyncio.run(_run(n))
        finally:
            CONFIG.no_rpc_metrics = saved
            rpc_metrics._reset_for_tests()

    on_runs, off_runs = [], []
    for _ in range(3):
        on_runs.append(_with_switch(False))
        off_runs.append(_with_switch(True))
    on_us, off_us = min(on_runs), min(off_runs)
    out: Dict[str, float] = {
        "rpc_call_us": on_us,
        "rpc_call_nometrics_us": off_us,
        "rpc_metrics_overhead_pct": (on_us - off_us) / off_us * 100.0,
    }
    from ray_tpu._native.fastrpc import NativeIO
    io = NativeIO.get()
    if io is not None and io.ring_stats() is not None:
        k = 20_000
        t0 = time.perf_counter()
        for _ in range(k):
            io.ring_stats()
        out["ring_stats_read_ns"] = (time.perf_counter() - t0) / k * 1e9
    for metric, value in out.items():
        unit = ("%" if metric.endswith("pct")
                else "ns" if metric.endswith("ns") else "us")
        _report(metric, value, unit)
    if results is not None:
        results.update(out)
    return out


def sampler_bench(results: Optional[Dict[str, float]] = None
                  ) -> Dict[str, float]:
    """Stack-sampler overhead: wall time of a fixed pure-Python workload
    with the profiler off (the RTPU_NO_PROFILER / default state: zero
    threads, zero cost) vs continuously sampling at 10 and 100 Hz, plus
    the direct per-pass cost of one sweep over all threads. Runs
    in-process (no cluster)."""
    from ray_tpu._internal import profiler

    def _workload() -> float:
        t0 = time.perf_counter()
        x = 0
        for i in range(5_000_000):
            x += i * i
        return time.perf_counter() - t0

    _workload()  # warm
    # min-of-5: on a shared 1-core box scheduler noise dwarfs the
    # sampler's true cost; the minimum is the least-perturbed run.
    base = min(_workload() for _ in range(5))
    out = {"sampler_off_workload_s": base}
    for hz in (10, 100):
        start = profiler.start_profiling(hz=hz)
        assert start["running"], start
        try:
            timed = min(_workload() for _ in range(5))
        finally:
            profiler.stop_profiling()
            profiler.get_profile(clear=True)  # drop the ring
        out[f"sampler_{hz}hz_workload_s"] = timed
        out[f"sampler_{hz}hz_overhead_pct"] = \
            max(0.0, (timed - base) / base * 100.0)
    # direct cost of one sampling pass (what every tick pays, ~N frames
    # deep x M threads wide)
    s = profiler.StackSampler(hz=100, ring_size=4096)
    for _ in range(50):
        s._sample_once()
    t0 = time.perf_counter()
    reps = 500
    for _ in range(reps):
        s._sample_once()
    out["sampler_pass_us"] = (time.perf_counter() - t0) / reps * 1e6
    for metric, value in out.items():
        unit = "%" if metric.endswith("pct") else \
            ("us" if metric.endswith("us") else "s")
        _report(metric, value, unit)
    if results is not None:
        results.update(out)
    return out


def accel_bench(results: Optional[Dict[str, float]] = None
                ) -> Dict[str, float]:
    """Accelerator-plane overhead: device snapshot cost (the
    get_accel_report hot part), report_step direct cost, and the
    per-step telemetry tax on the REAL paged decode loop — one tiny
    engine built with the plane on and one with the kill switch set,
    decoding the same workload (the off-vs-on A/B that proves the
    default-on plane is sub-noise). Runs in-process (no cluster)."""
    import numpy as np

    from ray_tpu._internal import accel
    from ray_tpu._internal.config import CONFIG
    from ray_tpu.llm import GenerationRequest, PagedEngineConfig, \
        PagedLLMEngine
    from ray_tpu.models.llama import LlamaConfig

    out: Dict[str, float] = {}
    accel.ensure_installed()
    # warm device state so the snapshot walks real buffers
    import jax.numpy as jnp
    keep = [jnp.ones((64, 64)) for _ in range(8)]
    accel.snapshot_devices(force_jax=True)
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        accel.snapshot_devices()
    out["accel_snapshot_us"] = (time.perf_counter() - t0) / reps * 1e6
    del keep

    t0 = time.perf_counter()
    reps = 20_000
    for _ in range(reps):
        accel.report_step("perf", 0.001, tokens=4, device_s=0.0005,
                          flops=1e6, device_kind="cpu")
    out["accel_report_step_us"] = \
        (time.perf_counter() - t0) / reps * 1e6

    # Decode-loop A/B: the engine caches the kill-switch state at
    # construction, so each arm builds its own engine on shared params
    # (one compile). Arms INTERLEAVE round-robin and each takes its
    # min-of-rounds — on a contended box back-to-back arms measure
    # machine drift, not the plane.
    model = LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=4, max_seq_len=256,
        remat=False, use_flash=False, attention_impl="reference")
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 128, size=12)) for _ in range(8)]
    engine_cfg = dict(max_batch=4, max_len=64, page_size=8,
                      num_pages=64, prefill_buckets=(16,))
    params = None  # first build inits; second reuses (one compile)

    def _build_engine(disabled: bool) -> PagedLLMEngine:
        CONFIG.apply_system_config({"no_accel_metrics": disabled})
        try:
            engine = PagedLLMEngine(
                PagedEngineConfig(model=model, **engine_cfg),
                params=params)
            engine.generate(prompts[:2], max_new_tokens=4)  # warm
            return engine
        finally:
            CONFIG.apply_system_config({"no_accel_metrics": False})

    def _round(engine) -> float:
        for i, p in enumerate(prompts):
            engine.submit(GenerationRequest(
                prompt_tokens=p, max_new_tokens=16, request_id=str(i)))
        done, ticks = 0, 0
        t0 = time.perf_counter()
        while done < len(prompts):
            done += len(engine.step())
            ticks += 1
        return (time.perf_counter() - t0) / max(1, ticks)

    off_engine = _build_engine(disabled=True)
    params = off_engine.params  # share: one init, one compile cache
    on_engine = _build_engine(disabled=False)
    best = {"off": None, "on": None}
    for _ in range(5):
        for key, engine in (("off", off_engine), ("on", on_engine)):
            tick = _round(engine)
            if best[key] is None or tick < best[key]:
                best[key] = tick
    out["accel_off_decode_tick_us"] = best["off"] * 1e6
    out["accel_on_decode_tick_us"] = best["on"] * 1e6
    out["accel_decode_overhead_pct"] = max(0.0, (
        out["accel_on_decode_tick_us"] - out["accel_off_decode_tick_us"])
        / out["accel_off_decode_tick_us"] * 100.0)
    for metric, value in out.items():
        unit = "%" if metric.endswith("pct") else "us"
        _report(metric, value, unit)
    if results is not None:
        results.update(out)
    return out


def logplane_bench(results: Optional[Dict[str, float]] = None
                   ) -> Dict[str, float]:
    """Log-plane overhead: the worker-side per-line stamp tax (what
    every print()/log record pays), raylet-side parse + ring-append
    cost, and a cluster A/B — the same print-heavy workload timed with
    the plane ON (ring-only capture) vs the RTPU_NO_LOG_PLANE kill
    switch (legacy DEVNULL), both with log_to_driver off. The A/B
    proves default-on capture rides within machine noise."""
    from ray_tpu._internal import logplane

    out: Dict[str, float] = {}
    line = "a typical task log line with some payload attached: 12345"
    reps = 200_000
    t0 = time.perf_counter()
    for _ in range(reps):
        logplane.stamp_line(line, "INFO")
    out["logplane_stamp_ns"] = (time.perf_counter() - t0) / reps * 1e9
    stamped = logplane.stamp_line(line, "INFO")
    t0 = time.perf_counter()
    for _ in range(reps):
        logplane.parse_line(stamped)
    out["logplane_parse_ns"] = (time.perf_counter() - t0) / reps * 1e9
    ring = logplane.LogRing("w" * 8, pid=1, maxlen=2000)
    t0 = time.perf_counter()
    for _ in range(reps):
        ring.append("stdout", "INFO", line, task="ab" * 8)
    out["logplane_ring_append_ns"] = \
        (time.perf_counter() - t0) / reps * 1e9

    # Cluster A/B: each arm spawns its own workers (the pipe wiring is
    # fixed at spawn), min-of-rounds inside each arm. The kill switch
    # rides the environment so worker subprocesses inherit it.
    def _arm(disabled: bool) -> float:
        import os

        import ray_tpu
        if disabled:
            os.environ["RTPU_NO_LOG_PLANE"] = "1"
        from ray_tpu._internal.config import CONFIG
        CONFIG.reset()
        try:
            ray_tpu.init(num_cpus=2, log_to_driver=False,
                         object_store_memory=128 * 1024 * 1024)

            @ray_tpu.remote
            def chatty(n):
                # a realistic logging task: some work per line, not a
                # pure print loop (which would benchmark /dev/null)
                x = 0
                for i in range(n):
                    for j in range(2000):
                        x += j * j
                    print("bench line", i, x % 97)
                return n

            ray_tpu.get(chatty.remote(20), timeout=120)  # warm worker
            best = None
            for _ in range(3):
                t0 = time.perf_counter()
                ray_tpu.get([chatty.remote(250) for _ in range(4)],
                            timeout=120)
                dt = time.perf_counter() - t0
                best = dt if best is None else min(best, dt)
            return best
        finally:
            ray_tpu.shutdown()
            os.environ.pop("RTPU_NO_LOG_PLANE", None)
            CONFIG.reset()

    off_s = _arm(disabled=True)
    on_s = _arm(disabled=False)
    total_lines = 250 * 4
    out["logplane_off_chatty_s"] = off_s
    out["logplane_on_chatty_s"] = on_s
    out["logplane_chatty_overhead_pct"] = \
        max(0.0, (on_s - off_s) / off_s * 100.0)
    # the honest per-line figure: what one captured line costs end to
    # end (stamp + pipe + parse + ring) vs the DEVNULL legacy path
    out["logplane_per_line_us"] = \
        max(0.0, (on_s - off_s)) / total_lines * 1e6
    for metric, value in out.items():
        unit = "%" if metric.endswith("pct") else \
            ("s" if metric.endswith("_s") else
             ("us" if metric.endswith("_us") else "ns"))
        _report(metric, value, unit)
    if results is not None:
        results.update(out)
    return out


def _rate(n: int, fn: Callable[[], None]) -> float:
    start = time.perf_counter()
    fn()
    return n / (time.perf_counter() - start)


def _report(metric: str, value: float, unit: str):
    baseline = BASELINES.get(metric)
    row = {"metric": metric, "value": round(value, 2), "unit": unit,
           "baseline": baseline,
           "vs_baseline": round(value / baseline, 3) if baseline else None}
    print(json.dumps(row), flush=True)
    return row


def main(quick: bool = False) -> Dict[str, float]:
    import ray_tpu

    scale = 1 if quick else 4
    results = {}
    codec_bench(n=5000 if quick else 20000, results=results)
    ray_tpu.init(num_cpus=8, object_store_memory=2 * 1024**3)

    @ray_tpu.remote
    def noop():
        return None

    @ray_tpu.remote
    class Sink:
        def ping(self):
            return None

        async def aping(self):
            return None

    # Warm up the worker pool + dispatch path (the reference benchmark
    # also measures steady state, not worker cold-start).
    ray_tpu.get([noop.remote() for _ in range(200)])

    n = 200 * scale
    results["tasks_sync_per_s"] = _rate(
        n, lambda: [ray_tpu.get(noop.remote()) for _ in range(n)])
    _report("tasks_sync_per_s", results["tasks_sync_per_s"], "tasks/s")

    n = 1000 * scale
    ray_tpu.get([noop.remote() for _ in range(n)])  # warm burst
    results["tasks_async_per_s"] = _rate(
        n, lambda: ray_tpu.get([noop.remote() for _ in range(n)]))
    _report("tasks_async_per_s", results["tasks_async_per_s"], "tasks/s")

    actor = Sink.remote()
    ray_tpu.get(actor.ping.remote())
    n = 500 * scale
    results["actor_calls_sync_per_s"] = _rate(
        n, lambda: [ray_tpu.get(actor.ping.remote()) for _ in range(n)])
    _report("actor_calls_sync_per_s", results["actor_calls_sync_per_s"],
            "calls/s")

    n = 2000 * scale
    results["actor_calls_async_per_s"] = _rate(
        n, lambda: ray_tpu.get([actor.ping.remote() for _ in range(n)]))
    _report("actor_calls_async_per_s", results["actor_calls_async_per_s"],
            "calls/s")

    # n:n — 4 async actors, 4 submitting threads.
    import threading
    actors = [Sink.options(max_concurrency=16).remote() for _ in range(4)]
    ray_tpu.get([a.aping.remote() for a in actors for _ in range(50)])
    n_per = 500 * scale

    def _pound(a):
        ray_tpu.get([a.aping.remote() for _ in range(n_per)])

    def _nn():
        threads = [threading.Thread(target=_pound, args=(a,))
                   for a in actors]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    results["actor_calls_async_nn_per_s"] = _rate(4 * n_per, _nn)
    _report("actor_calls_async_nn_per_s",
            results["actor_calls_async_nn_per_s"], "calls/s")

    try:
        multi_client_bench(n_clients=2 if quick else 4,
                           n_per=500 * scale, results=results)
    except Exception as e:  # noqa: BLE001 — keep the rest of the suite
        print(json.dumps({"metric": "tasks_async_multi_client_per_s",
                          "error": str(e)}), flush=True)

    small = np.zeros(8, np.int64)
    n = 1000 * scale
    results["put_small_per_s"] = _rate(
        n, lambda: [ray_tpu.put(small) for _ in range(n)])
    _report("put_small_per_s", results["put_small_per_s"], "puts/s")

    ref = ray_tpu.put(small)
    results["get_small_per_s"] = _rate(
        n, lambda: [ray_tpu.get(ref) for _ in range(n)])
    _report("get_small_per_s", results["get_small_per_s"], "gets/s")

    # Put throughput: 40 x 25 MiB numpy arrays through plasma (the
    # reference benchmark also puts numpy — pickle-5 out-of-band, the
    # array body memcpys straight into the store mmap).
    chunk = np.random.randint(0, 255, 25 * 1024**2, np.uint8)
    reps = 10 if quick else 40
    start = time.perf_counter()
    refs = [ray_tpu.put(chunk) for _ in range(reps)]
    dt = time.perf_counter() - start
    del refs
    results["put_gib_per_s"] = reps * 25 / 1024 / dt
    _report("put_gib_per_s", results["put_gib_per_s"], "GiB/s")

    # The multi-client bench leaves 4 dead drivers whose leases the
    # GCS driver-liveness sweep reclaims (~10 s). Wait for the CPUs to
    # come back so the PG bench measures PG throughput, not
    # dead-driver reclamation latency.
    _await_full_cpus()

    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    n = 50 * scale

    def _pg_cycle():
        for _ in range(n):
            pg = placement_group([{"CPU": 1}])
            pg.wait(timeout_seconds=30)
            remove_placement_group(pg)
    results["pg_create_remove_per_s"] = _rate(n, _pg_cycle)
    _report("pg_create_remove_per_s", results["pg_create_remove_per_s"],
            "pgs/s")

    ray_tpu.shutdown()
    return results


def shards_bench(shard_counts=(1, 2, 4), quick: bool = False,
                 decode_arms=(True, False)) -> Dict[str, float]:
    """Owner-shard x native-decode A/B: the workloads the sharded core
    and the in-ring receive decode target — sync tasks, n:n async actor
    calls (4 async actors x 4 submitting threads) and the multi-client
    flood (4 separate driver processes) — at each shard count, paired
    with native decode on and off (`RTPU_NO_NATIVE_DECODE`), one fresh
    cluster per arm. ``shards=1`` + decode-off is the exact-legacy
    path; only paired same-window ratios are signal. Feeds the PERF.md
    round-10/round-14 tables. Decode arms set the ENV flag so spawned
    raylets/workers inherit it (CONFIG alone would only flip the
    driver)."""
    import os

    from ray_tpu._internal.config import CONFIG

    scale = 1 if quick else 4
    results: Dict[str, float] = {}
    saved_nd = os.environ.get("RTPU_NO_NATIVE_DECODE")
    try:
        _shards_bench_arms(shard_counts, decode_arms, scale, quick,
                           results)
    finally:
        if saved_nd is None:
            os.environ.pop("RTPU_NO_NATIVE_DECODE", None)
        else:
            os.environ["RTPU_NO_NATIVE_DECODE"] = saved_nd
        CONFIG.reset()
    return results


def _shards_bench_arms(shard_counts, decode_arms, scale, quick, results):
    import os
    import threading

    import ray_tpu
    from ray_tpu._internal.config import CONFIG

    for decode_on in decode_arms:
        os.environ["RTPU_NO_NATIVE_DECODE"] = "" if decode_on else "1"
        CONFIG.reset()
        tag = "" if decode_on else "_nodecode"
        for count in shard_counts:
            CONFIG.apply_system_config({"owner_shards": int(count)})
            ray_tpu.init(num_cpus=8, object_store_memory=2 * 1024**3)
            try:
                from ray_tpu._internal.core_worker import get_core_worker
                got = len(get_core_worker().shards)
                if got != count:
                    raise RuntimeError(
                        f"arm shards={count}: driver came up with {got}")

                @ray_tpu.remote
                def noop():
                    return None

                @ray_tpu.remote
                class Sink:
                    async def aping(self):
                        return None

                # sync tasks (one at a time, full lease + push + reply
                # round trip per call)
                ray_tpu.get([noop.remote() for _ in range(20)])
                n_sync = 100 * scale
                metric = f"tasks_sync_per_s_shards{count}{tag}"
                results[metric] = _rate(
                    n_sync,
                    lambda: [ray_tpu.get(noop.remote())
                             for _ in range(n_sync)])
                _report(metric, results[metric], "tasks/s")

                actors = [Sink.options(max_concurrency=16).remote()
                          for _ in range(4)]
                ray_tpu.get([a.aping.remote() for a in actors
                             for _ in range(50)])
                n_per = 500 * scale

                def _pound(a):
                    ray_tpu.get([a.aping.remote() for _ in range(n_per)])

                def _nn():
                    threads = [threading.Thread(target=_pound, args=(a,))
                               for a in actors]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                metric = f"actor_calls_async_nn_per_s_shards{count}{tag}"
                results[metric] = _rate(4 * n_per, _nn)
                _report(metric, results[metric], "calls/s")
                per_shard = [(row["shard"], row["submits"])
                             for row in get_core_worker().shards.stats()]
                print(json.dumps(
                    {"metric": f"shard_submits_shards{count}{tag}",
                     "per_shard": per_shard}), flush=True)
                mc_metric = \
                    f"tasks_async_multi_client_per_s_shards{count}{tag}"
                try:
                    multi_client_bench(
                        n_clients=2 if quick else 4, n_per=500 * scale,
                        results=results, metric=mc_metric)
                except Exception as e:  # noqa: BLE001 — keep other arms
                    print(json.dumps({"metric": mc_metric,
                                      "error": str(e)}), flush=True)
            finally:
                ray_tpu.shutdown()


def failover_bench(quick: bool = False) -> Dict[str, float]:
    """GCS durability + failover numbers (PERF.md round-13):

    - persist-path overhead per mutation, A/B across
      RTPU_GCS_PERSIST=off|legacy|wal (the WAL's O(record) append vs the
      legacy whole-snapshot rewrite),
    - recovery time (snapshot + WAL-tail replay) for a populated store,
    - time-to-first-task-after-restart on a live cluster (in-process GCS
      restart at the same address; raylet re-registers, a fresh actor
      schedules on the new incarnation).
    """
    import os
    import tempfile

    from ray_tpu._internal.config import CONFIG
    from ray_tpu._internal.gcs import GcsServer
    from ray_tpu._internal.rpc import EventLoopThread

    results: Dict[str, float] = {}
    loop = EventLoopThread.get()
    n_fast = 2000 if quick else 10000
    n_legacy = 100 if quick else 300  # whole-snapshot per op: keep small

    for mode in ("off", "legacy", "wal"):
        CONFIG.apply_system_config({"gcs_persist": mode})
        tmp = tempfile.mkdtemp(prefix=f"rtpu-failover-{mode}-")
        path = os.path.join(tmp, "gcs.db")
        gcs = GcsServer("perf", persist_path=path)
        loop.run_sync(gcs.start())
        # add_job persists in EVERY mode (legacy rewrote the whole
        # snapshot per call — the n must stay small there; the WAL
        # appends three O(record) rows).
        n = n_legacy if mode == "legacy" else n_fast

        async def _pound(gcs=gcs, n=n):
            import asyncio
            for i in range(n):
                await gcs.handle_add_job(driver_address=None,
                                         namespace="bench")
                # One loop tick per mutation, as real RPC arrivals pay:
                # the group-commit fsync callback fires per tick — a
                # no-yield loop would amortize ALL fsyncs into one.
                await asyncio.sleep(0)
        start = time.perf_counter()
        loop.run_sync(_pound())
        per_op_us = (time.perf_counter() - start) / n * 1e6
        results[f"gcs_mutation_{mode}_us"] = per_op_us
        _report(f"gcs_mutation_{mode}_us", per_op_us, "us/op")
        if mode == "wal":
            # ... plus the fine-grained KV append path (new in wal mode)
            payload = b"x" * 256

            async def _kv(gcs=gcs, n=n_fast):
                import asyncio
                for i in range(n):
                    await gcs.handle_kv_put(ns="bench", key=f"k{i}",
                                            value=payload)
                    await asyncio.sleep(0)  # fsync per tick (see above)
            start = time.perf_counter()
            loop.run_sync(_kv())
            kv_us = (time.perf_counter() - start) / n_fast * 1e6
            results["gcs_kv_append_wal_us"] = kv_us
            _report("gcs_kv_append_wal_us", kv_us, "us/op")
            loop.run_sync(gcs.stop())
            start = time.perf_counter()
            gcs2 = GcsServer("perf", persist_path=path)
            loop.run_sync(gcs2.start())
            recovery_ms = (time.perf_counter() - start) * 1e3
            assert len(gcs2.kv.get("bench", {})) == n_fast
            assert len(gcs2.jobs) == n
            results["gcs_recovery_ms"] = recovery_ms
            _report("gcs_recovery_ms", recovery_ms, "ms")
            loop.run_sync(gcs2.stop())
        else:
            loop.run_sync(gcs.stop())
        CONFIG.reset()

    # -- time-to-first-task-after-restart on a live cluster ------------
    import ray_tpu
    from ray_tpu._internal.node import Node
    CONFIG.apply_system_config({"gcs_persist": "wal"})
    tmp = tempfile.mkdtemp(prefix="rtpu-failover-e2e-")
    path = os.path.join(tmp, "gcs.db")
    node = Node(head=True, resources={"CPU": 4}, gcs_persist_path=path)
    node.start()
    ray_tpu.init(_node=node, log_to_driver=False)
    try:
        @ray_tpu.remote
        class Probe:
            def ping(self):
                return 1

        warm = Probe.remote()
        ray_tpu.get(warm.ping.remote(), timeout=60)
        port = node.gcs_address[1]
        start = time.perf_counter()
        loop.run_sync(node.gcs.stop())
        new_gcs = GcsServer(node.session_name, persist_path=path)
        loop.run_sync(new_gcs.start(port=port))
        node.gcs = new_gcs
        # First NEW control-plane work on the new incarnation: schedule
        # a fresh actor and run one call on it.
        fresh = Probe.remote()
        ray_tpu.get(fresh.ping.remote(), timeout=120)
        ttft_ms = (time.perf_counter() - start) * 1e3
        results["gcs_restart_first_task_ms"] = ttft_ms
        _report("gcs_restart_first_task_ms", ttft_ms, "ms")
        # The pre-restart actor still answers (zero lost state).
        ray_tpu.get(warm.ping.remote(), timeout=60)
    finally:
        ray_tpu.shutdown()
        CONFIG.reset()
    return results


def collectives_bench(world: int = 8, mb: int = 64,
                      dcn_gbps: float = 0.01) -> Dict[str, float]:
    """Collective-backend A/B (PR-12): allreduce size sweep
    (256KB / 4MB / `mb`MB float32) x algorithm (ring / tree / hier,
    hier+int8) across `world` single-process ranks on a virtual
    two-slice topology, with a quantization-error column and measured
    per-link bytes.

    The slice boundary is EMULATED: this box has no real DCN, so
    cross-slice sends pay nbytes/(dcn_gbps GB/s) of sender-side delay
    (0 disables). The default 0.01 GB/s preserves the REAL per-chip
    ICI:DCN bandwidth ratio (~100:1 on v4/v5p pods — ~900 GB/s ICI vs
    single-digit GB/s DCN per chip) against this box's ~1 GB/s
    effective in-process transport playing the ICI role; without a
    slow cross-slice link the topology doesn't exist and every
    equal-byte schedule ties on a compute-bound core. The dcn/ici BYTE
    columns are measured from the group ledger, not modeled — they
    hold on any hardware. Run the bench on an otherwise idle box
    (see PERF.md machine calibration)."""
    import ray_tpu

    ray_tpu.init(num_cpus=world + 1)

    @ray_tpu.remote(num_cpus=1)
    class R:
        def __init__(self, rank, world, group):
            self.rank, self.world, self.group = rank, world, group

        def join(self, algo, quant, num_slices, gbps):
            from ray_tpu._internal.config import CONFIG
            from ray_tpu.util.collective import collective as col
            CONFIG.apply_system_config({"collective_algo": algo,
                                        "collective_quant": quant})
            col.init_collective_group(self.world, self.rank,
                                      group_name=self.group,
                                      num_slices=num_slices,
                                      dcn_emulate_gbps=gbps)
            return True

        def allreduce(self, n_elems, check):
            from ray_tpu.util.collective import collective as col
            x = np.random.RandomState(1000 + self.rank) \
                .standard_normal(n_elems).astype(np.float32)
            t0 = time.perf_counter()
            out = col.allreduce(x, group_name=self.group)
            dt = time.perf_counter() - t0
            err = None
            if check:  # exact fp64 reference (regenerate every rank)
                exact = np.zeros(n_elems, np.float64)
                for r in range(self.world):
                    exact += np.random.RandomState(1000 + r) \
                        .standard_normal(n_elems)
                err = float(np.abs(out.astype(np.float64) - exact).max()
                            / np.abs(exact).max())
            return dt, err

        def bytes_sent(self):
            from ray_tpu.util.collective import collective as col
            return col._group(self.group).bytes_sent()

    sizes = [(256 * 1024, "256KB"), (4 << 20, "4MB"),
             (mb << 20, f"{mb}MB")]
    arms = [("ring", "off"), ("tree", "off"), ("hier", "off"),
            ("hier", "int8")]
    results: Dict[str, float] = {}
    rows = []
    for algo, quant_arm in arms:
        group = f"cb-{algo}-{quant_arm}"
        ranks = [R.remote(r, world, group) for r in range(world)]
        ray_tpu.get([a.join.remote(algo, quant_arm, 2, dcn_gbps)
                     for a in ranks], timeout=180)
        # warm connections + compile nothing: one small round
        ray_tpu.get([a.allreduce.remote(1 << 12, False) for a in ranks],
                    timeout=180)
        prev = ray_tpu.get([a.bytes_sent.remote() for a in ranks],
                           timeout=60)
        for nbytes, label in sizes:
            n_elems = nbytes // 4
            check = nbytes <= (4 << 20)  # fp64 reference is O(W*N)
            t0 = time.perf_counter()
            outs = ray_tpu.get([a.allreduce.remote(n_elems, check)
                                for a in ranks], timeout=900)
            wall = time.perf_counter() - t0
            cur = ray_tpu.get([a.bytes_sent.remote() for a in ranks],
                              timeout=60)
            dcn = sum(c["dcn"] - p["dcn"] for c, p in zip(cur, prev))
            ici = sum(c["ici"] - p["ici"] for c, p in zip(cur, prev))
            prev = cur
            errs = [e for _dt, e in outs if e is not None]
            err = max(errs) if errs else float("nan")
            arm_key = f"{algo}_{quant_arm}_{label}"
            results[arm_key] = wall
            results[f"{arm_key}_dcn_mb"] = dcn / 2**20
            rows.append((algo, quant_arm, label, wall, dcn / 2**20,
                         ici / 2**20, err))
            _report(f"allreduce_{arm_key}_x{world}", wall, "s")
        for a in ranks:
            ray_tpu.kill(a)
        del ranks
    print(f"\n| algo | quant | size | wall s | dcn MB | ici MB "
          f"| max rel err |")
    print("|---|---|---|---|---|---|---|")
    for algo, q, label, wall, dcn_mb, ici_mb, err in rows:
        err_s = f"{err:.2e}" if err == err else "-"
        print(f"| {algo} | {q} | {label} | {wall:.3f} | {dcn_mb:.2f} "
              f"| {ici_mb:.2f} | {err_s} |")
    big = sizes[-1][1]
    results["hier_vs_ring_speedup"] = \
        results[f"ring_off_{big}"] / results[f"hier_off_{big}"]
    results["dcn_bytes_ratio_int8"] = \
        results[f"hier_off_{big}_dcn_mb"] / \
        max(1e-9, results[f"hier_int8_{big}_dcn_mb"])
    _report("hier_vs_ring_speedup", results["hier_vs_ring_speedup"], "x")
    _report("dcn_bytes_ratio_int8", results["dcn_bytes_ratio_int8"], "x")
    ray_tpu.shutdown()
    return results


if __name__ == "__main__":
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true")
    parser.add_argument("--collectives", action="store_true")
    parser.add_argument("--codec", action="store_true",
                        help="flat-codec microbench only (no cluster)")
    parser.add_argument("--callsites", action="store_true",
                        help="callsite-capture microbench only "
                             "(no cluster)")
    parser.add_argument("--sampler", action="store_true",
                        help="stack-sampler overhead microbench only "
                             "(no cluster)")
    parser.add_argument("--rpc", action="store_true",
                        help="transport-observatory overhead "
                             "microbench: loopback call cost with "
                             "metrics on vs RTPU_NO_RPC_METRICS, plus "
                             "the ring-stats read cost (no cluster)")
    parser.add_argument("--accel", action="store_true",
                        help="accelerator-plane overhead microbench: "
                             "snapshot cost + decode-loop on/off A/B "
                             "(no cluster)")
    parser.add_argument("--logplane", action="store_true",
                        help="log-plane overhead microbench: per-line "
                             "stamp/parse/ring cost + print-heavy "
                             "cluster A/B (plane on vs kill switch)")
    parser.add_argument("--failover", action="store_true",
                        help="GCS durability/failover bench: per-"
                             "mutation persist A/B (off/legacy/wal), "
                             "recovery time, time-to-first-task after "
                             "an in-process GCS restart")
    parser.add_argument("--shards", nargs="?", const="1,2,4",
                        default=None, metavar="N,N,...",
                        help="owner-shard A/B: n:n + multi-client at "
                             "each shard count (default 1,2,4)")
    parser.add_argument("--world", type=int, default=8)
    parser.add_argument("--mb", type=int, default=64)
    parser.add_argument("--dcn-gbps", type=float, default=0.01,
                        help="emulated cross-slice (DCN) bandwidth for "
                             "--collectives (GB/s; 0 disables the "
                             "sender-side delay; default keeps the "
                             "real ~100:1 ICI:DCN per-chip ratio)")
    args = parser.parse_args()
    if args.collectives:
        collectives_bench(world=args.world, mb=args.mb,
                          dcn_gbps=args.dcn_gbps)
    elif args.codec:
        codec_bench()
    elif args.callsites:
        callsite_bench()
    elif args.sampler:
        sampler_bench()
    elif args.rpc:
        rpc_bench()
    elif args.accel:
        accel_bench()
    elif args.logplane:
        logplane_bench()
    elif args.failover:
        failover_bench(quick=args.quick)
    elif args.shards:
        shards_bench(tuple(int(x) for x in args.shards.split(",")),
                     quick=args.quick)
    else:
        main(quick=args.quick)
