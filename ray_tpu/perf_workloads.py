"""ML-workload benchmarks covering the five BASELINE.json configs
(VERDICT r2 item 3): RLlib PPO / IMPALA sampling+learning rates, Serve
HTTP throughput + latency, Data pipeline throughput, and LLM engine
decode throughput. The Train number comes from bench.py on the TPU.

Plus the **standing chaos soak** (``--which soak`` / ``bench_soak``):
sustained serve+train-style load on a real multi-process cluster
(external killable GCS, subprocess raylets) under a seeded fault
script — scheduled transport chaos, a full rolling restart of every
worker raylet through the graceful-drain path, and a ``kill -9`` of
the GCS mid-rollout — gated on SLOs (zero lost/doubled tasks, zero
dropped serve streams, bounded p99 during failover) and recorded as a
JSON artifact like the mesh-sustained bench.

Plus the **LLM serving saturation bench** (``--which serve_saturation``
/ ``bench_serve_saturation``): the paired continuous-batching vs
RTPU_NO_CONT_BATCH legacy engine A/B (same seed, same weights, same
mixed-length workload), the radix shared-prefix arm, and a sustained
streaming load through the real serve proxy — gated on SLOs (p95 TTFT,
zero dropped streams, zero leaked KV pages, cross-arm token parity)
and recorded as ``tests/artifacts_serve_saturation.json``. The same
run regression-gates request-lifecycle tracing overhead (reqtrace
on/off req/s within noise) and exports the per-request serve timeline
to ``tests/artifacts_requests_timeline.json``.

Run: python -m ray_tpu.perf_workloads \
    [--which all|ppo|impala|serve|data|llm|soak|serve_saturation]
Prints one JSON line per metric.
"""

from __future__ import annotations

import argparse
import json
import logging
import time


def _report(metric: str, value: float, unit: str, **extra):
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": unit, **extra}), flush=True)


def bench_ppo(iters: int = 12):
    from ray_tpu.rllib import PPOConfig
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                         rollout_fragment_length=128)
            .training(lr=1e-3, num_epochs=10, minibatch_size=256)
            .build())
    algo.train()  # warm/compile
    t0 = time.perf_counter()
    steps = 0
    learner_rates = []
    for _ in range(iters):
        result = algo.train()
        steps += result["num_env_steps_sampled"]
        learner_rates.append(result["learner_samples_per_s"])
    wall = time.perf_counter() - t0
    algo.stop()
    _report("ppo_env_steps_per_s", steps / wall, "steps/s")
    _report("ppo_learner_samples_per_s",
            sum(learner_rates) / len(learner_rates), "samples/s")


def bench_impala(iters: int = 20):
    from ray_tpu.rllib import ImpalaConfig
    algo = (ImpalaConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=32,
                         rollout_fragment_length=32)
            .training(lr=1e-3, train_batch_slots=64, num_epochs=2)
            .build())
    algo.train()
    t0 = time.perf_counter()
    trained = 0
    for _ in range(iters):
        result = algo.train()
        trained += result["num_env_steps_trained_this_iter"]
    wall = time.perf_counter() - t0
    algo.stop()
    _report("impala_env_steps_trained_per_s", trained / wall, "steps/s")


def bench_serve(seconds: float = 10.0, concurrency: int = 8):
    import threading
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    def echo(request):
        return {"ok": True}

    serve.run(echo.bind(), name="bench", route_prefix="/bench")
    base = f"{serve.api.get_http_address()}/bench"
    # warm
    for _ in range(5):
        urllib.request.urlopen(base, timeout=10).read()
    latencies = []
    lock = threading.Lock()
    stop_at = time.perf_counter() + seconds

    def pound():
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            urllib.request.urlopen(base, timeout=30).read()
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)
    threads = [threading.Thread(target=pound) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()
    n = len(latencies)
    _report("serve_requests_per_s", n / wall, "req/s")
    _report("serve_p50_ms", latencies[n // 2] * 1000, "ms")
    _report("serve_p95_ms", latencies[int(n * 0.95)] * 1000, "ms")
    serve.shutdown()


def bench_data(rows: int = 200_000):
    import numpy as np

    import ray_tpu.data as rd

    t0 = time.perf_counter()
    ds = rd.range(rows).map_batches(
        lambda b: {"x": np.asarray(b["id"]) * 2},
        batch_size=8192)
    total = 0
    for batch in ds.iter_batches(batch_size=8192):
        total += len(batch["x"])
    wall = time.perf_counter() - t0
    assert total == rows
    _report("data_rows_per_s", rows / wall, "rows/s")


def bench_llm(steps: int = 40):
    import numpy as np

    from ray_tpu.llm import PagedEngineConfig, PagedLLMEngine
    from ray_tpu.models.llama import LlamaConfig

    model = LlamaConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_layers=4, num_heads=8,
                        num_kv_heads=8, max_seq_len=512, remat=False,
                        use_flash=False, attention_impl="reference")
    engine = PagedLLMEngine(PagedEngineConfig(
        model=model, max_batch=8, max_len=256, page_size=16,
        num_pages=256, prefill_buckets=(32,)))
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 1024, size=16)) for _ in range(8)]
    engine.generate(prompts, max_new_tokens=4)  # compile
    t0 = time.perf_counter()
    engine.generate(prompts, max_new_tokens=steps)
    wall = time.perf_counter() - t0
    _report("llm_decode_tokens_per_s", 8 * steps / wall, "tok/s",
            note="tiny CPU model; engine-overhead measurement, "
                 "HBM-bound decode is the TPU bench")


# ---------------------------------------------------------------------------
# serve_saturation: continuous-batching vs legacy A/B + streaming SLO soak
# (PR 17 headline — sustained mixed-length saturation load with SLO gates,
# recorded as tests/artifacts_serve_saturation.json)
# ---------------------------------------------------------------------------


def _sat_tiny_model():
    from ray_tpu.models.llama import LlamaConfig
    return LlamaConfig(vocab_size=128, hidden_size=64,
                       intermediate_size=128, num_layers=2, num_heads=4,
                       num_kv_heads=4, max_seq_len=256, remat=False,
                       use_flash=False, attention_impl="reference")


def _sat_engine_config(num_pages: int = 96):
    from ray_tpu.llm import PagedEngineConfig
    return PagedEngineConfig(
        model=_sat_tiny_model(), max_batch=8, max_len=128, page_size=8,
        num_pages=num_pages, prefill_buckets=(16, 32, 64))


def _sat_mixed_workload(seed: int, n: int):
    """Mixed-length saturation mix: 1/3 short chat turns with long
    answers, 1/3 medium, 1/3 long doc-grounded prompts with short
    answers — the decode-heavy chat shape where upfront
    prompt+max_new page reservation hurts most (a short question
    reserves 10 pages for its 64-token answer that lazy allocation
    grows into one page at a time)."""
    import numpy as np
    rng = np.random.RandomState(seed)
    reqs = []
    for i in range(n):
        kind = i % 3
        if kind == 0:
            plen, max_new = rng.randint(4, 12), 64
        elif kind == 1:
            plen, max_new = rng.randint(24, 48), 48
        else:
            plen, max_new = rng.randint(64, 100), 24
        reqs.append(([int(t) for t in rng.randint(1, 128, size=plen)],
                     int(max_new)))
    return reqs


def _prefill_tokens_counter():
    from ray_tpu.llm._metrics import llm_metrics
    snap = llm_metrics().prefill_tokens.snapshot()
    key = ["paged"]
    for tag_values, value in snap["series"]:
        if tag_values == key:
            return value
    return 0.0


def _drive_engine_arm(engine, workload) -> dict:
    """Submit the whole workload up front (saturation) and step the
    engine to drain, recording per-request TTFT, throughput, prefill
    tokens computed, preemptions, and the page-ledger balance."""
    from ray_tpu.llm import GenerationRequest
    outputs: dict = {}
    t_submit: dict = {}
    t_first: dict = {}

    def make_cbs(i):
        def on_tok(request, token):
            if i not in t_first:
                t_first[i] = time.perf_counter()

        def on_done(request, tokens):
            outputs[i] = tokens
        return on_tok, on_done

    prefill0 = _prefill_tokens_counter()
    t0 = time.perf_counter()
    for i, (prompt, max_new) in enumerate(workload):
        on_tok, on_done = make_cbs(i)
        t_submit[i] = time.perf_counter()
        engine.submit(
            GenerationRequest(prompt_tokens=prompt,
                              max_new_tokens=max_new,
                              request_id=f"sat-{i}"),
            done_callback=on_done, token_callback=on_tok)
    steps = 0
    while engine.has_work():
        engine.step()
        steps += 1
        assert steps < 100_000
    wall = time.perf_counter() - t0
    ttfts = sorted(t_first[i] - t_submit[i] for i in t_first)
    gen_tokens = sum(len(t) for t in outputs.values())
    stats = engine.stats()
    return {
        "requests": len(workload),
        "wall_s": round(wall, 3),
        "requests_per_s": round(len(workload) / wall, 2),
        "decode_tokens_per_s": round(gen_tokens / wall, 1),
        "ttft_p50_s": round(ttfts[len(ttfts) // 2], 4),
        "ttft_p95_s": round(ttfts[int(len(ttfts) * 0.95)], 4),
        "prefill_tokens": int(_prefill_tokens_counter() - prefill0),
        "preemptions": stats["preemptions"],
        "leaked_pages": engine.page_leak_check(),
        "outputs": outputs,
    }


def serve_engine_ab(seed: int = 1234, n_requests: int = 24) -> dict:
    """Paired A/B (same seed, same params, same workload): continuous
    batching vs the RTPU_NO_CONT_BATCH legacy per-drain scheduler, plus
    the radix shared-prefix arm. Gates: token parity between arms, zero
    leaked pages, and >= 2x fewer prefill tokens on the shared-
    system-prompt workload."""
    import numpy as np

    from ray_tpu._internal.config import CONFIG
    from ray_tpu.llm import PagedLLMEngine

    workload = _sat_mixed_workload(seed, n_requests)
    # Bound the prefix cache for BOTH arms: the legacy scheduler has no
    # pressure eviction, so an unbounded pinned-prefix store would
    # starve its admission loop outright on a saturated pool (the
    # continuous engine evicts unreferenced radix leaves on demand and
    # preempts — it doesn't need the bound, but a paired A/B does).
    # A/B pool is deliberately tight (40 pages): the legacy scheduler
    # reserves ceil((prompt+max_new)/page_size) pages up front per
    # admission, so page pressure caps its decode concurrency at ~3
    # sequences, while the continuous engine allocates lazily and
    # preempts, keeping ~7 of 8 slots decoding — that concurrency gap
    # is the structural win being measured (a roomy pool makes the
    # arms compute-identical and the margin pure noise). Floor check:
    # 39 usable - 12 pinned >= 16 pages, the largest single request,
    # so legacy admission can never wedge.
    CONFIG.apply_system_config({"prefix_cache_entries": 12})
    try:
        cont = PagedLLMEngine(_sat_engine_config(num_pages=40))
        params = cont.params
        assert cont._continuous, \
            "kill switch armed — A/B needs the default"
        # warm every compiled program on the measured engine itself
        # before timing — jit caches are per-instance closures, so an
        # unwarmed arm would spend its wall clock in the XLA compiler,
        # not the scheduler. Prompt lengths cover each (chunk bucket,
        # dense-cache length) pair the workload and its preemption
        # resumes can hit; the repeated-prefix pair warms gather_pages
        _warmup = [([1] * 8, 2), ([2] * 30, 2), ([3] * 60, 2),
                   ([4] * 70, 2), ([5] * 90, 2), ([6] * 100, 2),
                   ([7] * 24 + [1], 2), ([7] * 24 + [2], 2)]
        _drive_engine_arm(cont, _warmup)
        cont_row = _drive_engine_arm(cont, workload)
        CONFIG.apply_system_config({"no_cont_batch": True})
        try:
            legacy = PagedLLMEngine(_sat_engine_config(num_pages=40),
                                    params=params)
            assert not legacy._continuous
            _drive_engine_arm(legacy, _warmup)
            legacy_row = _drive_engine_arm(legacy, workload)
        finally:
            CONFIG.apply_system_config({"no_cont_batch": False})
    finally:
        CONFIG.apply_system_config({"prefix_cache_entries": 128})
    parity_ok = cont_row.pop("outputs") == legacy_row.pop("outputs")

    # radix arm: shared system prompt, unique tails — the shared span
    # must cost zero prefill FLOPs after the first request
    rng = np.random.RandomState(seed + 1)
    system = [int(t) for t in rng.randint(1, 128, size=56)]
    shared_workload = [
        (system + [int(t) for t in rng.randint(1, 128,
                                               size=rng.randint(2, 9))],
         8)
        for _ in range(12)]
    submitted_tokens = sum(len(p) for p, _ in shared_workload)
    radix_engine = PagedLLMEngine(_sat_engine_config(num_pages=128),
                                  params=params)
    # warm the radix cache with one request so the shared system prompt
    # is resident before the measured batch (concurrently-admitted cold
    # requests can't hit a prefix that no finisher has registered yet)
    _drive_engine_arm(radix_engine, [(system + [1], 2)])
    radix_row = _drive_engine_arm(radix_engine, shared_workload)
    radix_row.pop("outputs")
    radix_row["prompt_tokens_submitted"] = submitted_tokens
    radix_row["prefill_tokens_saved_frac"] = round(
        1.0 - radix_row["prefill_tokens"] / submitted_tokens, 3)
    radix_row["shared_prefix_hits"] = radix_engine.stats()["prefix_hits"]

    result = {
        "seed": seed,
        "continuous": cont_row,
        "legacy": legacy_row,
        "radix_shared_prefix": radix_row,
        "gates": {
            "token_parity": parity_ok,
            "throughput_wins": cont_row["requests_per_s"]
            > legacy_row["requests_per_s"],
            "ttft_p95_wins": cont_row["ttft_p95_s"]
            < legacy_row["ttft_p95_s"],
            "zero_leaked_pages": cont_row["leaked_pages"] == 0
            and legacy_row["leaked_pages"] == 0
            and radix_row["leaked_pages"] == 0,
            "radix_2x_fewer_prefill_tokens":
            radix_row["prefill_tokens"] * 2 <= submitted_tokens,
        },
    }
    result["passed"] = all(result["gates"].values())
    return result


def reqtrace_overhead_ab(seed: int = 1234, n_requests: int = 24,
                         rounds: int = 3) -> dict:
    """Paired A/B (same seed, same params, same workload): request
    lifecycle tracing ON (default) vs the RTPU_NO_REQTRACE kill switch.
    Both arms interleave round-robin and the BEST round per arm is
    compared (the round-11 idiom: on a contended container, min-wall
    is the only stable estimator — single-shot walls swing 50%+).
    Regression gate: tracing stays within machine noise — the traced
    arm's best req/s must hold >= 0.8x the untraced arm's (a real
    per-event regression shows up far below that). Token parity is
    gated too: tracing must never perturb scheduling."""
    from ray_tpu._internal.config import CONFIG
    from ray_tpu.llm import PagedLLMEngine

    workload = _sat_mixed_workload(seed, n_requests)
    _warmup = [([1] * 8, 2), ([2] * 30, 2), ([3] * 60, 2),
               ([4] * 70, 2), ([5] * 90, 2), ([6] * 100, 2),
               ([7] * 24 + [1], 2), ([7] * 24 + [2], 2)]
    # same tight pool as serve_engine_ab so the arms see real page
    # pressure — parks/preemptions are where tracing records most
    CONFIG.apply_system_config({"prefix_cache_entries": 12})
    try:
        on_engine = PagedLLMEngine(_sat_engine_config(num_pages=40))
        off_engine = PagedLLMEngine(_sat_engine_config(num_pages=40),
                                    params=on_engine.params)
        _drive_engine_arm(on_engine, _warmup)
        _drive_engine_arm(off_engine, _warmup)
        on_rows, off_rows = [], []
        for _ in range(max(1, int(rounds))):
            CONFIG.apply_system_config({"no_reqtrace": True})
            try:
                off_rows.append(_drive_engine_arm(off_engine, workload))
            finally:
                CONFIG.apply_system_config({"no_reqtrace": False})
            on_rows.append(_drive_engine_arm(on_engine, workload))
    finally:
        CONFIG.apply_system_config({"prefix_cache_entries": 128})
    parity_ok = all(row["outputs"] == on_rows[0]["outputs"]
                    for row in on_rows + off_rows)
    on_row = max(on_rows, key=lambda r: r["requests_per_s"])
    off_row = max(off_rows, key=lambda r: r["requests_per_s"])
    for row in on_rows + off_rows:
        row.pop("outputs")
    result = {
        "seed": seed,
        "rounds": len(on_rows),
        "reqtrace_on": on_row,
        "reqtrace_off": off_row,
        "reqtrace_on_req_per_s_rounds":
        [r["requests_per_s"] for r in on_rows],
        "reqtrace_off_req_per_s_rounds":
        [r["requests_per_s"] for r in off_rows],
        "gates": {
            "token_parity": parity_ok,
            "overhead_within_noise": on_row["requests_per_s"]
            >= 0.8 * off_row["requests_per_s"],
        },
    }
    result["passed"] = all(result["gates"].values())
    return result


class _SatLLMServer:
    """LLMServer + a stats op the saturation client polls for the
    zero-leaked-pages SLO (the proxy only routes __call__, so the leak
    probe rides the same HTTP path as the load), + a reqtrace flush op
    so the driver can collect the replica's request-lifecycle ring
    deterministically (no waiting on the metrics-flush cadence)."""

    def __new__(cls, engine_config, params=None):
        from ray_tpu.llm.serving import LLMServer

        class _Server(LLMServer):
            async def __call__(self, http_request):
                body = http_request.json()
                if body.get("op") == "leak_check":
                    stats = self._engine.stats()
                    stats["leaked_pages"] = \
                        self._engine.page_leak_check()
                    return stats
                if body.get("op") == "reqtrace_flush":
                    import asyncio

                    from ray_tpu.llm import reqtrace
                    # gcs.put must run off the replica's io loop
                    ok = await asyncio.get_event_loop() \
                        .run_in_executor(None, reqtrace.flush)
                    return {"flushed": ok}
                return await super().__call__(http_request)
        return _Server(engine_config, params=params)


def _sat_stream_once(host: str, port: int, body: dict,
                     timeout_s: float = 240.0) -> dict:
    """One streaming request over a raw socket; returns token count and
    time-to-first-token (first chunk with a token line)."""
    import socket

    payload = json.dumps(body).encode()
    s = socket.create_connection((host, int(port)), timeout=timeout_s)
    t0 = time.perf_counter()
    ttft = None
    tokens = []
    try:
        s.sendall((f"POST /llm HTTP/1.1\r\nHost: x\r\n"
                   f"Content-Length: {len(payload)}\r\n"
                   "Connection: close\r\n\r\n").encode() + payload)
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
            # the proxy only writes chunks that carry tokens (or an
            # error), so the first body line IS the first token batch
            if ttft is None and b'"tokens"' in data:
                ttft = time.perf_counter() - t0
    finally:
        s.close()
    head, _, rest = data.partition(b"\r\n\r\n")
    if b"200" not in head.split(b"\r\n", 1)[0]:
        raise RuntimeError(f"stream request failed: {head[:120]!r}")
    error = None
    buf = rest
    while buf:
        line, _, buf = buf.partition(b"\r\n")
        if not line:
            continue
        try:
            n = int(line, 16)
        except ValueError:
            continue
        if n == 0:
            break
        chunk, buf = buf[:n], buf[n + 2:]
        for ln in chunk.decode().splitlines():
            if not ln.strip():
                continue
            rec = json.loads(ln)
            tokens.extend(rec.get("tokens", []))
            if rec.get("error"):
                error = rec["error"]
    return {"tokens": tokens, "ttft_s": ttft, "error": error}


def bench_serve_saturation(seed: int = 1234, clients: int = 3,
                           requests_per_client: int = 5,
                           slo_ttft_p95_s: float = 30.0,
                           artifact_path: str =
                           "tests/artifacts_serve_saturation.json",
                           timeline_artifact_path: str =
                           "tests/artifacts_requests_timeline.json",
                           skip_cluster: bool = False) -> dict:
    """PR 17 headline bench: the in-process engine A/B (continuous vs
    RTPU_NO_CONT_BATCH legacy, radix shared-prefix arm), then sustained
    mixed-length streaming saturation through the REAL serve proxy.
    SLO gates: p95 TTFT bounded, zero dropped streams, zero leaked KV
    pages, preempted requests complete with token parity. Also runs the
    reqtrace on/off overhead A/B (regression gate: tracing within
    noise) and exports the per-request lifecycle chrome trace next to
    the SLO artifact."""
    import threading

    result = {"seed": seed, "engine_ab": serve_engine_ab(seed),
              "reqtrace_ab": reqtrace_overhead_ab(seed)}

    if not skip_cluster:
        import ray_tpu
        from ray_tpu import serve

        ray_tpu.init(num_cpus=4, object_store_memory=300 * 1024 * 1024)
        try:
            app = serve.deployment(
                _SatLLMServer, name="satllm",
                max_ongoing_requests=64).bind(_sat_engine_config())
            serve.run(app, name="llm", route_prefix="/llm",
                      wait_for_ready_timeout_s=240)
            addr = serve.api.get_http_address().replace("http://", "")
            host, port = addr.rsplit(":", 1)
            # warm the engine (first request pays the jit compiles)
            _sat_stream_once(host, int(port),
                             {"prompt_tokens": [1, 2, 3],
                              "max_new_tokens": 2, "stream": True})
            workload = _sat_mixed_workload(
                seed + 2, clients * requests_per_client)
            streams: list = []
            lock = threading.Lock()

            def client(cid):
                for r in range(requests_per_client):
                    prompt, max_new = workload[
                        cid * requests_per_client + r]
                    try:
                        out = _sat_stream_once(
                            host, int(port),
                            {"prompt_tokens": prompt,
                             "max_new_tokens": max_new, "stream": True})
                        ok = (out["error"] is None
                              and len(out["tokens"]) == max_new)
                        row = {"ok": ok, "ttft_s": out["ttft_s"],
                               "tokens": len(out["tokens"]),
                               "expected": max_new,
                               "error": out["error"]}
                    except Exception as e:  # noqa: BLE001 — gated below
                        row = {"ok": False, "ttft_s": None, "tokens": 0,
                               "expected": max_new, "error": repr(e)}
                    with lock:
                        streams.append(row)

            threads = [threading.Thread(target=client, args=(c,))
                       for c in range(clients)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            import urllib.request
            stats = json.loads(urllib.request.urlopen(
                urllib.request.Request(
                    f"http://{host}:{port}/llm",
                    data=json.dumps({"op": "leak_check"}).encode(),
                    method="POST"), timeout=60).read())
            dropped = [s for s in streams if not s["ok"]]
            ttfts = sorted(s["ttft_s"] for s in streams
                           if s["ttft_s"] is not None)
            p95 = ttfts[int(len(ttfts) * 0.95)] if ttfts \
                else float("inf")
            sat = {
                "streams": len(streams),
                "wall_s": round(wall, 2),
                "requests_per_s": round(len(streams) / wall, 2),
                "ttft_p50_s": round(ttfts[len(ttfts) // 2], 4)
                if ttfts else None,
                "ttft_p95_s": round(p95, 4),
                "dropped": dropped[:10],
                "preemptions": stats.get("preemptions"),
                "leaked_pages": stats.get("leaked_pages"),
                "slo": {
                    "zero_dropped_streams": bool(streams) and not dropped,
                    "ttft_p95_bounded": p95 <= slo_ttft_p95_s,
                    "zero_leaked_pages":
                    stats.get("leaked_pages") == 0,
                },
            }
            sat["passed"] = all(sat["slo"].values())
            result["serve_saturation"] = sat
            # requests-timeline artifact: flush the replica's reqtrace
            # ring into the GCS on demand, then fold every flushed
            # lifecycle into one chrome trace next to the SLO gates
            if timeline_artifact_path:
                from ray_tpu.llm import reqtrace
                if not reqtrace.reqtrace_disabled():
                    flushed = json.loads(urllib.request.urlopen(
                        urllib.request.Request(
                            f"http://{host}:{port}/llm",
                            data=json.dumps(
                                {"op": "reqtrace_flush"}).encode(),
                            method="POST"), timeout=60).read())
                    from ray_tpu.util import state as rt_state
                    trace = rt_state.serve_timeline(
                        timeline_artifact_path)
                    sat["requests_timeline"] = {
                        "path": timeline_artifact_path,
                        "spans": len(trace),
                        "replica_flushed": flushed.get("flushed"),
                    }
            serve.shutdown()
        finally:
            ray_tpu.shutdown()

    result["passed"] = (result["engine_ab"]["passed"]
                        and result["reqtrace_ab"]["passed"]
                        and result.get("serve_saturation",
                                       {}).get("passed", True))
    ab = result["engine_ab"]
    _report("serve_sat_cont_req_per_s",
            ab["continuous"]["requests_per_s"], "req/s")
    _report("serve_sat_legacy_req_per_s",
            ab["legacy"]["requests_per_s"], "req/s")
    _report("serve_sat_cont_ttft_p95_s",
            ab["continuous"]["ttft_p95_s"], "s")
    _report("serve_sat_legacy_ttft_p95_s",
            ab["legacy"]["ttft_p95_s"], "s")
    _report("serve_sat_radix_prefill_saved",
            ab["radix_shared_prefix"]["prefill_tokens_saved_frac"],
            "frac")
    rab = result["reqtrace_ab"]
    _report("serve_sat_reqtrace_on_req_per_s",
            rab["reqtrace_on"]["requests_per_s"], "req/s")
    _report("serve_sat_reqtrace_off_req_per_s",
            rab["reqtrace_off"]["requests_per_s"], "req/s")
    _report("serve_sat_passed", 1.0 if result["passed"] else 0.0,
            "bool", gates=dict(ab["gates"], **{
                "reqtrace_" + k: v for k, v in rab["gates"].items()}))
    if artifact_path:
        with open(artifact_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


class _SoakStreamer:
    """Streaming serve deployment for the soak: each request opens a
    token stream the proxy relays as chunked ndjson (the LLM serving
    wire shape), paced so streams span the fault windows."""

    def __init__(self, chunks: int = 40, delay_s: float = 0.15):
        self._chunks = chunks
        self._delay = delay_s
        self._streams = {}
        self._opened = 0

    def __call__(self, request):
        import uuid
        sid = uuid.uuid4().hex
        self._streams[sid] = 0
        self._opened += 1
        return {"__rtpu_stream__": sid}

    def stream_next(self, sid):
        sent = self._streams.get(sid)
        if sent is None or sent >= self._chunks:
            self._streams.pop(sid, None)
            return {"tokens": [], "done": True}
        time.sleep(self._delay)
        self._streams[sid] = sent + 1
        return {"tokens": [f"tok-{sent}"],
                "done": sent + 1 >= self._chunks}

    def cancel_stream(self, sid):
        self._streams.pop(sid, None)
        return True


def _soak_stream_once(host: str, port: int, path: str,
                      expected_chunks: int, timeout_s: float):
    """One streaming client request over a raw socket; returns the
    number of token lines received (== expected on a healthy stream)."""
    import socket

    s = socket.create_connection((host, int(port)), timeout=timeout_s)
    try:
        s.sendall((f"GET {path} HTTP/1.1\r\nHost: x\r\n"
                   "Content-Length: 2\r\n"
                   "Connection: close\r\n\r\n{}").encode())
        data = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            data += chunk
    finally:
        s.close()
    head, _, body = data.partition(b"\r\n\r\n")
    if b"200" not in head.split(b"\r\n", 1)[0]:
        raise RuntimeError(f"stream request failed: {head[:120]!r}")
    tokens = body.count(b"tok-")
    return tokens


def bench_soak(duration_s: float = 45.0, seed: int = 1234,
               nodes: int = 2, wave_size: int = 24,
               stream_chunks: int = 30, stream_delay_s: float = 0.15,
               drain_timeout_s: float = 20.0,
               slo_wave_p99_s: float = 20.0,
               slo_recover_s: float = 10.0,
               chaos_schedule: str = "",
               artifact_path: str = "") -> dict:
    """Standing chaos soak (ROADMAP item 5): sustained mixed load —
    a train-style task flood with an exactly-once audit trail plus
    streaming serve clients — on a multi-process cluster while a
    SEEDED fault script runs: scheduled transport chaos from t=0, a
    graceful rolling restart of every worker raylet, and one GCS
    ``kill -9`` mid-rollout. Gates: zero lost / zero doubled tasks,
    zero dropped streams, wave p99 under ``slo_wave_p99_s`` and
    post-fault recovery under ``slo_recover_s``. Returns (and
    optionally writes) the artifact dict."""
    import os
    import tempfile
    import threading

    import ray_tpu
    from ray_tpu import serve
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.util.state import api as state_api

    tmpdir = tempfile.mkdtemp(prefix="rtpu-soak-")
    persist = os.path.join(tmpdir, "gcs.db")
    audit = os.path.join(tmpdir, "audit.log")
    # The control-plane fault script: duplicate heartbeat replies from
    # t=0 (idempotency drill), a heartbeat delay window opening at 25%
    # of the run and closing at 60% — deterministic under the seed.
    schedule = chaos_schedule or (
        f"0:heartbeat:dup:0.05,"
        f"{duration_s * 0.25:g}:heartbeat:delay:0.3:0.05,"
        f"{duration_s * 0.6:g}:heartbeat:delay:0")
    cluster = Cluster(
        head_node_args={"num_cpus": 2},
        external_gcs=True, gcs_persist_path=persist,
        gcs_env={"RTPU_GCS_PERSIST": "wal",
                 "RTPU_CHAOS_SCHEDULE": schedule,
                 "RTPU_CHAOS_SEED": str(seed)})
    result = {"duration_s": duration_s, "seed": seed,
              "chaos_schedule": schedule, "nodes": nodes}
    try:
        cluster.connect()
        worker_nodes = [cluster.add_node(num_cpus=2)
                        for _ in range(nodes)]
        cluster.wait_for_nodes()
        # Arm the same schedule on the driver+raylet side registries.
        state_api.set_chaos(seed=seed, schedule=schedule)

        @ray_tpu.remote(num_cpus=1)
        def bump(i, marker):
            fd = os.open(marker, os.O_WRONLY | os.O_APPEND | os.O_CREAT,
                         0o644)
            try:
                os.write(fd, f"{i}\n".encode())
            finally:
                os.close(fd)
            time.sleep(0.02)
            return i

        from ray_tpu.util.scheduling_strategies import \
            NodeAffinitySchedulingStrategy
        head_id = next(n["node_id"] for n in state_api.list_nodes()
                       if n["is_head"])
        streamer = serve.deployment(_SoakStreamer).options(
            ray_actor_options={
                "num_cpus": 0,
                # replicas live on the head (off the rolled nodes): a
                # drained replica's in-flight streams are killed by
                # contract — the zero-dropped-streams SLO exercises the
                # proxy + GCS failover planes under the rollout
                "scheduling_strategy": NodeAffinitySchedulingStrategy(
                    head_id, soft=True)})
        serve.run(streamer.bind(stream_chunks, stream_delay_s),
                  name="soak", route_prefix="/soak")
        addr = serve.api.get_http_address()
        host, port = addr.rsplit("://", 1)[-1].rsplit(":", 1)

        stop = threading.Event()
        wave_lat: list = []        # (t_rel, wall_s, n_tasks)
        task_errors: list = []
        submitted = []
        streams: list = []         # (t_rel, chunks_received, error)
        t0 = time.monotonic()

        def task_thread():
            base = 0
            while not stop.is_set():
                idx = list(range(base, base + wave_size))
                base += wave_size
                submitted.extend(idx)
                w0 = time.monotonic()
                try:
                    got = ray_tpu.get(
                        [bump.remote(i, audit) for i in idx],
                        timeout=180)
                    assert got == idx
                except Exception as e:  # noqa: BLE001 — gated below
                    task_errors.append(repr(e))
                    return
                wave_lat.append((round(w0 - t0, 2),
                                 time.monotonic() - w0, len(idx)))

        def stream_thread():
            while not stop.is_set():
                s0 = time.monotonic()
                try:
                    n = _soak_stream_once(
                        host, port, "/soak", stream_chunks,
                        timeout_s=duration_s + 120)
                    streams.append((round(s0 - t0, 2), n, None))
                except Exception as e:  # noqa: BLE001 — gated below
                    streams.append((round(s0 - t0, 2), 0, repr(e)))
                    return

        from ray_tpu._internal.threads import spawn_daemon
        threads = [spawn_daemon(task_thread, name="rtpu-soak-tasks"),
                   spawn_daemon(stream_thread, name="rtpu-soak-stream")]

        # --- the fault script (wall-clock scheduled, seed-stable) ----
        faults = []

        def _at(frac, name, fn):
            target = t0 + duration_s * frac
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            f0 = time.monotonic()
            fn()
            faults.append({"at_s": round(f0 - t0, 2), "fault": name,
                           "took_s": round(time.monotonic() - f0, 2)})

        replacements = {}

        def _roll(i):
            def _do():
                replacements[i] = cluster.restart_node(
                    worker_nodes[i], timeout_s=drain_timeout_s)
            return _do

        def _gcs_bounce():
            cluster.kill_gcs()
            time.sleep(0.5)
            cluster.restart_gcs()

        _at(0.15, "rolling_restart_node_0", _roll(0))
        _at(0.40, "gcs_kill9_restart", _gcs_bounce)
        if nodes > 1:
            _at(0.60, "rolling_restart_node_1", _roll(1))
        # run out the clock under load, then stop and settle
        remaining = t0 + duration_s - time.monotonic()
        if remaining > 0:
            time.sleep(remaining)
        last_fault_rel = faults[-1]["at_s"] + faults[-1]["took_s"]
        stop.set()
        for t in threads:
            t.join(timeout=duration_s + 180)

        # --- SLO gates ----------------------------------------------
        with open(audit) as f:
            executed = sorted(int(x) for x in f.read().split())
        lost = sorted(set(submitted) - set(executed))
        doubled = sorted(x for x in set(executed)
                         if executed.count(x) > 1)
        lats = sorted(w for (_t, w, _n) in wave_lat)
        p99 = lats[int(len(lats) * 0.99)] if lats else float("inf")
        p50 = lats[len(lats) // 2] if lats else float("inf")
        # time-to-recover: the gap from the last fault to the FIRST
        # wave completion after it (NOT that wave's own latency — a
        # long wedge followed by fast waves must not pass this gate)
        recover = [t_rel + w - last_fault_rel
                   for (t_rel, w, _n) in wave_lat
                   if t_rel + w >= last_fault_rel]
        recover_s = min(recover) if recover else None
        dropped_streams = [s for s in streams
                           if s[2] is not None or s[1] != stream_chunks]
        result.update({
            "waves": len(wave_lat),
            "tasks_submitted": len(submitted),
            "tasks_lost": lost[:10],
            "tasks_doubled": doubled[:10],
            "task_errors": task_errors,
            "wave_p50_s": round(p50, 3),
            "wave_p99_s": round(p99, 3),
            "streams_completed": len(streams),
            "streams_dropped": dropped_streams[:10],
            "recover_wave_s": round(recover_s, 3)
            if recover_s is not None else None,
            "faults": faults,
            "slo": {
                "zero_lost": not lost and not task_errors,
                "zero_doubled": not doubled,
                "zero_dropped_streams": bool(streams)
                and not dropped_streams,
                "p99_bounded": p99 <= slo_wave_p99_s,
                "recovered": recover_s is not None
                and recover_s <= slo_recover_s,
            },
        })
        result["passed"] = all(result["slo"].values())
        _report("soak_wave_p99_s", p99, "s")
        _report("soak_streams_completed", len(streams), "streams")
        _report("soak_passed", 1.0 if result["passed"] else 0.0, "bool",
                slo=result["slo"])
        try:
            serve.shutdown()
        except Exception:  # noqa: BLE001 — teardown best-effort
            logging.getLogger(__name__).debug(
                "serve shutdown after soak failed", exc_info=True)
    finally:
        cluster.shutdown()
    if artifact_path:
        with open(artifact_path, "w") as f:
            json.dump(result, f, indent=1)
    return result


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--which", default="all")
    parser.add_argument("--soak-seconds", type=float, default=45.0)
    parser.add_argument("--soak-seed", type=int, default=1234)
    parser.add_argument("--soak-artifact", default="")
    parser.add_argument("--saturation-seed", type=int, default=1234)
    parser.add_argument("--saturation-artifact",
                        default="tests/artifacts_serve_saturation.json")
    args = parser.parse_args()
    which = args.which
    if which == "soak":
        # builds its OWN multi-process cluster (killable external GCS)
        bench_soak(duration_s=args.soak_seconds, seed=args.soak_seed,
                   artifact_path=args.soak_artifact)
        return
    if which == "serve_saturation":
        # does its own init (in-process engine A/B first, then the
        # serve-proxy streaming soak)
        bench_serve_saturation(seed=args.saturation_seed,
                               artifact_path=args.saturation_artifact)
        return
    import ray_tpu
    ray_tpu.init(num_cpus=8, object_store_memory=1 << 30)
    try:
        if which in ("all", "ppo"):
            bench_ppo()
        if which in ("all", "impala"):
            bench_impala()
        if which in ("all", "data"):
            bench_data()
        if which in ("all", "llm"):
            bench_llm()
        if which in ("all", "serve"):
            bench_serve()
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
