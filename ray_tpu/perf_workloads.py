"""ML-workload benchmarks covering the five BASELINE.json configs
(VERDICT r2 item 3): RLlib PPO / IMPALA sampling+learning rates, Serve
HTTP throughput + latency, Data pipeline throughput, and LLM engine
decode throughput. The Train number comes from bench.py on the TPU.

Run: python -m ray_tpu.perf_workloads [--which all|ppo|impala|serve|data|llm]
Prints one JSON line per metric.
"""

from __future__ import annotations

import argparse
import json
import time


def _report(metric: str, value: float, unit: str, **extra):
    print(json.dumps({"metric": metric, "value": round(value, 2),
                      "unit": unit, **extra}), flush=True)


def bench_ppo(iters: int = 12):
    from ray_tpu.rllib import PPOConfig
    algo = (PPOConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=8,
                         rollout_fragment_length=128)
            .training(lr=1e-3, num_epochs=10, minibatch_size=256)
            .build())
    algo.train()  # warm/compile
    t0 = time.perf_counter()
    steps = 0
    learner_rates = []
    for _ in range(iters):
        result = algo.train()
        steps += result["num_env_steps_sampled"]
        learner_rates.append(result["learner_samples_per_s"])
    wall = time.perf_counter() - t0
    algo.stop()
    _report("ppo_env_steps_per_s", steps / wall, "steps/s")
    _report("ppo_learner_samples_per_s",
            sum(learner_rates) / len(learner_rates), "samples/s")


def bench_impala(iters: int = 20):
    from ray_tpu.rllib import ImpalaConfig
    algo = (ImpalaConfig().environment("CartPole-v1")
            .env_runners(num_env_runners=2, num_envs_per_env_runner=32,
                         rollout_fragment_length=32)
            .training(lr=1e-3, train_batch_slots=64, num_epochs=2)
            .build())
    algo.train()
    t0 = time.perf_counter()
    trained = 0
    for _ in range(iters):
        result = algo.train()
        trained += result["num_env_steps_trained_this_iter"]
    wall = time.perf_counter() - t0
    algo.stop()
    _report("impala_env_steps_trained_per_s", trained / wall, "steps/s")


def bench_serve(seconds: float = 10.0, concurrency: int = 8):
    import threading
    import urllib.request

    import ray_tpu
    from ray_tpu import serve

    @serve.deployment(num_replicas=2)
    def echo(request):
        return {"ok": True}

    serve.run(echo.bind(), name="bench", route_prefix="/bench")
    base = f"{serve.api.get_http_address()}/bench"
    # warm
    for _ in range(5):
        urllib.request.urlopen(base, timeout=10).read()
    latencies = []
    lock = threading.Lock()
    stop_at = time.perf_counter() + seconds

    def pound():
        while time.perf_counter() < stop_at:
            t0 = time.perf_counter()
            urllib.request.urlopen(base, timeout=30).read()
            dt = time.perf_counter() - t0
            with lock:
                latencies.append(dt)
    threads = [threading.Thread(target=pound) for _ in range(concurrency)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    latencies.sort()
    n = len(latencies)
    _report("serve_requests_per_s", n / wall, "req/s")
    _report("serve_p50_ms", latencies[n // 2] * 1000, "ms")
    _report("serve_p95_ms", latencies[int(n * 0.95)] * 1000, "ms")
    serve.shutdown()


def bench_data(rows: int = 200_000):
    import numpy as np

    import ray_tpu.data as rd

    t0 = time.perf_counter()
    ds = rd.range(rows).map_batches(
        lambda b: {"x": np.asarray(b["id"]) * 2},
        batch_size=8192)
    total = 0
    for batch in ds.iter_batches(batch_size=8192):
        total += len(batch["x"])
    wall = time.perf_counter() - t0
    assert total == rows
    _report("data_rows_per_s", rows / wall, "rows/s")


def bench_llm(steps: int = 40):
    import numpy as np

    from ray_tpu.llm import PagedEngineConfig, PagedLLMEngine
    from ray_tpu.models.llama import LlamaConfig

    model = LlamaConfig(vocab_size=1024, hidden_size=256,
                        intermediate_size=512, num_layers=4, num_heads=8,
                        num_kv_heads=8, max_seq_len=512, remat=False,
                        use_flash=False, attention_impl="reference")
    engine = PagedLLMEngine(PagedEngineConfig(
        model=model, max_batch=8, max_len=256, page_size=16,
        num_pages=256, prefill_buckets=(32,)))
    rng = np.random.RandomState(0)
    prompts = [list(rng.randint(1, 1024, size=16)) for _ in range(8)]
    engine.generate(prompts, max_new_tokens=4)  # compile
    t0 = time.perf_counter()
    engine.generate(prompts, max_new_tokens=steps)
    wall = time.perf_counter() - t0
    _report("llm_decode_tokens_per_s", 8 * steps / wall, "tok/s",
            note="tiny CPU model; engine-overhead measurement, "
                 "HBM-bound decode is the TPU bench")


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--which", default="all")
    args = parser.parse_args()
    import ray_tpu
    ray_tpu.init(num_cpus=8, object_store_memory=1 << 30)
    which = args.which
    try:
        if which in ("all", "ppo"):
            bench_ppo()
        if which in ("all", "impala"):
            bench_impala()
        if which in ("all", "data"):
            bench_data()
        if which in ("all", "llm"):
            bench_llm()
        if which in ("all", "serve"):
            bench_serve()
    finally:
        ray_tpu.shutdown()


if __name__ == "__main__":
    main()
