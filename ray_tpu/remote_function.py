"""Remote functions (reference: python/ray/remote_function.py).

`@ray_tpu.remote` on a function yields a RemoteFunction; `.remote(*args)`
builds a TaskSpec and submits it through the CoreWorker. `.options(**kw)`
returns a shallow copy with overridden options, like the reference.

Argument packing: positional/keyword args are bundled into one inline
serialized argument with top-level ObjectRefs hoisted out as explicit
dependencies (resolved to values before execution); refs *nested* inside
structures stay refs — the reference's semantics exactly.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

from ._internal import serialization
from ._internal.config import CONFIG
from ._internal.core_worker import get_core_worker
from ._internal.ids import TaskID
from ._internal.object_ref import ObjectRef
from ._internal.options import (normalize_strategy, resources_from_options,
                                validate_options)
from ._internal.runtime_env import upload_packages
from ._internal.task_spec import (NORMAL_TASK, TaskArg, TaskSpec, _CallBundle,
                                  _RefPlaceholder)


_EMPTY_ARGS_DATA = None
_EMPTY_ARGS_ARG = None


def _trace_ctx():
    """Active span context for submit-time propagation (cheap: one
    contextvar read; None when tracing isn't in use)."""
    from .util.tracing import child_context_for_submit
    return child_context_for_submit()


def pack_args(args: Tuple, kwargs: Dict) -> List[TaskArg]:
    """Bundle (args, kwargs) into TaskArgs: one inline bundle + ref deps."""
    global _EMPTY_ARGS_DATA, _EMPTY_ARGS_ARG
    if not args and not kwargs:
        # No-arg calls (actor pings, pollers) dominate control-plane
        # floods; their bundle bytes are constant — pickle once, and
        # share ONE TaskArg template (nothing mutates inline args; only
        # the per-spec args LIST must be fresh).
        if _EMPTY_ARGS_ARG is None:
            _EMPTY_ARGS_DATA = serialization.serialize(
                _CallBundle((), {})).to_bytes()
            _EMPTY_ARGS_ARG = TaskArg(is_ref=False, data=_EMPTY_ARGS_DATA,
                                      contained_ref_ids=[])
            from ._internal.task_spec import register_constant_arg
            register_constant_arg(_EMPTY_ARGS_ARG)
        return [_EMPTY_ARGS_ARG]
    refs: List[ObjectRef] = []

    def hoist(value):
        if isinstance(value, ObjectRef):
            refs.append(value)
            return _RefPlaceholder(len(refs) - 1)
        return value

    bundle = _CallBundle(tuple(hoist(a) for a in args),
                         {k: hoist(v) for k, v in kwargs.items()})
    sobj = serialization.serialize(bundle)
    task_args = [TaskArg(is_ref=False, data=sobj.to_bytes(),
                         contained_ref_ids=[r.id()
                                            for r in sobj.contained_refs])]
    for ref in refs:
        task_args.append(TaskArg(is_ref=True, object_id=ref.id(),
                                 owner_address=ref.owner_address()))
    return task_args


class RemoteFunction:
    def __init__(self, function, options: Optional[Dict[str, Any]] = None):
        self._function = function
        self._options = dict(options or {})
        validate_options(self._options, for_actor=False)
        functools.update_wrapper(self, function)
        self._descriptor = None
        self._descriptor_owner = None
        # (worker, job_id, SpecTemplate, shape_key): the flat-wire
        # template and the lease shape key are invariant per handle —
        # computed on the first submit, reused until the core worker or
        # job changes (init/shutdown cycles, nested submissions).
        self._call_shape = None

    def options(self, **new_options) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(new_options)
        return RemoteFunction(self._function, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function.__name__} cannot be called "
            "directly; use .remote()")

    def remote(self, *args, **kwargs):
        worker = get_core_worker()
        job_id = worker.current_job_id()
        # Per core-worker export cache: module-level remote functions
        # outlive shutdown()/init() cycles (see ActorClass.remote).
        if self._descriptor is None or self._descriptor_owner is not worker:
            self._descriptor = worker.function_manager.export(
                job_id, self._function)
            self._descriptor_owner = worker
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        max_retries = opts.get("max_retries",
                               CONFIG.task_max_retries_default)
        spec = TaskSpec(
            task_id=TaskID.of(job_id),
            job_id=job_id,
            task_type=NORMAL_TASK,
            function=self._descriptor,
            args=pack_args(args, kwargs),
            num_returns=num_returns,
            resources=resources_from_options(opts, default_num_cpus=1),
            owner_address=worker.rpc_address,
            owner_worker_id=worker.worker_id,
            name=opts.get("name") or self._function.__qualname__,
            scheduling_strategy=normalize_strategy(
                opts.get("scheduling_strategy")),
            max_retries=max_retries,
            retry_exceptions=opts.get("retry_exceptions", False),
            runtime_env=upload_packages(opts.get("runtime_env"),
                                        worker.gcs),
            label_selector=opts.get("label_selector") or {},
            enable_task_events=opts.get("enable_task_events", True),
            trace_context=_trace_ctx(),
        )
        # Handle-level shape cache, invalidated on runtime_env CONTENT
        # change: upload_packages re-hashes working_dir/py_modules per
        # call, so an edited package shows up as a different env dict
        # here — freezing on (worker, job) alone would pin the stale
        # template/shape key (and the old package) forever.
        shape = self._call_shape
        if shape is None or shape[0] is not worker or shape[1] != job_id \
                or shape[2] != spec.runtime_env:
            from ._internal.task_spec import make_template
            shape = (worker, job_id, spec.runtime_env,
                     make_template(spec), spec.shape_key())
            self._call_shape = shape
        spec.flat_template = shape[3]
        spec._shape_key = shape[4]
        refs = worker.submit_task(spec)
        if num_returns == "streaming":
            from ._internal.object_ref import ObjectRefGenerator
            return ObjectRefGenerator(generator_ref=refs[0])
        if num_returns == "dynamic":
            return refs[0]
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

