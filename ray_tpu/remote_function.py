"""Remote functions (reference: python/ray/remote_function.py).

`@ray_tpu.remote` on a function yields a RemoteFunction; `.remote(*args)`
builds a TaskSpec and submits it through the CoreWorker. `.options(**kw)`
returns a shallow copy with overridden options, like the reference.

Argument packing: positional/keyword args are bundled into one inline
serialized argument with top-level ObjectRefs hoisted out as explicit
dependencies (resolved to values before execution); refs *nested* inside
structures stay refs — the reference's semantics exactly.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Tuple

from ._internal import serialization
from ._internal.config import CONFIG
from ._internal.core_worker import get_core_worker
from ._internal.ids import TaskID
from ._internal.object_ref import ObjectRef
from ._internal.options import (normalize_strategy, resources_from_options,
                                validate_options)
from ._internal.runtime_env import upload_packages
from ._internal.task_spec import (NORMAL_TASK, TaskArg, TaskSpec, _CallBundle,
                                  _RefPlaceholder)


_EMPTY_ARGS_DATA = None


def _trace_ctx():
    """Active span context for submit-time propagation (cheap: one
    contextvar read; None when tracing isn't in use)."""
    from .util.tracing import child_context_for_submit
    return child_context_for_submit()


def pack_args(args: Tuple, kwargs: Dict) -> List[TaskArg]:
    """Bundle (args, kwargs) into TaskArgs: one inline bundle + ref deps."""
    global _EMPTY_ARGS_DATA
    if not args and not kwargs:
        # No-arg calls (actor pings, pollers) dominate control-plane
        # floods; their bundle bytes are constant — pickle once.
        if _EMPTY_ARGS_DATA is None:
            _EMPTY_ARGS_DATA = serialization.serialize(
                _CallBundle((), {})).to_bytes()
        return [TaskArg(is_ref=False, data=_EMPTY_ARGS_DATA,
                        contained_ref_ids=[])]
    refs: List[ObjectRef] = []

    def hoist(value):
        if isinstance(value, ObjectRef):
            refs.append(value)
            return _RefPlaceholder(len(refs) - 1)
        return value

    bundle = _CallBundle(tuple(hoist(a) for a in args),
                         {k: hoist(v) for k, v in kwargs.items()})
    sobj = serialization.serialize(bundle)
    task_args = [TaskArg(is_ref=False, data=sobj.to_bytes(),
                         contained_ref_ids=[r.id()
                                            for r in sobj.contained_refs])]
    for ref in refs:
        task_args.append(TaskArg(is_ref=True, object_id=ref.id(),
                                 owner_address=ref.owner_address()))
    return task_args


class RemoteFunction:
    def __init__(self, function, options: Optional[Dict[str, Any]] = None):
        self._function = function
        self._options = dict(options or {})
        validate_options(self._options, for_actor=False)
        functools.update_wrapper(self, function)
        self._descriptor = None
        self._descriptor_owner = None

    def options(self, **new_options) -> "RemoteFunction":
        merged = dict(self._options)
        merged.update(new_options)
        return RemoteFunction(self._function, merged)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"remote function {self._function.__name__} cannot be called "
            "directly; use .remote()")

    def remote(self, *args, **kwargs):
        worker = get_core_worker()
        job_id = worker.current_job_id()
        # Per core-worker export cache: module-level remote functions
        # outlive shutdown()/init() cycles (see ActorClass.remote).
        if self._descriptor is None or self._descriptor_owner is not worker:
            self._descriptor = worker.function_manager.export(
                job_id, self._function)
            self._descriptor_owner = worker
        opts = self._options
        num_returns = opts.get("num_returns", 1)
        max_retries = opts.get("max_retries",
                               CONFIG.task_max_retries_default)
        spec = TaskSpec(
            task_id=TaskID.of(job_id),
            job_id=job_id,
            task_type=NORMAL_TASK,
            function=self._descriptor,
            args=pack_args(args, kwargs),
            num_returns=num_returns,
            resources=resources_from_options(opts, default_num_cpus=1),
            owner_address=worker.rpc_address,
            owner_worker_id=worker.worker_id,
            name=opts.get("name") or self._function.__qualname__,
            scheduling_strategy=normalize_strategy(
                opts.get("scheduling_strategy")),
            max_retries=max_retries,
            retry_exceptions=opts.get("retry_exceptions", False),
            runtime_env=upload_packages(opts.get("runtime_env"),
                                        worker.gcs),
            label_selector=opts.get("label_selector") or {},
            enable_task_events=opts.get("enable_task_events", True),
            trace_context=_trace_ctx(),
        )
        refs = worker.submit_task(spec)
        if num_returns == "streaming":
            from ._internal.object_ref import ObjectRefGenerator
            return ObjectRefGenerator(generator_ref=refs[0])
        if num_returns == "dynamic":
            return refs[0]
        if num_returns == 0:
            return None
        if num_returns == 1:
            return refs[0]
        return refs

