"""ray_tpu.rllib — reinforcement learning on the TPU-native runtime
(reference: python/ray/rllib — Algorithm algorithms/algorithm.py:207,
EnvRunnerGroup env/env_runner_group.py:71, SingleAgentEnvRunner
env/single_agent_env_runner.py:68, Learner core/learner/learner.py:106
compute_gradients :463 / update :979, PPO algorithms/ppo/).

Architecture (TPU-first redesign of the reference's data path):
env-runner actors sample episodes with a CPU copy of the policy; the
learner holds the canonical parameters on a device mesh and runs ONE
jitted update per minibatch (GAE + clipped surrogate + value + entropy in
a single XLA program); fresh weights broadcast back to runners each
iteration. The reference's torch DDP learner-group maps here to mesh
data-parallelism inside the jitted update."""

from .algorithm import PPO, PPOConfig
from .appo import Appo, AppoConfig, AppoLearner
from .cql import CQL, CQLConfig
from .dqn import DQN, DQNConfig, DQNLearner, ReplayBufferActor
from .env_runner import SingleAgentEnvRunner
from .impala import Impala, ImpalaConfig, ImpalaLearner
from .iql import IQL, IQLConfig
from .learner import PPOLearner
from .multi_agent import (MultiAgentEnv, MultiAgentEnvRunner,
                          MultiAgentPPO, MultiAgentPPOConfig,
                          make_multi_agent)
from .offline import (BC, BCConfig, MARWIL, MARWILConfig,
                      record_episodes)
from .sac import SAC, SACConfig, SACLearner

__all__ = ["PPO", "PPOConfig", "PPOLearner", "SingleAgentEnvRunner",
           "Impala", "ImpalaConfig", "ImpalaLearner",
           "Appo", "AppoConfig", "AppoLearner", "CQL", "CQLConfig",
           "IQL", "IQLConfig",
           "DQN", "DQNConfig", "DQNLearner", "ReplayBufferActor",
           "SAC", "SACConfig", "SACLearner",
           "MultiAgentEnv", "MultiAgentEnvRunner", "MultiAgentPPO",
           "MultiAgentPPOConfig", "make_multi_agent",
           "BC", "BCConfig", "MARWIL", "MARWILConfig", "record_episodes"]
