"""PPO Algorithm: config + training loop
(reference: rllib/algorithms/algorithm.py:207 Algorithm.step :1007 /
training_step :2068; AlgorithmConfig builder pattern
algorithm_config.py; PPO algorithms/ppo/ppo.py).

training_step: env-runner actors sample fragments in parallel → GAE →
flatten → learner minibatch update → weights broadcast back to runners."""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


class PPOConfig:
    """Builder-style config (reference: AlgorithmConfig)."""

    def __init__(self):
        self.env_name = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 64
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 6
        self.minibatch_size = 512
        self.grad_clip = 0.5
        self.model = {"hidden": (64, 64)}
        self.seed = 0

    def environment(self, env: str) -> "PPOConfig":
        self.env_name = env
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "PPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "PPOConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "PPO":
        return PPO(self)


class PPO:
    def __init__(self, config: PPOConfig):
        import ray_tpu
        from .env_runner import SingleAgentEnvRunner
        from .learner import PPOLearner

        self.config = config
        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        self._runners = [
            runner_cls.options(num_cpus=1).remote(
                config.env_name, config.num_envs_per_env_runner,
                config.rollout_fragment_length, dict(config.model),
                seed=config.seed + 1000 * (i + 1), gamma=config.gamma)
            for i in range(config.num_env_runners)
        ]
        obs_shape = ray_tpu.get(
            self._runners[0].observation_shape.remote(), timeout=120)
        import gymnasium as gym
        probe = gym.make(config.env_name)
        num_actions = int(probe.action_space.n)
        probe.close()
        self._learner = PPOLearner(
            obs_shape=obs_shape, num_actions=num_actions,
            model_config=dict(config.model), lr=config.lr,
            clip_param=config.clip_param, vf_coeff=config.vf_coeff,
            entropy_coeff=config.entropy_coeff, grad_clip=config.grad_clip,
            seed=config.seed)
        self._broadcast_weights()
        self._iteration = 0
        self._recent_returns: List[float] = []

    def _broadcast_weights(self):
        import ray_tpu
        weights = self._learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights) for r in self._runners],
                    timeout=120)

    def train(self) -> Dict[str, Any]:
        """One training iteration (reference: Algorithm.step :1007)."""
        import ray_tpu
        from .learner import compute_gae

        config = self.config
        t0 = time.perf_counter()
        fragments = ray_tpu.get(
            [r.sample.remote() for r in self._runners], timeout=300)
        sample_time = time.perf_counter() - t0

        obs, actions, logp, adv, rets = [], [], [], [], []
        for frag in fragments:
            a, r = compute_gae(frag["rewards"], frag["values"],
                               frag["dones"], frag["bootstrap_value"],
                               config.gamma, config.lambda_)
            obs.append(frag["obs"].reshape(-1, *frag["obs"].shape[2:]))
            actions.append(frag["actions"].reshape(-1))
            logp.append(frag["logp"].reshape(-1))
            adv.append(a.reshape(-1))
            rets.append(r.reshape(-1))
            self._recent_returns.extend(frag["episode_returns"].tolist())
        batch = {
            "obs": np.concatenate(obs),
            "actions": np.concatenate(actions),
            "logp_old": np.concatenate(logp),
            "advantages": np.concatenate(adv),
            "returns": np.concatenate(rets),
        }
        t1 = time.perf_counter()
        learn_metrics = self._learner.update(
            batch, num_epochs=config.num_epochs,
            minibatch_size=config.minibatch_size,
            seed=config.seed + self._iteration)
        learn_time = time.perf_counter() - t1
        self._broadcast_weights()

        self._iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        num_samples = len(batch["obs"])
        return {
            "training_iteration": self._iteration,
            "num_env_steps_sampled": num_samples,
            "episode_return_mean": float(np.mean(self._recent_returns))
            if self._recent_returns else float("nan"),
            "num_episodes": len(self._recent_returns),
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
            "learner_samples_per_s": num_samples / max(learn_time, 1e-9),
            **learn_metrics,
        }

    def stop(self):
        import ray_tpu
        for runner in self._runners:
            try:
                ray_tpu.kill(runner)
            except Exception:  # noqa: BLE001
                logger.debug("runner kill at stop failed", exc_info=True)
