"""APPO: asynchronous PPO on the IMPALA actor-learner pipeline
(reference: rllib/algorithms/appo/appo.py — APPOConfig :59 with
clip_param / use_kl_loss / kl_coeff / target_network_update_freq,
training_step :268 reusing IMPALA's async sampling; loss in
appo_learner — PPO clipped surrogate over v-trace advantages computed
against a slow-moving TARGET policy).

Why a target network at all: the async pipeline trains on fragments that
are several weight-broadcasts stale. Pure IMPALA corrects the
distribution gap with per-step importance clipping; APPO instead anchors
the v-trace targets and the trust region to a policy that only moves
every `target_network_update_freq` learner steps, then takes PPO-style
clipped steps against it — bounded-size updates no matter how stale the
behavior data.

TPU notes: the whole update (current + target forward, v-trace reverse
scan, surrogate, Adam) is ONE jitted program in [T, B] layout; the
target refresh is a host-side params copy every N steps, not a traced
branch."""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from .impala import Impala, ImpalaConfig, make_vtrace


class AppoConfig(ImpalaConfig):
    """Builder config (reference: appo.py APPOConfig :59)."""

    def __init__(self):
        super().__init__()
        self.clip_param = 0.2
        self.use_kl_loss = True
        self.kl_coeff = 0.2
        self.target_network_update_freq = 4   # learner steps
        self.lr = 3e-4
        self.num_epochs = 2                   # PPO reuses each batch

    def build(self) -> "Appo":
        return Appo(self)


class AppoLearner:
    """Jitted APPO update in [T, B] layout.

    v-trace advantages/targets come from the TARGET policy (its logp as
    the numerator of the correction ratio, its values for bootstrap);
    the policy step is the PPO clipped surrogate of the CURRENT policy
    against the recorded behavior logp, optionally with a KL(target ||
    current) penalty (reference: appo_learner loss)."""

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 model_config: Optional[Dict[str, Any]] = None,
                 lr: float = 3e-4, gamma: float = 0.99,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 rho_bar: float = 1.0, c_bar: float = 1.0,
                 grad_clip: float = 40.0, seed: int = 0,
                 normalize_advantages: bool = True,
                 vtrace_lambda: float = 0.95,
                 clip_param: float = 0.2,
                 use_kl_loss: bool = True, kl_coeff: float = 0.2,
                 target_network_update_freq: int = 4,
                 lr_final: Optional[float] = None,
                 lr_decay_steps: int = 0,
                 lr_decay_begin: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        from .models import ActorCriticMLP

        model_config = model_config or {}
        self.model = ActorCriticMLP(
            num_actions=num_actions,
            hidden=tuple(model_config.get("hidden", (64, 64))))
        sample_obs = jnp.zeros((1,) + tuple(obs_shape), jnp.float32)
        self.params = self.model.init(
            jax.random.PRNGKey(seed), sample_obs)["params"]
        self.target_params = jax.tree.map(lambda x: x, self.params)
        if lr_final is not None and lr_decay_steps > 0:
            lr = optax.linear_schedule(
                init_value=lr, end_value=lr_final,
                transition_steps=lr_decay_steps,
                transition_begin=lr_decay_begin)
        self.tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)
        self._step = 0
        self._target_freq = max(1, target_network_update_freq)
        self._entropy_coeff = entropy_coeff

        vtrace = make_vtrace(gamma, rho_bar, c_bar, vtrace_lambda)

        def _update(params, target_params, opt_state, batch, ent_coeff):
            T, B = batch["actions"].shape
            flat_obs = batch["obs"].reshape((T * B,) +
                                            batch["obs"].shape[2:])
            # Target-policy pass: anchors v-trace and the trust region.
            t_logits, t_values = self.model.apply(
                {"params": target_params}, flat_obs)
            t_logits = t_logits.reshape(T, B, -1)
            t_values = t_values.reshape(T, B)
            _lb, t_boot = self.model.apply(
                {"params": target_params}, batch["last_obs"])
            t_logp_all = jax.nn.log_softmax(t_logits)
            t_logp = jnp.take_along_axis(
                t_logp_all, batch["actions"][..., None], axis=-1)[..., 0]
            vs, pg_adv = vtrace(t_logp, batch["logp"], t_values, t_boot,
                                batch["rewards"], batch["dones"])
            if normalize_advantages:
                pg_adv = (pg_adv - pg_adv.mean()) / (pg_adv.std() + 1e-8)

            def loss_fn(p):
                logits, values = self.model.apply({"params": p}, flat_obs)
                logits = logits.reshape(T, B, -1)
                values = values.reshape(T, B)
                logp_all = jax.nn.log_softmax(logits)
                curr_logp = jnp.take_along_axis(
                    logp_all, batch["actions"][..., None],
                    axis=-1)[..., 0]
                ratio = jnp.exp(curr_logp - batch["logp"])
                clipped = jnp.clip(ratio, 1.0 - clip_param,
                                   1.0 + clip_param)
                surrogate = -jnp.mean(
                    jnp.minimum(ratio * pg_adv, clipped * pg_adv))
                vf_loss = 0.5 * jnp.mean((values - vs) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
                kl = jnp.mean(jnp.sum(
                    jnp.exp(t_logp_all) * (t_logp_all - logp_all),
                    axis=-1))
                total = surrogate + vf_coeff * vf_loss \
                    - ent_coeff * entropy
                if use_kl_loss:
                    total = total + kl_coeff * kl
                return total, (surrogate, vf_loss, entropy, kl)

            (total, (pl, vl, ent, kl)), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {
                "total_loss": total, "policy_loss": pl, "vf_loss": vl,
                "entropy": ent, "kl": kl}

        self._update_fn = jax.jit(_update)

    def update(self, batch: Dict[str, np.ndarray], num_epochs: int = 1,
               entropy_coeff: Optional[float] = None) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k != "episode_returns"}
        coeff = jnp.float32(self._entropy_coeff if entropy_coeff is None
                            else entropy_coeff)
        metrics = {}
        for _ in range(num_epochs):
            self.params, self.opt_state, metrics = self._update_fn(
                self.params, self.target_params, self.opt_state, jb,
                coeff)
            self._step += 1
            if self._step % self._target_freq == 0:
                self.target_params = jax.tree.map(lambda x: x,
                                                  self.params)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        import jax
        return jax.device_get(self.params)


class Appo(Impala):
    """IMPALA's async sampling pipeline + the APPO learner (reference:
    appo.py training_step :268 — 'inherits from IMPALA')."""

    def _make_learner(self, obs_shape, num_actions):
        config = self.config
        return AppoLearner(
            obs_shape=obs_shape, num_actions=num_actions,
            model_config=dict(config.model), lr=config.lr,
            gamma=config.gamma, vf_coeff=config.vf_coeff,
            entropy_coeff=config.entropy_coeff, rho_bar=config.rho_bar,
            c_bar=config.c_bar, grad_clip=config.grad_clip,
            seed=config.seed,
            normalize_advantages=config.normalize_advantages,
            vtrace_lambda=config.vtrace_lambda,
            clip_param=config.clip_param,
            use_kl_loss=config.use_kl_loss, kl_coeff=config.kl_coeff,
            target_network_update_freq=config.target_network_update_freq,
            lr_final=config.lr_final,
            lr_decay_steps=config.lr_decay_iters * config.num_epochs,
            lr_decay_begin=config.lr_decay_begin_iters *
            config.num_epochs)
