"""CQL: conservative Q-learning from offline data
(reference: rllib/algorithms/cql/cql.py — CQLConfig :51 with
bc_iters/temperature/min_q_weight, built on SAC's Q machinery;
cql_learner adds the conservative penalty to the critic loss. The CQL
paper's discrete form is exact: logsumexp over the action set needs no
sampled-action approximation).

The critic update is the repo's double-Q TD step (rllib/dqn.py) plus the
conservative term  E_s[ log Σ_a exp(Q(s,a)/τ)·τ − Q(s, a_data) ]: it
pushes down Q on out-of-distribution actions while holding it up on
dataset actions, which is what keeps a greedy policy from exploiting
extrapolation error the dataset can't refute. Whole update is one jitted
program; data comes from a ray_tpu.data Dataset of recorded transitions
(the Data↔RLlib offline bridge, offline.py)."""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np


class CQLConfig:
    """Builder config (reference: cql.py CQLConfig :51)."""

    def __init__(self):
        self.env_name = "CartPole-v1"
        self.lr = 5e-4
        self.gamma = 0.99
        self.batch_size = 256
        self.num_steps = 3000
        self.target_update_freq = 100        # gradient steps
        self.min_q_weight = 1.0              # alpha on the CQL penalty
        self.temperature = 1.0               # tau in the logsumexp
        self.model = {"hidden": (128, 128)}
        self.seed = 0

    def environment(self, env: str) -> "CQLConfig":
        self.env_name = env
        return self

    def training(self, **kwargs) -> "CQLConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "CQL":
        return CQL(self)


def _transitions_from_dataset(dataset) -> Dict[str, np.ndarray]:
    """Reconstruct (obs, action, reward, next_obs, done) from the
    row-per-step episodes that offline.record_episodes writes: within an
    episode rows are in step order, so next_obs is the next row's obs;
    terminal steps get a zero next_obs masked by done."""
    from .offline import group_episodes
    by_ep = group_episodes(dataset.take_all())
    obs, actions, rewards, next_obs, dones = [], [], [], [], []
    for ep_rows in by_ep.values():
        for i, r in enumerate(ep_rows):
            done = bool(r["done"])
            last = i + 1 == len(ep_rows)
            if last and not done:
                # truncated recording (step budget, not a terminal):
                # there is no real next_obs to bootstrap from, and
                # done=0 would bootstrap from a fabricated state —
                # drop the transition (the standard truncation fix)
                continue
            o = np.asarray(r["obs"], np.float32)
            obs.append(o)
            actions.append(int(r["action"]))
            rewards.append(float(r["reward"]))
            dones.append(done)
            next_obs.append(np.zeros_like(o) if done
                            else np.asarray(ep_rows[i + 1]["obs"],
                                            np.float32))
    return {
        "obs": np.stack(obs),
        "actions": np.asarray(actions, np.int32),
        "rewards": np.asarray(rewards, np.float32),
        "next_obs": np.stack(next_obs),
        "dones": np.asarray(dones, np.float32),
    }


class CQL:
    def __init__(self, config: CQLConfig):
        self.config = config
        self._params = None
        self._model = None

    def fit(self, dataset) -> Dict[str, Any]:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from .models import QMLP

        c = self.config
        probe = gym.make(c.env_name)
        num_actions = int(probe.action_space.n)
        probe.close()

        data = _transitions_from_dataset(dataset)
        n = data["obs"].shape[0]
        jd = {k: jnp.asarray(v) for k, v in data.items()}

        model = QMLP(num_actions=num_actions,
                     hidden=tuple(c.model.get("hidden", (128, 128))))
        rng = jax.random.PRNGKey(c.seed)
        params = model.init(rng, jd["obs"][:1])["params"]
        target_params = jax.tree.map(lambda x: x, params)
        tx = optax.adam(c.lr)
        opt_state = tx.init(params)
        tau = c.temperature

        @jax.jit
        def step(params, target_params, opt_state, idx):
            b_obs = jd["obs"][idx]  # jit capture ok: trace-constant dataset tensors
            b_act = jd["actions"][idx]
            b_rew = jd["rewards"][idx]
            b_next = jd["next_obs"][idx]
            b_done = jd["dones"][idx]

            # double-Q target: argmax under online net, value under target
            next_online = model.apply({"params": params}, b_next)
            next_a = jnp.argmax(next_online, axis=-1)
            next_target = model.apply({"params": target_params}, b_next)
            next_q = jnp.take_along_axis(
                next_target, next_a[:, None], axis=-1)[:, 0]
            td_target = b_rew + c.gamma * (1.0 - b_done) * next_q

            def loss_fn(p):
                q_all = model.apply({"params": p}, b_obs)
                q_data = jnp.take_along_axis(
                    q_all, b_act[:, None], axis=-1)[:, 0]
                td_loss = jnp.mean(
                    (q_data - jax.lax.stop_gradient(td_target)) ** 2)
                # discrete CQL: exact logsumexp over actions
                lse = tau * jax.scipy.special.logsumexp(
                    q_all / tau, axis=-1)
                cql_penalty = jnp.mean(lse - q_data)
                return td_loss + c.min_q_weight * cql_penalty, \
                    (td_loss, cql_penalty)

            (total, (td, pen)), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, total, td, pen

        key = jax.random.PRNGKey(c.seed + 1)
        total = td = pen = jnp.float32(0)
        first_pen = None
        for i in range(c.num_steps):
            key, sub = jax.random.split(key)
            idx = jax.random.randint(sub, (c.batch_size,), 0, n)
            params, opt_state, total, td, pen = step(
                params, target_params, opt_state, idx)
            if first_pen is None:
                first_pen = float(pen)  # host-sync ok: once per fit
            if (i + 1) % c.target_update_freq == 0:
                target_params = jax.tree.map(lambda x: x, params)

        self._params = params
        self._model = model
        return {"final_loss": float(total), "td_loss": float(td),
                "cql_penalty": float(pen),
                "cql_penalty_initial": first_pen,
                "num_transitions": int(n)}

    def evaluate(self, num_episodes: int = 5) -> float:
        import jax
        import jax.numpy as jnp
        assert self._params is not None, "fit() first"
        model, params = self._model, self._params

        @jax.jit
        def act(obs):
            q = model.apply({"params": params}, obs[None])
            return jnp.argmax(q, axis=-1)[0]

        from .offline import greedy_rollout_score
        return greedy_rollout_score(self.config.env_name, act,
                                    num_episodes, seed_base=30_000)
