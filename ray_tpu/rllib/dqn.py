"""DQN: off-policy value learning with replay-buffer actors
(reference: rllib/algorithms/dqn/ — DQN/DQNConfig, replay via
EpisodeReplayBuffer actors, target network, double-Q, sample-ratio
control a.k.a. training_intensity).

Structurally different from PPO/IMPALA (VERDICT r3 missing #3): the
hot state is a LARGE replay buffer living in its own actor(s), learners
sample from it at a controlled replay ratio, and the behavior policy
(epsilon-greedy on the online net) trails the learned greedy policy.

TPU-first: the TD update is one jitted program (double-DQN target,
Huber loss, adam) over batched transitions; replay actors hold numpy
ring buffers and batch samples for the learner's device puts."""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)


class DQNConfig:
    """Builder-style config (reference: dqn/dqn.py DQNConfig)."""

    def __init__(self):
        self.env_name = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 16
        self.buffer_capacity = 50_000
        self.num_replay_shards = 1
        self.learning_starts = 1_000
        self.batch_size = 128
        self.lr = 5e-4
        self.gamma = 0.99
        self.target_update_freq = 500      # in learner updates
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 6_000   # env steps
        # n-step targets (reference: dqn config n_step): bootstraps over
        # gamma^n with n-step reward sums — much faster credit
        # assignment on dense-reward control tasks
        self.n_step = 3
        # replay ratio: trained transitions per sampled transition
        # (reference: training_intensity)
        self.training_intensity = 16.0
        self.grad_clip = 10.0
        self.model = {"hidden": (128, 128)}
        self.seed = 0

    def environment(self, env: str) -> "DQNConfig":
        self.env_name = env
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "DQNConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "DQNConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "DQN":
        return DQN(self)


class ReplayBufferActor:
    """Uniform-sampling transition ring buffer as an actor (reference:
    rllib/utils/replay_buffers/ — buffers live outside the learner so
    capacity scales with cluster memory, and N shards parallelize the
    sample path)."""

    def __init__(self, capacity: int, obs_shape, seed: int = 0,
                 action_shape=(), action_dtype="int32"):
        self._capacity = capacity
        self._obs = np.zeros((capacity,) + tuple(obs_shape), np.float32)
        self._next_obs = np.zeros_like(self._obs)
        # () int32 for discrete control; (act_dim,) float32 for
        # continuous (SAC reuses these shards — reference builds SAC on
        # DQN's replay machinery, sac.py:560)
        self._actions = np.zeros((capacity,) + tuple(action_shape),
                                 np.dtype(action_dtype))
        self._rewards = np.zeros(capacity, np.float32)
        self._dones = np.zeros(capacity, np.float32)
        # per-transition bootstrap discount gamma^k (n-step targets may
        # shorten at episode/fragment ends)
        self._discounts = np.zeros(capacity, np.float32)
        self._size = 0
        self._pos = 0
        self._rng = np.random.default_rng(seed)

    def add_batch(self, obs, actions, rewards, next_obs, dones,
                  discounts=None) -> int:
        n = len(actions)
        idx = (self._pos + np.arange(n)) % self._capacity
        self._obs[idx] = obs
        self._actions[idx] = actions
        self._rewards[idx] = rewards
        self._next_obs[idx] = next_obs
        self._dones[idx] = dones
        self._discounts[idx] = discounts if discounts is not None else 0.99
        self._pos = int((self._pos + n) % self._capacity)
        self._size = int(min(self._size + n, self._capacity))
        return self._size

    def sample(self, batch_size: int) -> Dict[str, np.ndarray]:
        idx = self._rng.integers(0, self._size, size=batch_size)
        return {
            "obs": self._obs[idx],
            "actions": self._actions[idx],
            "rewards": self._rewards[idx],
            "next_obs": self._next_obs[idx],
            "dones": self._dones[idx],
            "discounts": self._discounts[idx],
        }

    def sample_many(self, batch_size: int, k: int) -> Dict[str, np.ndarray]:
        """k independent uniform batches in ONE actor call (the learner
        slices locally) — amortizes the RPC over a replay burst."""
        return self.sample(batch_size * k)

    def size(self) -> int:
        return self._size


class DQNEnvRunner:
    """Epsilon-greedy fragment sampler (reference:
    single_agent_env_runner.py with the EpsilonGreedy exploration
    connector)."""

    def __init__(self, env_name: str, num_envs: int, fragment_len: int,
                 model_config: Dict[str, Any], seed: int = 0,
                 gamma: float = 0.99, n_step: int = 1):
        import gymnasium as gym
        import jax
        import jax.numpy as jnp

        from .models import QMLP

        self._gamma = gamma
        self._n_step = max(1, n_step)

        env_fns = [lambda: gym.make(env_name) for _ in range(num_envs)]
        try:
            self._envs = gym.vector.SyncVectorEnv(
                env_fns, autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        except (AttributeError, TypeError):
            self._envs = gym.vector.SyncVectorEnv(env_fns)
        self._num_envs = num_envs
        self._T = fragment_len
        self._model = QMLP(
            num_actions=int(self._envs.single_action_space.n),
            hidden=tuple(model_config.get("hidden", (128, 128))))
        self._rng = jax.random.PRNGKey(seed)
        self._params = None

        def greedy(params, obs):
            q = self._model.apply({"params": params}, obs)
            return jnp.argmax(q, axis=-1)

        self._greedy = jax.jit(greedy)
        obs, _ = self._envs.reset(seed=seed)
        self._obs = obs.astype(np.float32)
        self._np_rng = np.random.default_rng(seed + 1)
        self._episode_returns = np.zeros(num_envs, np.float64)
        self._completed: List[float] = []

    def observation_shape(self):
        return tuple(self._envs.single_observation_space.shape)

    def num_actions(self) -> int:
        return int(self._envs.single_action_space.n)

    def set_weights(self, params) -> bool:
        self._params = params
        return True

    def sample(self, epsilon: float) -> Dict[str, np.ndarray]:
        assert self._params is not None, "set_weights first"
        T, N = self._T, self._num_envs
        obs_buf = np.empty((T, N) + self._obs.shape[1:], np.float32)
        next_buf = np.empty_like(obs_buf)
        act_buf = np.empty((T, N), np.int32)
        rew_buf = np.empty((T, N), np.float32)
        term_buf = np.empty((T, N), bool)
        break_buf = np.empty((T, N), bool)  # terminated OR truncated
        for t in range(T):
            greedy = np.asarray(self._greedy(self._params, self._obs))
            explore = self._np_rng.random(N) < epsilon
            random_actions = self._np_rng.integers(
                0, self._model.num_actions, size=N)
            actions = np.where(explore, random_actions, greedy).astype(
                np.int32)
            next_obs, reward, terminated, truncated, _infos = \
                self._envs.step(actions)
            obs_buf[t] = self._obs
            act_buf[t] = actions
            rew_buf[t] = reward
            next_buf[t] = next_obs.astype(np.float32)
            # Truncation is not termination: the target must still
            # bootstrap from s' (done=0), matching the reference's
            # episode-truncation handling.
            term_buf[t] = terminated
            break_buf[t] = np.logical_or(terminated, truncated)
            self._episode_returns += reward
            for i in np.nonzero(break_buf[t])[0]:
                self._completed.append(float(self._episode_returns[i]))
                self._episode_returns[i] = 0.0
            self._obs = next_obs.astype(np.float32)
        # n-step aggregation within the fragment (reference: dqn n_step):
        # sum rewards forward up to n steps, stopping at episode breaks;
        # bootstrap from the final reached state with discount gamma^k.
        gamma, n = self._gamma, self._n_step
        r_agg = rew_buf.copy()
        next_k = next_buf.copy()
        done_k = term_buf.astype(np.float32)
        disc = np.full((T, N), gamma, np.float32)
        cur = ~break_buf  # can this transition extend past step t+k-1?
        for k in range(1, n):
            can = np.zeros((T, N), bool)
            can[:T - k] = cur[:T - k]
            ts, es = np.nonzero(can)
            if len(ts) == 0:
                break
            r_agg[ts, es] += (gamma ** k) * rew_buf[ts + k, es]
            next_k[ts, es] = next_buf[ts + k, es]
            done_k[ts, es] = term_buf[ts + k, es].astype(np.float32)
            disc[ts, es] = gamma ** (k + 1)
            nxt = np.zeros((T, N), bool)
            nxt[:T - k] = cur[:T - k] & ~break_buf[k:]
            cur = nxt
        returns, self._completed = self._completed, []
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])  # noqa: E731
        return {"obs": flat(obs_buf), "actions": flat(act_buf),
                "rewards": flat(r_agg), "next_obs": flat(next_k),
                "dones": flat(done_k), "discounts": flat(disc),
                "episode_returns": np.asarray(returns, np.float64)}


class DQNLearner:
    """Jitted double-DQN update (reference: dqn torch learner; here one
    XLA program: gather Q(s,a), double-Q target, Huber, adam)."""

    def __init__(self, obs_shape, num_actions: int,
                 model_config: Dict[str, Any], lr: float, gamma: float,
                 grad_clip: float, seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        from .models import QMLP

        self._model = QMLP(num_actions=num_actions,
                           hidden=tuple(model_config.get(
                               "hidden", (128, 128))))
        rng = jax.random.PRNGKey(seed)
        dummy = jnp.zeros((1,) + tuple(obs_shape), jnp.float32)
        self.params = self._model.init(rng, dummy)["params"]
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)
        self._tx = optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr))
        self.opt_state = self._tx.init(self.params)
        model = self._model
        tx = self._tx

        def update(params, target_params, opt_state, batch):
            def loss_fn(p):
                q = model.apply({"params": p}, batch["obs"])
                q_sa = jnp.take_along_axis(
                    q, batch["actions"][:, None].astype(jnp.int32),
                    axis=-1)[:, 0]
                # double DQN: online net picks a', target net evaluates
                q_next_online = model.apply({"params": p},
                                            batch["next_obs"])
                a_next = jnp.argmax(q_next_online, axis=-1)
                q_next_target = model.apply({"params": target_params},
                                            batch["next_obs"])
                q_next = jnp.take_along_axis(
                    q_next_target, a_next[:, None], axis=-1)[:, 0]
                # per-transition discount = gamma^k (n-step targets)
                target = batch["rewards"] + (1.0 - batch["dones"]) * \
                    batch["discounts"] * jax.lax.stop_gradient(q_next)
                td = q_sa - target
                huber = jnp.where(jnp.abs(td) <= 1.0, 0.5 * td ** 2,
                                  jnp.abs(td) - 0.5)
                return huber.mean(), jnp.abs(td).mean()

            (loss, td_mean), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            import optax as _optax
            params = _optax.apply_updates(params, updates)
            return params, opt_state, loss, td_mean

        import jax as _jax
        self._update = _jax.jit(update)

    def update(self, batch) -> Dict[str, float]:
        import jax.numpy as jnp
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        self.params, self.opt_state, loss, td = self._update(
            self.params, self.target_params, self.opt_state, dev)
        return {"loss": float(loss), "td_error_mean": float(td)}

    def sync_target(self):
        import jax
        self.target_params = jax.tree_util.tree_map(
            lambda x: x, self.params)

    def get_weights(self):
        import jax
        return jax.device_get(self.params)


class DQN:
    """The algorithm driver (reference: dqn.py DQN.training_step —
    sample, store, replay at training_intensity, target sync)."""

    def __init__(self, config: DQNConfig):
        import ray_tpu

        self.config = config
        runner_cls = ray_tpu.remote(DQNEnvRunner)
        self._runners = [
            runner_cls.options(num_cpus=1).remote(
                config.env_name, config.num_envs_per_env_runner,
                config.rollout_fragment_length, dict(config.model),
                seed=config.seed + 1000 * (i + 1), gamma=config.gamma,
                n_step=config.n_step)
            for i in range(config.num_env_runners)]
        obs_shape = ray_tpu.get(
            self._runners[0].observation_shape.remote(), timeout=120)
        num_actions = ray_tpu.get(
            self._runners[0].num_actions.remote(), timeout=120)
        buffer_cls = ray_tpu.remote(ReplayBufferActor)
        per_shard = config.buffer_capacity // config.num_replay_shards
        self._buffers = [
            buffer_cls.options(num_cpus=0.5).remote(
                per_shard, obs_shape, seed=config.seed + i)
            for i in range(config.num_replay_shards)]
        self._learner = DQNLearner(
            obs_shape, num_actions, dict(config.model), config.lr,
            config.gamma, config.grad_clip, seed=config.seed)
        self._broadcast_weights()
        self._env_steps = 0
        self._updates = 0
        self._trained_transitions = 0
        self._iteration = 0
        self._recent_returns: List[float] = []
        self._rr = 0  # buffer round-robin cursor

    def _broadcast_weights(self):
        import ray_tpu
        weights = self._learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights)
                     for r in self._runners], timeout=120)

    def _epsilon(self) -> float:
        c = self.config
        frac = min(1.0, self._env_steps / max(1, c.epsilon_decay_steps))
        return c.epsilon_initial + frac * (c.epsilon_final -
                                           c.epsilon_initial)

    def train(self) -> Dict[str, Any]:
        import ray_tpu
        c = self.config
        t0 = time.perf_counter()
        eps = self._epsilon()
        fragments = ray_tpu.get(
            [r.sample.remote(eps) for r in self._runners], timeout=300)
        adds = []
        sampled = 0
        for frag in fragments:
            sampled += len(frag["actions"])
            self._recent_returns.extend(frag["episode_returns"].tolist())
            buf = self._buffers[self._rr % len(self._buffers)]
            self._rr += 1
            adds.append(buf.add_batch.remote(
                frag["obs"], frag["actions"], frag["rewards"],
                frag["next_obs"], frag["dones"], frag["discounts"]))
        if len(self._buffers) == 1:
            # Adds are ordered actor calls on one buffer, each returning
            # the cumulative size — the last one is the true total
            # (summing would double-count earlier fragments).
            buffer_size = ray_tpu.get(adds, timeout=120)[-1] if adds else 0
        else:
            ray_tpu.get(adds, timeout=120)
            buffer_size = sum(ray_tpu.get(
                [b.size.remote() for b in self._buffers], timeout=120))
        self._env_steps += sampled
        sample_time = time.perf_counter() - t0

        metrics: Dict[str, float] = {}
        t1 = time.perf_counter()
        if buffer_size >= c.learning_starts:
            # sample-ratio control: keep trained/sampled at
            # training_intensity
            want_trained = int(self._env_steps * c.training_intensity)
            n_updates = max(0, (want_trained - self._trained_transitions)
                            // c.batch_size)
            # one replay RPC per burst of updates (sliced locally), with
            # the next burst prefetched while this one trains
            burst = 8
            remaining = n_updates
            pending = None
            if remaining:
                pending = self._buffers[self._rr % len(self._buffers)] \
                    .sample_many.remote(c.batch_size,
                                        min(burst, remaining))
            while remaining > 0:
                k = min(burst, remaining)
                big = ray_tpu.get(pending, timeout=120)
                self._rr += 1
                nxt = min(burst, remaining - k)
                if nxt:
                    pending = self._buffers[
                        self._rr % len(self._buffers)] \
                        .sample_many.remote(c.batch_size, nxt)
                for j in range(k):
                    sl = slice(j * c.batch_size, (j + 1) * c.batch_size)
                    batch = {key: v[sl] for key, v in big.items()}
                    metrics = self._learner.update(batch)
                    self._updates += 1
                    self._trained_transitions += c.batch_size
                    if self._updates % c.target_update_freq == 0:
                        self._learner.sync_target()
                remaining -= k
            self._broadcast_weights()
        learn_time = time.perf_counter() - t1

        self._iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        return {
            "training_iteration": self._iteration,
            "num_env_steps_sampled": self._env_steps,
            "num_updates": self._updates,
            "replay_buffer_size": buffer_size,
            "epsilon": eps,
            "episode_return_mean": float(np.mean(self._recent_returns))
            if self._recent_returns else float("nan"),
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
            **metrics,
        }

    def evaluate(self, num_episodes: int = 5) -> float:
        """Greedy-policy evaluation on a fresh env."""
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        env = gym.make(self.config.env_name)
        model = self._learner._model
        params = self._learner.params

        @jax.jit
        def act(obs):
            q = model.apply({"params": params}, obs[None])
            return jnp.argmax(q, axis=-1)[0]

        total = 0.0
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=10_000 + ep)
            done = False
            while not done:
                action = int(act(jnp.asarray(obs, jnp.float32)))  # host-sync ok: env.step needs a host int
                obs, reward, terminated, truncated, _ = env.step(action)
                total += reward
                done = terminated or truncated
        env.close()
        return total / num_episodes

    def stop(self):
        import ray_tpu
        for actor in self._runners + self._buffers:
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001
                logger.debug("actor kill at stop failed", exc_info=True)
