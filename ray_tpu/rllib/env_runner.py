"""SingleAgentEnvRunner actor
(reference: rllib/env/single_agent_env_runner.py:68 — vectorized gym envs,
samples fixed-length fragments with the current policy, reports episode
returns; EnvRunnerGroup env_runner_group.py:71 manages N of these actors).

Runs the policy on CPU (jitted once); the learner owns the canonical
device-mesh copy and pushes weights here every iteration."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np


class SingleAgentEnvRunner:
    def __init__(self, env_name: str, num_envs: int,
                 rollout_fragment_length: int, model_config: Dict[str, Any],
                 seed: int = 0, gamma: float = 0.99):
        import gymnasium as gym
        import jax
        from .models import ActorCriticMLP

        env_fns = [lambda: gym.make(env_name) for _ in range(num_envs)]
        try:
            # Same-step autoreset: the done step carries the episode's real
            # final reward and the returned obs is already the reset obs —
            # every recorded transition is a genuine one. (The 1.x default
            # NEXT_STEP mode ignores the action on the post-done step and
            # returns reward 0: one corrupt transition per episode.)
            self._envs = gym.vector.SyncVectorEnv(
                env_fns, autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        except (AttributeError, TypeError):  # older gymnasium
            self._envs = gym.vector.SyncVectorEnv(env_fns)
        self._num_envs = num_envs
        self._T = rollout_fragment_length
        self._gamma = gamma
        self._model = ActorCriticMLP(
            num_actions=int(self._envs.single_action_space.n),
            hidden=tuple(model_config.get("hidden", (64, 64))))
        self._rng = jax.random.PRNGKey(seed)
        self._params = None

        from .models import sample_action
        self._sample = jax.jit(
            lambda p, obs, rng: sample_action(p, self._model, obs, rng))

        obs, _info = self._envs.reset(seed=seed)
        self._obs = obs.astype(np.float32)
        self._episode_returns = np.zeros(num_envs, np.float64)
        self._completed_returns: List[float] = []

    def observation_shape(self):
        return tuple(self._envs.single_observation_space.shape)

    def set_weights(self, params) -> bool:
        self._params = params
        return True

    def sample(self) -> Dict[str, np.ndarray]:
        """One fragment: arrays shaped [T, N, ...] plus bootstrap values.
        Also drains completed-episode returns for metrics."""
        import jax
        assert self._params is not None, "set_weights first"
        T, N = self._T, self._num_envs
        obs_buf = np.empty((T, N) + self._obs.shape[1:], np.float32)
        act_buf = np.empty((T, N), np.int32)
        logp_buf = np.empty((T, N), np.float32)
        val_buf = np.empty((T, N), np.float32)
        rew_buf = np.empty((T, N), np.float32)
        done_buf = np.empty((T, N), np.float32)

        for t in range(T):
            self._rng, key = jax.random.split(self._rng)
            action, logp, value = self._sample(self._params, self._obs, key)
            action = np.asarray(action)
            obs_buf[t] = self._obs
            act_buf[t] = action
            logp_buf[t] = np.asarray(logp)
            val_buf[t] = np.asarray(value)
            next_obs, reward, terminated, truncated, infos = \
                self._envs.step(action)
            done = np.logical_or(terminated, truncated)
            rew_buf[t] = reward
            if np.any(truncated):
                # Time-limit truncation is NOT termination: bootstrap the
                # cut-off return with V(final_obs) folded into the reward
                # (reference: postprocessing treats truncated episodes by
                # bootstrapping the value of the last observation).
                finals = infos.get("final_obs",
                                   infos.get("final_observation"))
                idx = np.nonzero(truncated)[0]
                if finals is not None:
                    fobs = np.stack([np.asarray(finals[i], np.float32)
                                     for i in idx])
                    self._rng, fkey = jax.random.split(self._rng)
                    _fa, _fl, fval = self._sample(self._params, fobs, fkey)
                    rew_buf[t, idx] += self._gamma * np.asarray(fval)
            done_buf[t] = done.astype(np.float32)
            self._episode_returns += reward
            for i in np.nonzero(done)[0]:
                self._completed_returns.append(float(
                    self._episode_returns[i]))
                self._episode_returns[i] = 0.0
            self._obs = next_obs.astype(np.float32)

        self._rng, key = jax.random.split(self._rng)
        _a, _lp, last_value = self._sample(self._params, self._obs, key)
        returns, self._completed_returns = self._completed_returns, []
        return {
            "obs": obs_buf, "actions": act_buf, "logp": logp_buf,
            "values": val_buf, "rewards": rew_buf, "dones": done_buf,
            "bootstrap_value": np.asarray(last_value, np.float32),
            # off-policy learners (IMPALA v-trace) bootstrap from the
            # final obs under their CURRENT params, not our stale value
            "last_obs": self._obs.copy(),
            "episode_returns": np.asarray(returns, np.float64),
        }
