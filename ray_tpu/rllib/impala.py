"""IMPALA: importance-weighted actor-learner architecture
(reference: rllib/algorithms/impala/impala.py — config :66, async
training_step :516, AggregatorActor batching :729, learner-group update
:869; v-trace from the IMPALA paper, re-derived as a jitted lax.scan).

Design (TPU-first):
- Env-runner actors sample CONTINUOUSLY: the driver keeps a window of
  in-flight sample() calls per runner and never blocks sampling on the
  learner (the off-policy gap is what v-trace corrects).
- Aggregator actors concatenate fragments into fixed-size train batches
  off the driver (reference :729's stateless AggregatorActors) so
  neither sampling nor learning waits on batch assembly.
- The learner's whole update — forward, v-trace targets (reverse scan),
  losses, Adam — is ONE jitted program in [T, B] layout; on a
  multi-device mesh the batch axis shards and GSPMD inserts the
  gradient allreduce.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Aggregation (reference: impala.py:729 AggregatorActor)
# ---------------------------------------------------------------------------

class AggregatorActor:
    """Accumulates [T, N] fragments; emits [T, B] train batches."""

    def __init__(self, batch_n: int):
        self._batch_n = batch_n  # env-slots per emitted batch (B)
        self._frags: List[Dict[str, np.ndarray]] = []
        self._slots = 0

    def add(self, fragment: Dict[str, np.ndarray]) -> Optional[
            Dict[str, np.ndarray]]:
        """Add one fragment; returns a train batch when full, else None."""
        self._frags.append(fragment)
        self._slots += fragment["obs"].shape[1]
        if self._slots < self._batch_n:
            return None
        frags, self._frags, self._slots = self._frags, [], 0
        batch = {
            key: np.concatenate([f[key] for f in frags], axis=1)
            for key in ("obs", "actions", "logp", "rewards", "dones")
        }
        batch["last_obs"] = np.concatenate(
            [f["last_obs"] for f in frags], axis=0)
        batch["episode_returns"] = np.concatenate(
            [f["episode_returns"] for f in frags])
        return batch


# ---------------------------------------------------------------------------
# v-trace (IMPALA paper eq. 1; shared by the IMPALA and APPO learners)
# ---------------------------------------------------------------------------

def make_vtrace(gamma: float, rho_bar: float, c_bar: float,
                lam: float):
    """Returns vtrace(correction_logp, behavior_logp, values, bootstrap,
    rewards, dones) -> (vs, pg_adv). All inputs [T, B]; bootstrap [B].
    `correction_logp` is the numerator policy of the importance ratio
    (IMPALA: the current policy; APPO: the target policy). `lam`
    discounts the trace cut (paper appendix C / rllib vtrace lambda_)."""
    import jax
    import jax.numpy as jnp

    def vtrace(correction_logp, behavior_logp, values, bootstrap,
               rewards, dones):
        rhos = jnp.exp(correction_logp - behavior_logp)
        clipped_rho = jnp.minimum(rho_bar, rhos)
        clipped_c = lam * jnp.minimum(c_bar, rhos)
        nonterminal = 1.0 - dones
        next_values = jnp.concatenate(
            [values[1:], bootstrap[None]], axis=0)
        deltas = clipped_rho * (
            rewards + gamma * nonterminal * next_values - values)

        def step(carry, xs):
            delta, c, nt = xs
            acc = delta + gamma * nt * c * carry
            return acc, acc

        _, vs_minus_v = jax.lax.scan(
            step, jnp.zeros_like(bootstrap),
            (deltas, clipped_c, nonterminal), reverse=True)
        vs = values + vs_minus_v
        next_vs = jnp.concatenate([vs[1:], bootstrap[None]], axis=0)
        pg_adv = clipped_rho * (
            rewards + gamma * nonterminal * next_vs - values)
        return vs, pg_adv

    return vtrace


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------

class ImpalaConfig:
    """Builder-style config (reference: impala.py IMPALAConfig :66)."""

    def __init__(self):
        self.env_name = "CartPole-v1"
        self.num_env_runners = 2
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 32
        self.num_aggregators = 1
        self.train_batch_slots = 32      # B of the [T, B] train batch
        self.sample_window = 2           # in-flight sample() per runner
        self.lr = 6e-4
        self.gamma = 0.99
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        # linear entropy decay: coeff anneals to `entropy_coeff_final`
        # over `entropy_decay_iters` learner iterations (None = constant).
        # Late-training entropy pressure is what caps CartPole ~360: the
        # optimal policy is near-deterministic, and a constant bonus
        # keeps prying it open.
        self.entropy_coeff_final: Optional[float] = None
        self.entropy_decay_iters = 0
        # linear lr decay to `lr_final` over `lr_decay_iters` learner
        # iterations (None = constant). The late-training plateau just
        # under the CartPole bar (best 440 @ 8M steps, round-4 artifact)
        # is lr-oscillation: a converged near-deterministic policy keeps
        # getting kicked off the optimum by full-size Adam steps.
        self.lr_final: Optional[float] = None
        self.lr_decay_iters = 0
        # iterations at full lr before the decay starts (the policy
        # needs the large steps to reach the 475-basin first; decaying
        # from iter 0 froze a run at ~394)
        self.lr_decay_begin_iters = 0
        self.rho_bar = 1.0
        self.c_bar = 1.0
        self.normalize_advantages = True
        self.vtrace_lambda = 0.95
        self.num_epochs = 1
        self.grad_clip = 40.0
        self.broadcast_interval = 1      # learner steps between syncs
        self.model = {"hidden": (64, 64)}
        self.seed = 0

    def environment(self, env: str) -> "ImpalaConfig":
        self.env_name = env
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "ImpalaConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "ImpalaConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "Impala":
        return Impala(self)


# ---------------------------------------------------------------------------
# Learner (v-trace)
# ---------------------------------------------------------------------------

class ImpalaLearner:
    """Jitted v-trace update in [T, B] layout."""

    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 model_config: Optional[Dict[str, Any]] = None,
                 lr: float = 6e-4, gamma: float = 0.99,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 rho_bar: float = 1.0, c_bar: float = 1.0,
                 grad_clip: float = 40.0, seed: int = 0,
                 normalize_advantages: bool = True,
                 vtrace_lambda: float = 0.95,
                 lr_final: Optional[float] = None,
                 lr_decay_steps: int = 0,
                 lr_decay_begin: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        from .models import ActorCriticMLP

        model_config = model_config or {}
        self.model = ActorCriticMLP(
            num_actions=num_actions,
            hidden=tuple(model_config.get("hidden", (64, 64))))
        sample_obs = jnp.zeros((1,) + tuple(obs_shape), jnp.float32)
        self.params = self.model.init(
            jax.random.PRNGKey(seed), sample_obs)["params"]
        if lr_final is not None and lr_decay_steps > 0:
            lr = optax.linear_schedule(
                init_value=lr, end_value=lr_final,
                transition_steps=lr_decay_steps,
                transition_begin=lr_decay_begin)
        self.tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)

        vtrace = make_vtrace(gamma, rho_bar, c_bar, vtrace_lambda)

        def _update(params, opt_state, batch, ent_coeff):
            def loss_fn(p):
                T, B = batch["actions"].shape
                flat_obs = batch["obs"].reshape((T * B,) +
                                                batch["obs"].shape[2:])
                logits, values = self.model.apply({"params": p}, flat_obs)
                logits = logits.reshape(T, B, -1)
                values = values.reshape(T, B)
                _lb, boot_values = self.model.apply(
                    {"params": p}, batch["last_obs"])
                logp_all = jax.nn.log_softmax(logits)
                target_logp = jnp.take_along_axis(
                    logp_all, batch["actions"][..., None], axis=-1)[..., 0]
                vs, pg_adv = vtrace(
                    jax.lax.stop_gradient(target_logp), batch["logp"],
                    jax.lax.stop_gradient(values),
                    jax.lax.stop_gradient(boot_values),
                    batch["rewards"], batch["dones"])
                if normalize_advantages:
                    # v-trace advantages are lambda=1 returns-minus-V:
                    # on long-horizon dense-reward envs their scale (tens)
                    # swamps the entropy/value terms — normalize per batch
                    # (the paper's Atari setup instead clips rewards to
                    # [-1,1], which serves the same purpose).
                    pg_adv = (pg_adv - pg_adv.mean()) / (pg_adv.std()
                                                         + 1e-8)
                policy_loss = -jnp.mean(
                    target_logp * jax.lax.stop_gradient(pg_adv))
                vf_loss = 0.5 * jnp.mean(
                    (values - jax.lax.stop_gradient(vs)) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
                total = policy_loss + vf_coeff * vf_loss \
                    - ent_coeff * entropy
                return total, (policy_loss, vf_loss, entropy)

            (total, (pl, vl, ent)), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {
                "total_loss": total, "policy_loss": pl, "vf_loss": vl,
                "entropy": ent}
        self._update = jax.jit(_update)
        self._entropy_coeff = entropy_coeff

    def update(self, batch: Dict[str, np.ndarray],
               num_epochs: int = 1,
               entropy_coeff: Optional[float] = None) -> Dict[str, float]:
        """Up to `num_epochs` v-trace passes over one batch (reference:
        impala.py:747 — num_epochs; the recorded behavior logp stays
        fixed, so later passes are just more off-policy and the
        importance clipping absorbs it). `entropy_coeff` overrides the
        configured coefficient (decay schedules — it's a traced scalar,
        no recompilation)."""
        import jax.numpy as jnp
        jb = {k: jnp.asarray(v) for k, v in batch.items()
              if k != "episode_returns"}
        coeff = jnp.float32(self._entropy_coeff if entropy_coeff is None
                            else entropy_coeff)
        metrics = {}
        for _ in range(num_epochs):
            self.params, self.opt_state, metrics = self._update(
                self.params, self.opt_state, jb, coeff)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        import jax
        return jax.device_get(self.params)


# ---------------------------------------------------------------------------
# Algorithm (reference: impala.py:516 async training_step)
# ---------------------------------------------------------------------------

class Impala:
    def __init__(self, config: ImpalaConfig):
        import gymnasium as gym

        import ray_tpu

        from .env_runner import SingleAgentEnvRunner

        self.config = config
        runner_cls = ray_tpu.remote(SingleAgentEnvRunner)
        self._runners = [
            runner_cls.options(num_cpus=0.5).remote(
                config.env_name, config.num_envs_per_env_runner,
                config.rollout_fragment_length, dict(config.model),
                seed=config.seed + 1000 * (i + 1), gamma=config.gamma)
            for i in range(config.num_env_runners)
        ]
        agg_cls = ray_tpu.remote(AggregatorActor)
        self._aggregators = [
            agg_cls.options(num_cpus=0.5).remote(config.train_batch_slots)
            for _ in range(config.num_aggregators)
        ]
        obs_shape = ray_tpu.get(
            self._runners[0].observation_shape.remote(), timeout=120)
        probe = gym.make(config.env_name)
        num_actions = int(probe.action_space.n)
        probe.close()
        self._learner = self._make_learner(obs_shape, num_actions)
        self._broadcast_weights()
        # continuous sampling pipeline: sample ref -> owning runner
        self._inflight: Dict[Any, Any] = {}
        for runner in self._runners:
            for _ in range(config.sample_window):
                self._inflight[runner.sample.remote()] = runner
        self._agg_rr = 0            # round-robin aggregator cursor
        self._pending_batches: List = []  # refs of aggregator outputs
        self._iteration = 0
        self._recent_returns: List[float] = []
        self._env_steps = 0

    def _make_learner(self, obs_shape, num_actions):
        """Overridable learner factory (APPO swaps in its clipped-
        surrogate learner while reusing the whole async pipeline)."""
        config = self.config
        return ImpalaLearner(
            obs_shape=obs_shape, num_actions=num_actions,
            model_config=dict(config.model), lr=config.lr,
            gamma=config.gamma, vf_coeff=config.vf_coeff,
            entropy_coeff=config.entropy_coeff, rho_bar=config.rho_bar,
            c_bar=config.c_bar, grad_clip=config.grad_clip,
            seed=config.seed,
            normalize_advantages=config.normalize_advantages,
            vtrace_lambda=config.vtrace_lambda,
            lr_final=config.lr_final,
            # the schedule counts optimizer steps: num_epochs per iter
            lr_decay_steps=config.lr_decay_iters * config.num_epochs,
            lr_decay_begin=config.lr_decay_begin_iters *
            config.num_epochs)

    def _broadcast_weights(self):
        import ray_tpu
        weights = self._learner.get_weights()
        # fire-and-forget: samplers stay async (reference: async_training)
        self._weight_refs = [r.set_weights.remote(weights)
                             for r in self._runners]
        ray_tpu.wait(self._weight_refs, num_returns=len(self._weight_refs),
                     timeout=60)

    def _pump_samples(self, timeout: float):
        """Move completed fragments into aggregators; refill the sample
        window; collect any completed train batches."""
        import ray_tpu
        ready, _ = ray_tpu.wait(
            list(self._inflight), num_returns=1, timeout=timeout)
        for ref in ready:
            runner = self._inflight.pop(ref)
            agg = self._aggregators[self._agg_rr % len(self._aggregators)]
            self._agg_rr += 1
            self._pending_batches.append(agg.add.remote(ref))
            self._inflight[runner.sample.remote()] = runner

    def train(self) -> Dict[str, Any]:
        """One learner iteration: wait for an aggregated batch while
        sampling continues, then v-trace update + weight broadcast."""
        import ray_tpu

        config = self.config
        t0 = time.perf_counter()
        batch = None
        dropped = 0
        while batch is None:
            self._pump_samples(timeout=10.0)
            ready_batches = []
            still_pending = []
            for ref in self._pending_batches:
                done, _ = ray_tpu.wait([ref], num_returns=1, timeout=0.001)
                if done:
                    out = ray_tpu.get(ref)
                    if out is not None:
                        ready_batches.append(out)
                else:
                    still_pending.append(ref)
            self._pending_batches = still_pending
            if ready_batches:
                # Train on the FRESHEST batch; older ready batches are
                # dropped (reference: impala's learner-queue semantics —
                # bounded staleness beats bonus throughput; stale
                # multi-epoch updates are what collapse the policy).
                batch = ready_batches[-1]
                dropped = len(ready_batches) - 1
                for extra in ready_batches[:-1]:
                    self._recent_returns.extend(
                        extra["episode_returns"].tolist())
            if time.perf_counter() - t0 > 300:
                raise TimeoutError("no train batch within 300s")
        sample_time = time.perf_counter() - t0
        self._dropped_batches = getattr(self, "_dropped_batches", 0) \
            + dropped

        self._recent_returns.extend(batch["episode_returns"].tolist())
        t1 = time.perf_counter()
        ent = None
        if config.entropy_coeff_final is not None and \
                config.entropy_decay_iters > 0:
            frac = min(1.0, self._iteration / config.entropy_decay_iters)
            ent = config.entropy_coeff + frac * (
                config.entropy_coeff_final - config.entropy_coeff)
        metrics = self._learner.update(batch,
                                       num_epochs=config.num_epochs,
                                       entropy_coeff=ent)
        learn_time = time.perf_counter() - t1
        self._iteration += 1
        if self._iteration % config.broadcast_interval == 0:
            self._broadcast_weights()

        T, B = batch["actions"].shape
        self._env_steps += T * B
        self._recent_returns = self._recent_returns[-100:]
        return {
            "training_iteration": self._iteration,
            "num_env_steps_sampled": self._env_steps,
            "num_env_steps_trained_this_iter": T * B,
            "episode_return_mean": float(np.mean(self._recent_returns))
            if self._recent_returns else float("nan"),
            "sample_wait_s": sample_time,
            "learn_time_s": learn_time,
            "learner_samples_per_s": T * B / max(learn_time, 1e-9),
            **metrics,
        }

    def stop(self):
        import ray_tpu
        for actor in self._runners + self._aggregators:
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001
                logger.debug("actor kill at stop failed", exc_info=True)
