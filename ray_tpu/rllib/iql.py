"""IQL: implicit Q-learning from offline data
(reference: rllib/algorithms — IQL sits in the offline family with
BC/MARWIL/CQL; Kostrikov et al. 2021. Three jitted pieces:

1. expectile value regression  V(s) <- argmin E[L2^tau(Q_target(s,a)-V(s))]
   — the tau-expectile of the DATASET's action-value distribution, an
   in-sample soft-max that never queries out-of-distribution actions;
2. TD critic  Q(s,a) <- r + gamma * V(s')  (SARSA-style, no argmax over
   actions the dataset can't refute — the anti-extrapolation property
   CQL gets from its penalty, IQL gets for free from in-sample V);
3. advantage-weighted extraction  pi <- argmax E[exp(beta*(Q-V)) log pi]
   (AWR on the implicit advantage).

Discrete-action form on the repo's offline transitions Dataset
(offline.record_episodes / group_episodes)."""

from __future__ import annotations

from typing import Any, Dict


class IQLConfig:
    def __init__(self):
        self.env_name = "CartPole-v1"
        self.lr = 5e-4
        self.gamma = 0.99
        self.expectile = 0.8          # tau of the value regression
        self.beta = 3.0               # AWR inverse temperature
        self.adv_clip = 20.0          # exp-weight ceiling
        self.batch_size = 256
        self.num_steps = 3000
        self.target_update_freq = 100
        self.model = {"hidden": (128, 128)}
        self.seed = 0

    def environment(self, env: str) -> "IQLConfig":
        self.env_name = env
        return self

    def training(self, **kwargs) -> "IQLConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "IQL":
        return IQL(self)


class IQL:
    def __init__(self, config: IQLConfig):
        self.config = config
        self._params = None
        self._model = None

    def fit(self, dataset) -> Dict[str, Any]:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        import flax.linen as nn

        from .cql import _transitions_from_dataset

        c = self.config
        probe = gym.make(c.env_name)
        num_actions = int(probe.action_space.n)
        probe.close()

        data = _transitions_from_dataset(dataset)
        n = data["obs"].shape[0]
        jd = {k: jnp.asarray(v) for k, v in data.items()}

        hidden = tuple(c.model.get("hidden", (128, 128)))

        class IQLNet(nn.Module):
            """Shared torso; Q head per action, scalar V head, policy
            logits head."""

            @nn.compact
            def __call__(self, obs):
                x = obs
                for width in hidden:
                    x = nn.relu(nn.Dense(width)(x))
                q = nn.Dense(num_actions, name="q_head")(x)
                v = jnp.squeeze(nn.Dense(1, name="v_head")(x), -1)
                logits = nn.Dense(num_actions, name="pi_head")(x)
                return q, v, logits

        model = IQLNet()
        params = model.init(jax.random.PRNGKey(c.seed),
                            jd["obs"][:1])["params"]
        target_params = jax.tree.map(lambda x: x, params)
        tx = optax.adam(c.lr)
        opt_state = tx.init(params)

        def expectile_loss(diff):
            weight = jnp.where(diff > 0, c.expectile, 1.0 - c.expectile)
            return weight * diff ** 2

        @jax.jit
        def step(params, target_params, opt_state, idx):
            b_obs = jd["obs"][idx]  # jit capture ok: trace-constant dataset tensors
            b_act = jd["actions"][idx]
            b_rew = jd["rewards"][idx]
            b_next = jd["next_obs"][idx]
            b_done = jd["dones"][idx]

            tq, _tv, _tl = model.apply({"params": target_params}, b_obs)
            tq_data = jnp.take_along_axis(tq, b_act[:, None],
                                          axis=-1)[:, 0]
            _nq, next_v, _nl = model.apply({"params": params}, b_next)
            next_v = jax.lax.stop_gradient(next_v)

            def loss_fn(p):
                q, v, logits = model.apply({"params": p}, b_obs)
                q_data = jnp.take_along_axis(q, b_act[:, None],
                                             axis=-1)[:, 0]
                # (1) expectile value regression toward target-Q
                v_loss = jnp.mean(expectile_loss(
                    jax.lax.stop_gradient(tq_data) - v))
                # (2) SARSA-style TD: bootstrap from V(s'), never from a
                # max over out-of-sample actions
                td_target = b_rew + c.gamma * (1.0 - b_done) * next_v
                q_loss = jnp.mean(
                    (q_data - jax.lax.stop_gradient(td_target)) ** 2)
                # (3) AWR extraction on the implicit advantage
                adv = jax.lax.stop_gradient(tq_data) - \
                    jax.lax.stop_gradient(v)
                weight = jnp.minimum(jnp.exp(c.beta * adv), c.adv_clip)
                logp = jax.nn.log_softmax(logits)
                nll = -jnp.take_along_axis(logp, b_act[:, None],
                                           axis=-1)[:, 0]
                pi_loss = jnp.mean(jax.lax.stop_gradient(weight) * nll)
                return v_loss + q_loss + pi_loss, (v_loss, q_loss,
                                                   pi_loss)

            (total, (vl, ql, pl)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, total, vl, ql, pl

        key = jax.random.PRNGKey(c.seed + 1)
        total = vl = ql = pl = jnp.float32(0)
        for i in range(c.num_steps):
            key, sub = jax.random.split(key)
            idx = jax.random.randint(sub, (c.batch_size,), 0, n)
            params, opt_state, total, vl, ql, pl = step(
                params, target_params, opt_state, idx)
            if (i + 1) % c.target_update_freq == 0:
                target_params = jax.tree.map(lambda x: x, params)

        self._params = params
        self._model = model
        return {"final_loss": float(total), "v_loss": float(vl),
                "q_loss": float(ql), "pi_loss": float(pl),
                "num_transitions": int(n)}

    def evaluate(self, num_episodes: int = 5) -> float:
        import jax
        import jax.numpy as jnp
        assert self._params is not None, "fit() first"
        model, params = self._model, self._params

        @jax.jit
        def act(obs):
            _q, _v, logits = model.apply({"params": params}, obs[None])
            return jnp.argmax(logits, axis=-1)[0]

        from .offline import greedy_rollout_score
        return greedy_rollout_score(self.config.env_name, act,
                                    num_episodes, seed_base=50_000)
