"""PPOLearner: the on-mesh update
(reference: rllib/core/learner/learner.py:106 — compute_gradients :463,
apply_gradients :609, update :979; PPO loss
algorithms/ppo/ppo_learner.py + torch policy losses).

The whole minibatch update — clipped surrogate, value loss, entropy bonus,
Adam — is ONE jitted program; with a multi-device mesh the minibatch
shards over the `data` axis and GSPMD inserts the gradient allreduce (the
reference's torch-DDP LearnerGroup equivalent)."""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ..parallel.mesh import MeshConfig
from .models import ActorCriticMLP


def compute_gae(rewards, values, dones, bootstrap_value, gamma: float,
                lam: float):
    """Generalized advantage estimation over [T, N] fragments (numpy,
    runner-side shapes; reference: postprocessing compute_advantages)."""
    T = rewards.shape[0]
    adv = np.zeros_like(rewards)
    last = np.zeros_like(bootstrap_value)
    next_value = bootstrap_value
    for t in range(T - 1, -1, -1):
        nonterminal = 1.0 - dones[t]
        delta = rewards[t] + gamma * next_value * nonterminal - values[t]
        last = delta + gamma * lam * nonterminal * last
        adv[t] = last
        next_value = values[t]
    returns = adv + values
    return adv, returns


class PPOLearner:
    def __init__(self, obs_shape: Tuple[int, ...], num_actions: int,
                 model_config: Optional[Dict[str, Any]] = None,
                 lr: float = 3e-4, clip_param: float = 0.2,
                 vf_coeff: float = 0.5, entropy_coeff: float = 0.01,
                 mesh_config: Optional[MeshConfig] = None,
                 grad_clip: float = 0.5, seed: int = 0):
        model_config = model_config or {}
        self.model = ActorCriticMLP(
            num_actions=num_actions,
            hidden=tuple(model_config.get("hidden", (64, 64))))
        self.mesh = (mesh_config or MeshConfig(data=1)).build() \
            if mesh_config else None
        sample_obs = jnp.zeros((1,) + tuple(obs_shape), jnp.float32)
        self.params = self.model.init(
            jax.random.PRNGKey(seed), sample_obs)["params"]
        self.tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                              optax.adam(lr))
        self.opt_state = self.tx.init(self.params)
        self.clip = clip_param
        self.vf_coeff = vf_coeff
        self.ent_coeff = entropy_coeff

        @jax.jit
        def _update(params, opt_state, batch):
            def loss_fn(p):
                logits, values = self.model.apply({"params": p},
                                                  batch["obs"])
                logp_all = jax.nn.log_softmax(logits)
                logp = jnp.take_along_axis(
                    logp_all, batch["actions"][:, None], axis=1)[:, 0]
                ratio = jnp.exp(logp - batch["logp_old"])
                adv = batch["advantages"]
                surr = jnp.minimum(
                    ratio * adv,
                    jnp.clip(ratio, 1 - self.clip, 1 + self.clip) * adv)
                policy_loss = -jnp.mean(surr)
                vf_loss = jnp.mean((values - batch["returns"]) ** 2)
                entropy = -jnp.mean(
                    jnp.sum(jnp.exp(logp_all) * logp_all, axis=-1))
                total = policy_loss + self.vf_coeff * vf_loss \
                    - self.ent_coeff * entropy
                return total, (policy_loss, vf_loss, entropy)

            (total, (pl, vl, ent)), grads = \
                jax.value_and_grad(loss_fn, has_aux=True)(params)
            updates, opt_state = self.tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, {
                "total_loss": total, "policy_loss": pl, "vf_loss": vl,
                "entropy": ent}
        self._update = _update

    def update(self, batch: Dict[str, np.ndarray],
               num_epochs: int = 4, minibatch_size: int = 512,
               seed: int = 0) -> Dict[str, float]:
        """Minibatch SGD over one flattened sample batch
        (reference: Learner.update minibatch iteration)."""
        n = batch["obs"].shape[0]
        adv = batch["advantages"]
        batch = dict(batch)
        batch["advantages"] = (adv - adv.mean()) / (adv.std() + 1e-8)
        rng = np.random.RandomState(seed)
        metrics = {}
        for _epoch in range(num_epochs):
            order = rng.permutation(n)
            for start in range(0, n, minibatch_size):
                idx = order[start:start + minibatch_size]
                mb = {k: jnp.asarray(v[idx]) for k, v in batch.items()}
                self.params, self.opt_state, metrics = self._update(
                    self.params, self.opt_state, mb)
        return {k: float(v) for k, v in metrics.items()}

    def get_weights(self):
        return jax.device_get(self.params)
