"""Policy/value networks (reference: rllib/core/rl_module/ — the RLModule
holds pi and vf; here one flax module with two heads)."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class ActorCriticMLP(nn.Module):
    """Tanh MLP torso with categorical policy + value heads
    (the reference's default fcnet for discrete control)."""
    num_actions: int
    hidden: Sequence[int] = (64, 64)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(self.dtype)
        for width in self.hidden:
            x = nn.tanh(nn.Dense(width, dtype=self.dtype)(x))
        logits = nn.Dense(self.num_actions, dtype=self.dtype,
                          kernel_init=nn.initializers.orthogonal(0.01))(x)
        value = nn.Dense(1, dtype=self.dtype,
                         kernel_init=nn.initializers.orthogonal(1.0))(x)
        return logits, jnp.squeeze(value, -1)


class QMLP(nn.Module):
    """Q-network for DQN (reference: dqn's default fcnet head): ReLU MLP
    torso, one Q value per action."""
    num_actions: int
    hidden: Sequence[int] = (128, 128)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(self.dtype)
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        return nn.Dense(self.num_actions, dtype=self.dtype,
                        kernel_init=nn.initializers.orthogonal(1.0))(x)


def sample_action(params, model, obs, rng):
    logits, value = model.apply({"params": params}, obs)
    action = jax.random.categorical(rng, logits)
    logp = jax.nn.log_softmax(logits)[
        jnp.arange(logits.shape[0]), action]
    return action, logp, value
