"""Policy/value networks (reference: rllib/core/rl_module/ — the RLModule
holds pi and vf; here one flax module with two heads)."""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp


class ActorCriticMLP(nn.Module):
    """Tanh MLP torso with categorical policy + value heads
    (the reference's default fcnet for discrete control)."""
    num_actions: int
    hidden: Sequence[int] = (64, 64)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(self.dtype)
        for width in self.hidden:
            x = nn.tanh(nn.Dense(width, dtype=self.dtype)(x))
        logits = nn.Dense(self.num_actions, dtype=self.dtype,
                          kernel_init=nn.initializers.orthogonal(0.01))(x)
        value = nn.Dense(1, dtype=self.dtype,
                         kernel_init=nn.initializers.orthogonal(1.0))(x)
        return logits, jnp.squeeze(value, -1)


class QMLP(nn.Module):
    """Q-network for DQN (reference: dqn's default fcnet head): ReLU MLP
    torso, one Q value per action."""
    num_actions: int
    hidden: Sequence[int] = (128, 128)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(self.dtype)
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        return nn.Dense(self.num_actions, dtype=self.dtype,
                        kernel_init=nn.initializers.orthogonal(1.0))(x)


def sample_action(params, model, obs, rng):
    logits, value = model.apply({"params": params}, obs)
    action = jax.random.categorical(rng, logits)
    logp = jax.nn.log_softmax(logits)[
        jnp.arange(logits.shape[0]), action]
    return action, logp, value


class SquashedGaussianPolicy(nn.Module):
    """Continuous-control policy: ReLU torso -> (mean, log_std), actions
    tanh-squashed to [-1, 1] (reference: SAC's default policy head in
    rllib/algorithms/sac/ — torch SACTorchModel; env-side scaling to the
    action bounds happens in the runner)."""
    act_dim: int
    hidden: Sequence[int] = (256, 256)
    log_std_min: float = -20.0
    log_std_max: float = 2.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs):
        x = obs.astype(self.dtype)
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        mean = nn.Dense(self.act_dim, dtype=self.dtype,
                        kernel_init=nn.initializers.orthogonal(0.01))(x)
        log_std = nn.Dense(
            self.act_dim, dtype=self.dtype,
            kernel_init=nn.initializers.orthogonal(0.01))(x)
        log_std = jnp.clip(log_std, self.log_std_min, self.log_std_max)
        return mean, log_std


def squashed_sample(mean, log_std, rng):
    """Reparameterized tanh-Gaussian sample with its log-prob (the
    change-of-variables correction summed over action dims)."""
    std = jnp.exp(log_std)
    eps = jax.random.normal(rng, mean.shape)
    pre = mean + std * eps
    action = jnp.tanh(pre)
    logp = (-0.5 * (eps ** 2 + 2 * log_std + jnp.log(2 * jnp.pi))
            ).sum(-1)
    # log det of tanh: sum log(1 - tanh^2), in the numerically stable
    # 2*(log2 - x - softplus(-2x)) form
    logp -= (2.0 * (jnp.log(2.0) - pre -
                    jax.nn.softplus(-2.0 * pre))).sum(-1)
    return action, logp


class ContinuousQMLP(nn.Module):
    """Q(s, a) for continuous actions: ReLU MLP over the concatenation
    (reference: SAC's twin Q heads)."""
    hidden: Sequence[int] = (256, 256)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, obs, action):
        x = jnp.concatenate(
            [obs.astype(self.dtype), action.astype(self.dtype)], axis=-1)
        for width in self.hidden:
            x = nn.relu(nn.Dense(width, dtype=self.dtype)(x))
        q = nn.Dense(1, dtype=self.dtype,
                     kernel_init=nn.initializers.orthogonal(1.0))(x)
        return jnp.squeeze(q, -1)
