"""Multi-agent RL: env API, runner, and PPO trainer
(reference: rllib/env/multi_agent_env.py — MultiAgentEnv + make_multi_agent
:379; rllib/env/multi_agent_env_runner.py:68 MultiAgentEnvRunner;
policy mapping via config.multi_agent(policy_mapping_fn=...)).

TPU-first shape: each runner steps N independent copies of the
multi-agent env and flattens (env, agent) slots into ONE batched policy
forward per POLICY (shared-policy agents ride the same jitted call);
fragments come back keyed by policy id so each policy's PPOLearner does
its usual GAE + clipped-surrogate update."""

from __future__ import annotations

import logging
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

logger = logging.getLogger(__name__)


class MultiAgentEnv:
    """Dict-keyed env protocol (reference: multi_agent_env.py).

    reset() -> (obs_dict, info_dict)
    step(action_dict) -> (obs, rewards, terminateds, truncateds, infos)
    with per-agent keys; terminateds/truncateds carry "__all__"."""

    agents: List[str] = []

    def reset(self, seed: Optional[int] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError


def make_multi_agent(env_name: str, num_agents: int = 2):
    """N independent copies of a gym env as one MultiAgentEnv, agents
    "agent_0".."agent_{N-1}" (reference: make_multi_agent :379 — the
    standard way to lift a single-agent env for multi-agent tests).
    Sub-envs auto-reset individually on done (same-step semantics: the
    done step carries the real final reward; the returned obs is the
    reset obs)."""
    import gymnasium as gym

    class _IndependentMultiAgent(MultiAgentEnv):
        def __init__(self, seed: int = 0):
            self.agents = [f"agent_{i}" for i in range(num_agents)]
            self._envs = {a: gym.make(env_name) for a in self.agents}
            self._seed = seed

        @property
        def observation_space(self):
            return next(iter(self._envs.values())).observation_space

        @property
        def action_space(self):
            return next(iter(self._envs.values())).action_space

        def reset(self, seed: Optional[int] = None):
            seed = self._seed if seed is None else seed
            obs, infos = {}, {}
            for i, (agent, env) in enumerate(self._envs.items()):
                obs[agent], infos[agent] = env.reset(seed=seed + i)
            return obs, infos

        def step(self, action_dict):
            obs, rewards, terms, truncs, infos = {}, {}, {}, {}, {}
            for agent, env in self._envs.items():
                o, r, te, tr, info = env.step(action_dict[agent])
                if te or tr:
                    info = dict(info, final_obs=o)
                    o, _ = env.reset()
                obs[agent] = o
                rewards[agent] = r
                terms[agent] = te
                truncs[agent] = tr
                infos[agent] = info
            terms["__all__"] = all(terms[a] for a in self.agents)
            truncs["__all__"] = all(truncs[a] for a in self.agents)
            return obs, rewards, terms, truncs, infos

    return _IndependentMultiAgent


class MultiAgentEnvRunner:
    """Samples PPO fragments from N copies of a multi-agent env, one
    batched policy forward per policy id per step (reference:
    multi_agent_env_runner.py:68; connector-style slot flattening)."""

    def __init__(self, env_maker: Callable[..., MultiAgentEnv],
                 num_envs: int, fragment_len: int,
                 policy_mapping: Dict[str, str],
                 model_configs: Dict[str, Dict[str, Any]],
                 num_actions: int, seed: int = 0, gamma: float = 0.99):
        import jax

        from .models import ActorCriticMLP, sample_action

        self._envs = [env_maker(seed=seed + 97 * i)
                      for i in range(num_envs)]
        self._T = fragment_len
        self._gamma = gamma
        self._mapping = dict(policy_mapping)
        agents = self._envs[0].agents
        self._agents = list(agents)
        # slot = (env_idx, agent); grouped per policy for batched forwards
        self._slots: Dict[str, List[Tuple[int, str]]] = {}
        for e in range(num_envs):
            for agent in agents:
                pid = self._mapping[agent]
                self._slots.setdefault(pid, []).append((e, agent))
        self._models = {
            pid: ActorCriticMLP(
                num_actions=num_actions,
                hidden=tuple(cfg.get("hidden", (64, 64))))
            for pid, cfg in model_configs.items()}
        self._sample_fns = {
            pid: jax.jit(lambda p, obs, rng, m=model:
                         sample_action(p, m, obs, rng))
            for pid, model in self._models.items()}
        self._rng = jax.random.PRNGKey(seed)
        self._params: Dict[str, Any] = {}
        self._obs: Dict[Tuple[int, str], np.ndarray] = {}
        for e, env in enumerate(self._envs):
            obs, _ = env.reset(seed=seed + 31 * e)
            for agent, o in obs.items():
                self._obs[(e, agent)] = np.asarray(o, np.float32)
        self._episode_returns = {k: 0.0 for k in self._obs}
        self._completed: Dict[str, List[float]] = \
            {pid: [] for pid in self._slots}

    def observation_shape(self):
        return next(iter(self._obs.values())).shape

    def set_weights(self, params_by_policy: Dict[str, Any]) -> bool:
        self._params.update(params_by_policy)
        return True

    def _forward(self, pid: str, obs: np.ndarray):
        import jax
        self._rng, key = jax.random.split(self._rng)
        action, logp, value = self._sample_fns[pid](
            self._params[pid], obs, key)
        return (np.asarray(action), np.asarray(logp),
                np.asarray(value))

    def sample(self) -> Dict[str, Dict[str, np.ndarray]]:
        """Per-policy PPO fragments: {policy_id: {obs [T, M, ...],
        actions, logp, values, rewards, dones [T, M],
        bootstrap_value [M], episode_returns}}."""
        assert self._params, "set_weights first"
        T = self._T
        out: Dict[str, Dict[str, np.ndarray]] = {}
        buffers = {}
        for pid, slots in self._slots.items():
            M = len(slots)
            obs_shape = self.observation_shape()
            buffers[pid] = {
                "obs": np.empty((T, M) + obs_shape, np.float32),
                "actions": np.empty((T, M), np.int32),
                "logp": np.empty((T, M), np.float32),
                "values": np.empty((T, M), np.float32),
                "rewards": np.empty((T, M), np.float32),
                "dones": np.empty((T, M), np.float32),
            }
        for t in range(T):
            actions_by_env: Dict[int, Dict[str, Any]] = {}
            per_policy = {}
            for pid, slots in self._slots.items():
                obs = np.stack([self._obs[s] for s in slots])
                action, logp, value = self._forward(pid, obs)
                per_policy[pid] = (obs, action, logp, value)
                for j, (e, agent) in enumerate(slots):
                    actions_by_env.setdefault(e, {})[agent] = \
                        int(action[j])
            step_results = {}
            for e, env in enumerate(self._envs):
                step_results[e] = env.step(actions_by_env[e])
                terms, truncs = step_results[e][2], step_results[e][3]
                if terms.get("__all__") or truncs.get("__all__"):
                    # Episode over for the whole env: reset it so the
                    # next step never advances a finished episode (a
                    # protocol env need not auto-reset; make_multi_agent
                    # sub-envs do, and re-resetting them is just a
                    # fresh episode).
                    fresh, _ = env.reset()
                    nobs = dict(step_results[e][0])
                    nobs.update({a: fresh[a] for a in fresh})
                    step_results[e] = (nobs,) + step_results[e][1:]
            for pid, slots in self._slots.items():
                obs, action, logp, value = per_policy[pid]
                buf = buffers[pid]
                buf["obs"][t] = obs
                buf["actions"][t] = action
                buf["logp"][t] = logp
                buf["values"][t] = value
                for j, (e, agent) in enumerate(slots):
                    nobs, rewards, terms, truncs, infos = step_results[e]
                    reward = float(rewards[agent])
                    done = bool(terms[agent] or truncs[agent])
                    if truncs[agent] and not terms[agent]:
                        # bootstrap time-limit truncations with
                        # V(final_obs) (mirrors the single-agent runner)
                        final = infos[agent].get("final_obs")
                        if final is not None:
                            _a, _l, fval = self._forward(
                                pid, np.asarray(final, np.float32)[None])
                            reward += self._gamma * float(fval[0])
                    buf["rewards"][t, j] = reward
                    buf["dones"][t, j] = float(done)
                    self._episode_returns[(e, agent)] += float(
                        rewards[agent])
                    if done:
                        self._completed[pid].append(
                            self._episode_returns[(e, agent)])
                        self._episode_returns[(e, agent)] = 0.0
                    self._obs[(e, agent)] = np.asarray(
                        nobs[agent], np.float32)
        for pid, slots in self._slots.items():
            obs = np.stack([self._obs[s] for s in slots])
            _a, _l, boot = self._forward(pid, obs)
            returns = self._completed[pid]
            self._completed[pid] = []
            out[pid] = dict(buffers[pid],
                            bootstrap_value=np.asarray(boot, np.float32),
                            episode_returns=np.asarray(returns,
                                                       np.float64))
        return out


class MultiAgentPPOConfig:
    """Builder config for multi-agent PPO (reference: AlgorithmConfig
    .multi_agent(policies=..., policy_mapping_fn=...))."""

    def __init__(self):
        self.env_name = "CartPole-v1"
        self.num_agents = 2
        self.num_env_runners = 2
        self.num_envs_per_env_runner = 4
        self.rollout_fragment_length = 64
        # policy_id -> model config; agents map via policy_mapping
        self.policies: Dict[str, Dict[str, Any]] = \
            {"shared": {"hidden": (64, 64)}}
        self.policy_mapping: Optional[Dict[str, str]] = None  # all->shared
        self.lr = 3e-4
        self.gamma = 0.99
        self.lambda_ = 0.95
        self.clip_param = 0.2
        self.vf_coeff = 0.5
        self.entropy_coeff = 0.01
        self.num_epochs = 6
        self.minibatch_size = 512
        self.grad_clip = 0.5
        self.seed = 0

    def environment(self, env: str) -> "MultiAgentPPOConfig":
        self.env_name = env
        return self

    def multi_agent(self, num_agents: Optional[int] = None,
                    policies: Optional[Dict[str, Dict]] = None,
                    policy_mapping: Optional[Dict[str, str]] = None
                    ) -> "MultiAgentPPOConfig":
        if num_agents is not None:
            self.num_agents = num_agents
        if policies is not None:
            self.policies = policies
        if policy_mapping is not None:
            self.policy_mapping = policy_mapping
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "MultiAgentPPOConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "MultiAgentPPOConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "MultiAgentPPO":
        return MultiAgentPPO(self)


class MultiAgentPPO:
    """One PPOLearner per policy over multi-agent fragments (reference:
    the learner-group keyed by module id in multi-agent setups)."""

    def __init__(self, config: MultiAgentPPOConfig):
        import gymnasium as gym

        import ray_tpu

        from .learner import PPOLearner

        self.config = config
        agents = [f"agent_{i}" for i in range(config.num_agents)]
        mapping = config.policy_mapping or \
            {a: next(iter(config.policies)) for a in agents}
        self._mapping = mapping
        probe = gym.make(config.env_name)
        num_actions = int(probe.action_space.n)
        obs_shape = tuple(probe.observation_space.shape)
        probe.close()
        maker = make_multi_agent(config.env_name, config.num_agents)
        runner_cls = ray_tpu.remote(MultiAgentEnvRunner)
        self._runners = [
            runner_cls.options(num_cpus=1).remote(
                maker, config.num_envs_per_env_runner,
                config.rollout_fragment_length, mapping,
                dict(config.policies), num_actions,
                seed=config.seed + 1000 * (i + 1), gamma=config.gamma)
            for i in range(config.num_env_runners)]
        self._learners = {
            pid: PPOLearner(
                obs_shape=obs_shape, num_actions=num_actions,
                model_config=dict(model_config), lr=config.lr,
                clip_param=config.clip_param, vf_coeff=config.vf_coeff,
                entropy_coeff=config.entropy_coeff,
                grad_clip=config.grad_clip,
                # stable per-policy seed: hash() is randomized per
                # process (PYTHONHASHSEED) and would break seeded repro
                seed=config.seed + zlib.crc32(pid.encode()) % 1000)
            for pid, model_config in config.policies.items()}
        self._broadcast_weights()
        self._iteration = 0
        self._recent: Dict[str, List[float]] = \
            {pid: [] for pid in self._learners}

    def _broadcast_weights(self):
        import ray_tpu
        weights = {pid: learner.get_weights()
                   for pid, learner in self._learners.items()}
        ray_tpu.get([r.set_weights.remote(weights)
                     for r in self._runners], timeout=120)

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        from .learner import compute_gae

        config = self.config
        t0 = time.perf_counter()
        fragments = ray_tpu.get(
            [r.sample.remote() for r in self._runners], timeout=300)
        sample_time = time.perf_counter() - t0
        metrics: Dict[str, Any] = {}
        steps = 0
        t1 = time.perf_counter()
        for pid, learner in self._learners.items():
            obs, actions, logp, adv, rets = [], [], [], [], []
            for frags in fragments:
                frag = frags.get(pid)
                if frag is None:
                    continue
                a, r = compute_gae(
                    frag["rewards"], frag["values"], frag["dones"],
                    frag["bootstrap_value"], config.gamma,
                    config.lambda_)
                obs.append(frag["obs"].reshape(
                    -1, *frag["obs"].shape[2:]))
                actions.append(frag["actions"].reshape(-1))
                logp.append(frag["logp"].reshape(-1))
                adv.append(a.reshape(-1))
                rets.append(r.reshape(-1))
                self._recent[pid].extend(
                    frag["episode_returns"].tolist())
            if not obs:
                continue
            batch = {"obs": np.concatenate(obs),
                     "actions": np.concatenate(actions),
                     "logp_old": np.concatenate(logp),
                     "advantages": np.concatenate(adv),
                     "returns": np.concatenate(rets)}
            steps += len(batch["obs"])
            learner_metrics = learner.update(
                batch, num_epochs=config.num_epochs,
                minibatch_size=config.minibatch_size,
                seed=config.seed + self._iteration)
            self._recent[pid] = self._recent[pid][-100:]
            metrics[f"{pid}/episode_return_mean"] = float(
                np.mean(self._recent[pid])) if self._recent[pid] \
                else float("nan")
            for key, value in learner_metrics.items():
                metrics[f"{pid}/{key}"] = value
        learn_time = time.perf_counter() - t1
        self._broadcast_weights()
        self._iteration += 1
        all_returns = [r for rs in self._recent.values() for r in rs]
        return {
            "training_iteration": self._iteration,
            "num_env_steps_sampled": steps,
            "episode_return_mean": float(np.mean(all_returns))
            if all_returns else float("nan"),
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
            **metrics,
        }

    def stop(self):
        import ray_tpu
        for runner in self._runners:
            try:
                ray_tpu.kill(runner)
            except Exception:  # noqa: BLE001
                logger.debug("runner kill at stop failed", exc_info=True)
