"""Offline RL: episode recording via ray_tpu.data + behavior cloning
(reference: rllib/offline/ — offline data I/O feeding offline algorithms;
rllib/algorithms/bc/ — BC as the minimal offline learner).

Episodes are recorded into a Dataset (the Data↔RLlib bridge the
reference builds with offline_data.py over ray.data), and BC trains a
categorical policy by supervised cross-entropy over (obs, action) — the
acceptance test recovers a scripted expert from its own demonstrations."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


def record_episodes(env_name: str, policy_fn: Callable[[np.ndarray], int],
                    num_episodes: int = 20, seed: int = 0,
                    parallelism: int = 2):
    """Roll out `policy_fn` and return a Dataset of transitions
    ({obs, action, reward, done, episode}); recording runs as remote
    tasks (reference: offline single-agent episode recording to
    ray.data)."""
    import ray_tpu
    from ray_tpu import data as rd

    @ray_tpu.remote(num_cpus=1)
    def rollout(ep_start: int, n: int):
        import gymnasium as gym
        env = gym.make(env_name)
        rows = []
        for e in range(ep_start, ep_start + n):
            obs, _ = env.reset(seed=seed + e)
            done = False
            while not done:
                action = int(policy_fn(np.asarray(obs, np.float32)))
                next_obs, reward, terminated, truncated, _ = \
                    env.step(action)
                rows.append({"obs": np.asarray(obs, np.float32),
                             "action": action,
                             "reward": float(reward),
                             "done": bool(terminated or truncated),
                             "episode": e})
                obs = next_obs
                done = terminated or truncated
        env.close()
        return rows

    per = max(1, -(-num_episodes // parallelism))
    refs = [rollout.remote(i * per, min(per, num_episodes - i * per))
            for i in range(parallelism) if i * per < num_episodes]
    all_rows: List[dict] = []
    for rows in ray_tpu.get(refs, timeout=600):
        all_rows.extend(rows)
    return rd.from_items(all_rows)




def group_episodes(rows) -> Dict[int, List[dict]]:
    """Rows-per-episode in recorded step order (the layout
    record_episodes writes; shared by CQL/MARWIL dataset loading)."""
    by_ep: Dict[int, List[dict]] = {}
    for r in rows:
        by_ep.setdefault(int(r["episode"]), []).append(r)
    return by_ep


def greedy_rollout_score(env_name: str, act_fn, num_episodes: int,
                         seed_base: int) -> float:
    """Mean return of `act_fn(obs)->action` over fresh episodes — the
    shared offline-algorithm evaluation (BC/CQL/MARWIL)."""
    import gymnasium as gym
    env = gym.make(env_name)
    total = 0.0
    for ep in range(num_episodes):
        obs, _ = env.reset(seed=seed_base + ep)
        done = False
        while not done:
            action = int(act_fn(np.asarray(obs, np.float32)))
            obs, reward, terminated, truncated, _ = env.step(action)
            total += reward
            done = terminated or truncated
    env.close()
    return total / num_episodes


class BCConfig:
    def __init__(self):
        self.env_name = "CartPole-v1"
        self.lr = 1e-3
        self.batch_size = 256
        self.num_epochs = 20
        self.model = {"hidden": (64, 64)}
        self.seed = 0

    def environment(self, env: str) -> "BCConfig":
        self.env_name = env
        return self

    def training(self, **kwargs) -> "BCConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "BC":
        return BC(self)


class BC:
    """Behavior cloning over a transitions Dataset (reference:
    rllib/algorithms/bc/bc.py — the policy head of the RLModule trained
    with negative log-likelihood of the dataset actions)."""

    def __init__(self, config: BCConfig):
        self.config = config
        self._params = None
        self._model = None

    def fit(self, dataset) -> Dict[str, Any]:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from .models import ActorCriticMLP

        c = self.config
        probe = gym.make(c.env_name)
        num_actions = int(probe.action_space.n)
        probe.close()
        rows = dataset.take_all()
        obs = jnp.asarray(np.stack([np.asarray(r["obs"], np.float32)
                                    for r in rows]))
        actions = jnp.asarray(np.asarray([r["action"] for r in rows],
                                         np.int32))
        model = ActorCriticMLP(num_actions=num_actions,
                               hidden=tuple(c.model.get("hidden",
                                                        (64, 64))))
        rng = jax.random.PRNGKey(c.seed)
        params = model.init(rng, obs[:1])["params"]
        tx = optax.adam(c.lr)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, batch_obs, batch_actions):
            def loss_fn(p):
                logits, _ = model.apply({"params": p}, batch_obs)
                logp = jax.nn.log_softmax(logits)
                nll = -jnp.take_along_axis(
                    logp, batch_actions[:, None], axis=-1)[:, 0]
                return nll.mean()
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        n = obs.shape[0]
        key = jax.random.PRNGKey(c.seed + 1)
        loss = jnp.inf
        for _epoch in range(c.num_epochs):
            key, sub = jax.random.split(key)
            perm = jax.random.permutation(sub, n)
            for start in range(0, n - c.batch_size + 1, c.batch_size):
                idx = perm[start:start + c.batch_size]
                params, opt_state, loss = step(
                    params, opt_state, obs[idx], actions[idx])
        self._params = params
        self._model = model
        return {"final_loss": float(loss), "num_transitions": int(n)}

    def evaluate(self, num_episodes: int = 5) -> float:
        import jax
        import jax.numpy as jnp
        assert self._params is not None, "fit() first"
        model, params = self._model, self._params

        @jax.jit
        def act(obs):
            logits, _ = model.apply({"params": params}, obs[None])
            return jnp.argmax(logits, axis=-1)[0]

        return greedy_rollout_score(self.config.env_name, act,
                                    num_episodes, seed_base=20_000)


class MARWILConfig:
    """(reference: rllib/algorithms/marwil/marwil.py MARWILConfig :43 —
    beta, moving_average_sqd_adv_norm_update_rate/_start; beta=0
    degenerates to BC :78,227)."""

    def __init__(self):
        self.env_name = "CartPole-v1"
        self.lr = 1e-3
        self.beta = 1.0
        self.gamma = 0.99
        self.vf_coeff = 1.0
        self.grad_clip = 40.0
        self.ma_adv_norm_update_rate = 1e-2
        self.ma_adv_norm_start = 1.0
        self.batch_size = 256
        self.num_epochs = 20
        self.model = {"hidden": (64, 64)}
        self.seed = 0

    def environment(self, env: str) -> "MARWILConfig":
        self.env_name = env
        return self

    def training(self, **kwargs) -> "MARWILConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "MARWIL":
        return MARWIL(self)


class MARWIL:
    """Monotonic advantage re-weighted imitation learning (reference:
    rllib/algorithms/marwil — the loss of marwil_torch_learner: value
    head regresses the Monte-Carlo return, the policy NLL of each
    dataset action is weighted by exp(beta * advantage / c) with c the
    moving RMS of advantages; beta=0 IS behavior cloning). Offline data
    comes from the same transitions Dataset as BC/CQL; advantages use
    discounted MC returns computed per episode at load time."""

    def __init__(self, config: MARWILConfig):
        self.config = config
        self._params = None
        self._model = None

    def fit(self, dataset) -> Dict[str, Any]:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from .models import ActorCriticMLP

        c = self.config
        probe = gym.make(c.env_name)
        num_actions = int(probe.action_space.n)
        probe.close()

        by_ep = group_episodes(dataset.take_all())
        obs_l, act_l, ret_l = [], [], []
        for ep_rows in by_ep.values():
            ret = 0.0
            returns = []
            for r in reversed(ep_rows):
                ret = float(r["reward"]) + c.gamma * ret  # host-sync ok: host JSON row
                returns.append(ret)
            returns.reverse()
            for r, g in zip(ep_rows, returns):
                obs_l.append(np.asarray(r["obs"], np.float32))  # host-sync ok: host JSON row
                act_l.append(int(r["action"]))  # host-sync ok: host JSON row
                ret_l.append(g)
        obs = jnp.asarray(np.stack(obs_l))
        actions = jnp.asarray(np.asarray(act_l, np.int32))
        ret_arr = np.asarray(ret_l, np.float32)
        # Standardize MC returns: raw CartPole returns are O(100), and
        # the value regression through the SHARED torso would drown the
        # weighted-NLL gradient (the reference's torch learner leans on
        # grad-clip + GAE value bootstrap instead; with plain MC targets
        # standardization is the stable equivalent — advantages and the
        # moving RMS normalizer c then live at O(1)).
        ret_arr = (ret_arr - ret_arr.mean()) / (ret_arr.std() + 1e-6)
        returns = jnp.asarray(ret_arr)

        model = ActorCriticMLP(num_actions=num_actions,
                               hidden=tuple(c.model.get("hidden",
                                                        (64, 64))))
        params = model.init(jax.random.PRNGKey(c.seed), obs[:1])["params"]
        tx = optax.chain(optax.clip_by_global_norm(c.grad_clip),
                         optax.adam(c.lr))
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, ma_sq, idx):
            b_obs, b_act, b_ret = obs[idx], actions[idx], returns[idx]  # jit capture ok: trace-constant dataset tensors

            def loss_fn(p):
                logits, values = model.apply({"params": p}, b_obs)
                logp = jax.nn.log_softmax(logits)
                nll = -jnp.take_along_axis(
                    logp, b_act[:, None], axis=-1)[:, 0]
                adv = b_ret - values
                vf_loss = 0.5 * jnp.mean(adv ** 2)
                # moving RMS normalizer c (reference: update in the
                # learner with rate * (mean(adv^2) - c^2))
                new_ma = ma_sq + c.ma_adv_norm_update_rate * (
                    jnp.mean(jax.lax.stop_gradient(adv) ** 2) - ma_sq)
                weight = jnp.exp(c.beta * jax.lax.stop_gradient(adv)
                                 / jnp.sqrt(new_ma + 1e-8))
                # clip the exploding exponential (reference clips the
                # weighted loss implicitly via grad clip; explicit here)
                weight = jnp.minimum(weight, 20.0)
                policy_loss = jnp.mean(weight * nll)
                return policy_loss + c.vf_coeff * vf_loss, new_ma

            (loss, new_ma), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, \
                new_ma, loss

        n = obs.shape[0]
        key = jax.random.PRNGKey(c.seed + 1)
        ma_sq = jnp.float32(c.ma_adv_norm_start)
        loss = jnp.inf
        for _epoch in range(c.num_epochs):
            key, sub = jax.random.split(key)
            perm = jax.random.permutation(sub, n)
            for start in range(0, n - c.batch_size + 1, c.batch_size):
                idx = perm[start:start + c.batch_size]
                params, opt_state, ma_sq, loss = step(
                    params, opt_state, ma_sq, idx)
        self._params = params
        self._model = model
        return {"final_loss": float(loss), "num_transitions": int(n),
                "ma_adv_sq_norm": float(ma_sq)}

    def evaluate(self, num_episodes: int = 5) -> float:
        import jax
        import jax.numpy as jnp
        assert self._params is not None, "fit() first"
        model, params = self._model, self._params

        @jax.jit
        def act(obs):
            logits, _ = model.apply({"params": params}, obs[None])
            return jnp.argmax(logits, axis=-1)[0]

        return greedy_rollout_score(self.config.env_name, act,
                                    num_episodes, seed_base=40_000)
