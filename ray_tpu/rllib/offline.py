"""Offline RL: episode recording via ray_tpu.data + behavior cloning
(reference: rllib/offline/ — offline data I/O feeding offline algorithms;
rllib/algorithms/bc/ — BC as the minimal offline learner).

Episodes are recorded into a Dataset (the Data↔RLlib bridge the
reference builds with offline_data.py over ray.data), and BC trains a
categorical policy by supervised cross-entropy over (obs, action) — the
acceptance test recovers a scripted expert from its own demonstrations."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np


def record_episodes(env_name: str, policy_fn: Callable[[np.ndarray], int],
                    num_episodes: int = 20, seed: int = 0,
                    parallelism: int = 2):
    """Roll out `policy_fn` and return a Dataset of transitions
    ({obs, action, reward, done, episode}); recording runs as remote
    tasks (reference: offline single-agent episode recording to
    ray.data)."""
    import ray_tpu
    from ray_tpu import data as rd

    @ray_tpu.remote(num_cpus=1)
    def rollout(ep_start: int, n: int):
        import gymnasium as gym
        env = gym.make(env_name)
        rows = []
        for e in range(ep_start, ep_start + n):
            obs, _ = env.reset(seed=seed + e)
            done = False
            while not done:
                action = int(policy_fn(np.asarray(obs, np.float32)))
                next_obs, reward, terminated, truncated, _ = \
                    env.step(action)
                rows.append({"obs": np.asarray(obs, np.float32),
                             "action": action,
                             "reward": float(reward),
                             "done": bool(terminated or truncated),
                             "episode": e})
                obs = next_obs
                done = terminated or truncated
        env.close()
        return rows

    per = max(1, -(-num_episodes // parallelism))
    refs = [rollout.remote(i * per, min(per, num_episodes - i * per))
            for i in range(parallelism) if i * per < num_episodes]
    all_rows: List[dict] = []
    for rows in ray_tpu.get(refs, timeout=600):
        all_rows.extend(rows)
    return rd.from_items(all_rows)


class BCConfig:
    def __init__(self):
        self.env_name = "CartPole-v1"
        self.lr = 1e-3
        self.batch_size = 256
        self.num_epochs = 20
        self.model = {"hidden": (64, 64)}
        self.seed = 0

    def environment(self, env: str) -> "BCConfig":
        self.env_name = env
        return self

    def training(self, **kwargs) -> "BCConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "BC":
        return BC(self)


class BC:
    """Behavior cloning over a transitions Dataset (reference:
    rllib/algorithms/bc/bc.py — the policy head of the RLModule trained
    with negative log-likelihood of the dataset actions)."""

    def __init__(self, config: BCConfig):
        self.config = config
        self._params = None
        self._model = None

    def fit(self, dataset) -> Dict[str, Any]:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        import optax

        from .models import ActorCriticMLP

        c = self.config
        probe = gym.make(c.env_name)
        num_actions = int(probe.action_space.n)
        probe.close()
        rows = dataset.take_all()
        obs = jnp.asarray(np.stack([np.asarray(r["obs"], np.float32)
                                    for r in rows]))
        actions = jnp.asarray(np.asarray([r["action"] for r in rows],
                                         np.int32))
        model = ActorCriticMLP(num_actions=num_actions,
                               hidden=tuple(c.model.get("hidden",
                                                        (64, 64))))
        rng = jax.random.PRNGKey(c.seed)
        params = model.init(rng, obs[:1])["params"]
        tx = optax.adam(c.lr)
        opt_state = tx.init(params)

        @jax.jit
        def step(params, opt_state, batch_obs, batch_actions):
            def loss_fn(p):
                logits, _ = model.apply({"params": p}, batch_obs)
                logp = jax.nn.log_softmax(logits)
                nll = -jnp.take_along_axis(
                    logp, batch_actions[:, None], axis=-1)[:, 0]
                return nll.mean()
            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        n = obs.shape[0]
        key = jax.random.PRNGKey(c.seed + 1)
        loss = jnp.inf
        for _epoch in range(c.num_epochs):
            key, sub = jax.random.split(key)
            perm = jax.random.permutation(sub, n)
            for start in range(0, n - c.batch_size + 1, c.batch_size):
                idx = perm[start:start + c.batch_size]
                params, opt_state, loss = step(
                    params, opt_state, obs[idx], actions[idx])
        self._params = params
        self._model = model
        return {"final_loss": float(loss), "num_transitions": int(n)}

    def evaluate(self, num_episodes: int = 5) -> float:
        import gymnasium as gym
        import jax
        import jax.numpy as jnp
        assert self._params is not None, "fit() first"
        env = gym.make(self.config.env_name)
        model, params = self._model, self._params

        @jax.jit
        def act(obs):
            logits, _ = model.apply({"params": params}, obs[None])
            return jnp.argmax(logits, axis=-1)[0]

        total = 0.0
        for ep in range(num_episodes):
            obs, _ = env.reset(seed=20_000 + ep)
            done = False
            while not done:
                action = int(act(jnp.asarray(obs, jnp.float32)))
                obs, reward, terminated, truncated, _ = env.step(action)
                total += reward
                done = terminated or truncated
        env.close()
        return total / num_episodes
