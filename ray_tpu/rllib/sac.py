"""SAC: soft actor-critic for continuous control
(reference: rllib/algorithms/sac/sac.py — SACConfig :60, built on DQN's
replay machinery :560; twin Q + target nets, tanh-Gaussian policy,
auto-tuned entropy temperature).

Reuses the DQN vertical's ReplayBufferActor shards (continuous action
layout) and its sample-ratio control; the whole SAC update — twin-Q
targets, reparameterized policy gradient, alpha adaptation, polyak —
is ONE jitted XLA program."""

from __future__ import annotations

import logging
import time
from typing import Any, Dict, List, Optional

import numpy as np

logger = logging.getLogger(__name__)

from .dqn import ReplayBufferActor


class SACConfig:
    """Builder-style config (reference: sac.py SACConfig :60)."""

    def __init__(self):
        self.env_name = "Pendulum-v1"
        self.num_env_runners = 1
        self.num_envs_per_env_runner = 8
        self.rollout_fragment_length = 16
        self.buffer_capacity = 100_000
        self.num_replay_shards = 1
        self.learning_starts = 1_500
        self.batch_size = 256
        self.actor_lr = 3e-4
        self.critic_lr = 3e-4
        self.alpha_lr = 3e-4
        self.gamma = 0.99
        self.tau = 0.005                  # polyak coefficient
        self.initial_alpha = 1.0
        self.target_entropy: Optional[float] = None  # None = -act_dim
        self.n_step = 1
        # trained transitions per sampled transition: 256 at batch 256
        # = one update per env step, the SAC paper's regime
        self.training_intensity = 256.0
        self.grad_clip = 40.0
        self.model = {"hidden": (256, 256)}
        self.seed = 0

    def environment(self, env: str) -> "SACConfig":
        self.env_name = env
        return self

    def env_runners(self, num_env_runners: Optional[int] = None,
                    num_envs_per_env_runner: Optional[int] = None,
                    rollout_fragment_length: Optional[int] = None
                    ) -> "SACConfig":
        if num_env_runners is not None:
            self.num_env_runners = num_env_runners
        if num_envs_per_env_runner is not None:
            self.num_envs_per_env_runner = num_envs_per_env_runner
        if rollout_fragment_length is not None:
            self.rollout_fragment_length = rollout_fragment_length
        return self

    def training(self, **kwargs) -> "SACConfig":
        for key, value in kwargs.items():
            if not hasattr(self, key):
                raise AttributeError(f"unknown training option {key!r}")
            setattr(self, key, value)
        return self

    def build(self) -> "SAC":
        return SAC(self)


class SACEnvRunner:
    """Stochastic-policy fragment sampler for continuous action spaces
    (reference: single_agent_env_runner with the SAC exploration —
    sampling from the squashed Gaussian IS the exploration)."""

    def __init__(self, env_name: str, num_envs: int, fragment_len: int,
                 model_config: Dict[str, Any], seed: int = 0):
        import gymnasium as gym
        import jax

        from .models import SquashedGaussianPolicy, squashed_sample

        env_fns = [lambda: gym.make(env_name) for _ in range(num_envs)]
        try:
            self._envs = gym.vector.SyncVectorEnv(
                env_fns, autoreset_mode=gym.vector.AutoresetMode.SAME_STEP)
        except (AttributeError, TypeError):
            self._envs = gym.vector.SyncVectorEnv(env_fns)
        self._num_envs = num_envs
        self._T = fragment_len
        space = self._envs.single_action_space
        self._act_dim = int(np.prod(space.shape))
        self._act_low = np.asarray(space.low, np.float32)
        self._act_high = np.asarray(space.high, np.float32)
        self._model = SquashedGaussianPolicy(
            act_dim=self._act_dim,
            hidden=tuple(model_config.get("hidden", (256, 256))))
        self._rng = jax.random.PRNGKey(seed)
        self._params = None

        def policy_sample(params, obs, rng):
            mean, log_std = self._model.apply({"params": params}, obs)
            action, _ = squashed_sample(mean, log_std, rng)
            return action

        self._sample_fn = jax.jit(policy_sample)
        obs, _ = self._envs.reset(seed=seed)
        self._obs = obs.astype(np.float32)
        self._episode_returns = np.zeros(num_envs, np.float64)
        self._completed: List[float] = []

    def observation_shape(self):
        return tuple(self._envs.single_observation_space.shape)

    def action_dim(self) -> int:
        return self._act_dim

    def set_weights(self, params) -> bool:
        self._params = params
        return True

    def _scale(self, squashed: np.ndarray) -> np.ndarray:
        """[-1, 1] policy output -> env action bounds."""
        return (self._act_low + (squashed + 1.0) * 0.5 *
                (self._act_high - self._act_low))

    def sample(self) -> Dict[str, np.ndarray]:
        import jax
        assert self._params is not None, "set_weights first"
        T, N = self._T, self._num_envs
        obs_buf = np.empty((T, N) + self._obs.shape[1:], np.float32)
        next_buf = np.empty_like(obs_buf)
        act_buf = np.empty((T, N, self._act_dim), np.float32)
        rew_buf = np.empty((T, N), np.float32)
        term_buf = np.empty((T, N), bool)
        for t in range(T):
            self._rng, key = jax.random.split(self._rng)
            squashed = np.asarray(
                self._sample_fn(self._params, self._obs, key), np.float32)
            next_obs, reward, terminated, truncated, _infos = \
                self._envs.step(self._scale(squashed))
            obs_buf[t] = self._obs
            act_buf[t] = squashed  # store the [-1,1] action the learner
            # evaluates; bounds scaling is env-side only
            rew_buf[t] = reward
            next_buf[t] = next_obs.astype(np.float32)
            # truncation still bootstraps (matches DQN's handling)
            term_buf[t] = terminated
            self._episode_returns += reward
            for i in np.nonzero(np.logical_or(terminated, truncated))[0]:
                self._completed.append(float(self._episode_returns[i]))
                self._episode_returns[i] = 0.0
            self._obs = next_obs.astype(np.float32)
        returns, self._completed = self._completed, []
        flat = lambda a: a.reshape((T * N,) + a.shape[2:])  # noqa: E731
        return {"obs": flat(obs_buf), "actions": flat(act_buf),
                "rewards": flat(rew_buf), "next_obs": flat(next_buf),
                "dones": flat(term_buf.astype(np.float32)),
                "episode_returns": np.asarray(returns, np.float64)}


class SACLearner:
    """Jitted SAC update: twin-Q TD with entropy-regularized targets,
    reparameterized actor gradient, temperature adaptation, polyak —
    one XLA program (reference: sac torch learner split across
    compute_gradients/update; here fused)."""

    def __init__(self, obs_shape, act_dim: int,
                 model_config: Dict[str, Any], actor_lr: float,
                 critic_lr: float, alpha_lr: float, gamma: float,
                 tau: float, initial_alpha: float,
                 target_entropy: Optional[float], grad_clip: float,
                 seed: int = 0):
        import jax
        import jax.numpy as jnp
        import optax

        from .models import (ContinuousQMLP, SquashedGaussianPolicy,
                             squashed_sample)

        hidden = tuple(model_config.get("hidden", (256, 256)))
        self._policy = SquashedGaussianPolicy(act_dim=act_dim,
                                              hidden=hidden)
        self._q = ContinuousQMLP(hidden=hidden)
        rng = jax.random.PRNGKey(seed)
        k_pi, k_q1, k_q2, self._rng = jax.random.split(rng, 4)
        dummy_obs = jnp.zeros((1,) + tuple(obs_shape), jnp.float32)
        dummy_act = jnp.zeros((1, act_dim), jnp.float32)
        self.pi_params = self._policy.init(k_pi, dummy_obs)["params"]
        self.q1_params = self._q.init(k_q1, dummy_obs, dummy_act)["params"]
        self.q2_params = self._q.init(k_q2, dummy_obs, dummy_act)["params"]
        copy = lambda t: jax.tree_util.tree_map(lambda x: x, t)  # noqa: E731
        self.q1_target = copy(self.q1_params)
        self.q2_target = copy(self.q2_params)
        self.log_alpha = jnp.asarray(np.log(initial_alpha), jnp.float32)
        if target_entropy is None:
            target_entropy = -float(act_dim)
        self._pi_tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                                  optax.adam(actor_lr))
        self._q_tx = optax.chain(optax.clip_by_global_norm(grad_clip),
                                 optax.adam(critic_lr))
        self._alpha_tx = optax.adam(alpha_lr)
        self.pi_opt = self._pi_tx.init(self.pi_params)
        self.q_opt = self._q_tx.init((self.q1_params, self.q2_params))
        self.alpha_opt = self._alpha_tx.init(self.log_alpha)
        policy, q = self._policy, self._q
        pi_tx, q_tx, alpha_tx = self._pi_tx, self._q_tx, self._alpha_tx

        def update(state, batch, rng):
            (pi_params, q1_params, q2_params, q1_tgt, q2_tgt, log_alpha,
             pi_opt, q_opt, alpha_opt) = state
            k_next, k_pi = jax.random.split(rng)
            alpha = jnp.exp(log_alpha)

            # -- critic: y = r + gamma^k (1-d) [min Q_tgt(s',a') - a*logp]
            mean_n, log_std_n = policy.apply(
                {"params": pi_params}, batch["next_obs"])
            next_act, next_logp = squashed_sample(mean_n, log_std_n,
                                                  k_next)
            q1_next = q.apply({"params": q1_tgt}, batch["next_obs"],
                              next_act)
            q2_next = q.apply({"params": q2_tgt}, batch["next_obs"],
                              next_act)
            q_next = jnp.minimum(q1_next, q2_next) - alpha * next_logp
            target = batch["rewards"] + (1.0 - batch["dones"]) * \
                batch["discounts"] * q_next
            target = jax.lax.stop_gradient(target)

            def critic_loss(q_params):
                q1p, q2p = q_params
                q1 = q.apply({"params": q1p}, batch["obs"],
                             batch["actions"])
                q2 = q.apply({"params": q2p}, batch["obs"],
                             batch["actions"])
                return ((q1 - target) ** 2 + (q2 - target) ** 2).mean()

            c_loss, q_grads = jax.value_and_grad(critic_loss)(
                (q1_params, q2_params))
            q_updates, q_opt = q_tx.update(q_grads, q_opt,
                                           (q1_params, q2_params))
            q1_params, q2_params = optax.apply_updates(
                (q1_params, q2_params), q_updates)

            # -- actor: maximize E[min Q(s, a~) - alpha logp(a~|s)]
            def actor_loss(p):
                mean, log_std = policy.apply({"params": p}, batch["obs"])
                act, logp = squashed_sample(mean, log_std, k_pi)
                q1 = q.apply({"params": q1_params}, batch["obs"], act)
                q2 = q.apply({"params": q2_params}, batch["obs"], act)
                loss = (alpha * logp - jnp.minimum(q1, q2)).mean()
                return loss, logp

            (a_loss, logp), pi_grads = jax.value_and_grad(
                actor_loss, has_aux=True)(pi_params)
            pi_updates, pi_opt = pi_tx.update(pi_grads, pi_opt, pi_params)
            pi_params = optax.apply_updates(pi_params, pi_updates)

            # -- temperature: drive policy entropy toward the target
            def alpha_loss(la):
                return -(la * jax.lax.stop_gradient(
                    logp + target_entropy)).mean()

            al_loss, a_grad = jax.value_and_grad(alpha_loss)(log_alpha)
            a_update, alpha_opt = alpha_tx.update(a_grad, alpha_opt,
                                                  log_alpha)
            log_alpha = optax.apply_updates(log_alpha, a_update)

            # -- polyak target update
            q1_tgt = jax.tree_util.tree_map(
                lambda t, o: (1 - tau) * t + tau * o, q1_tgt, q1_params)
            q2_tgt = jax.tree_util.tree_map(
                lambda t, o: (1 - tau) * t + tau * o, q2_tgt, q2_params)
            new_state = (pi_params, q1_params, q2_params, q1_tgt, q2_tgt,
                         log_alpha, pi_opt, q_opt, alpha_opt)
            metrics = {"critic_loss": c_loss, "actor_loss": a_loss,
                       "alpha_loss": al_loss, "alpha": alpha,
                       "entropy": -logp.mean()}
            return new_state, metrics

        self._update = jax.jit(update)

        def update_many(state, batches, rng):
            """k updates in ONE compiled program: lax.scan over stacked
            [k, B, ...] minibatches — the TPU-first replay burst (per-
            update Python dispatch is what makes update-per-env-step
            intensities CPU-bound otherwise)."""
            def step(carry, xs):
                batch_k, key = xs
                new_state, metrics = update(carry, batch_k, key)
                return new_state, metrics

            keys = jax.random.split(rng, batches["rewards"].shape[0])
            state, metrics = jax.lax.scan(step, state, (batches, keys))
            return state, jax.tree_util.tree_map(lambda m: m[-1],
                                                 metrics)

        self._update_many = jax.jit(update_many)

    def _state(self):
        return (self.pi_params, self.q1_params, self.q2_params,
                self.q1_target, self.q2_target, self.log_alpha,
                self.pi_opt, self.q_opt, self.alpha_opt)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, float]:
        import jax
        import jax.numpy as jnp
        dev = {k: jnp.asarray(v) for k, v in batch.items()}
        if "discounts" not in dev:
            dev["discounts"] = jnp.full_like(dev["rewards"], 0.99)
        self._rng, key = jax.random.split(self._rng)
        state, metrics = self._update(self._state(), dev, key)
        (self.pi_params, self.q1_params, self.q2_params, self.q1_target,
         self.q2_target, self.log_alpha, self.pi_opt, self.q_opt,
         self.alpha_opt) = state
        return {k: float(v) for k, v in metrics.items()}

    def update_burst(self, flat: Dict[str, np.ndarray],
                     k: int) -> Dict[str, float]:
        """Split a [k*B, ...] sample into k minibatches and run them as
        one jitted scan (k fixed shapes -> one compilation per k)."""
        import jax
        import jax.numpy as jnp
        stacked = {
            key: jnp.asarray(value).reshape(
                (k, value.shape[0] // k) + value.shape[1:])
            for key, value in flat.items()}
        if "discounts" not in stacked:
            stacked["discounts"] = jnp.full_like(stacked["rewards"],
                                                 0.99)
        self._rng, key = jax.random.split(self._rng)
        state, metrics = self._update_many(self._state(), stacked, key)
        (self.pi_params, self.q1_params, self.q2_params, self.q1_target,
         self.q2_target, self.log_alpha, self.pi_opt, self.q_opt,
         self.alpha_opt) = state
        return {k2: float(v) for k2, v in metrics.items()}

    def get_weights(self):
        import jax
        return jax.device_get(self.pi_params)


class SAC:
    """Algorithm driver: mirrors DQN's training_step (sample → replay →
    update at training_intensity) with SAC's learner and stochastic
    exploration (reference: sac.py:560 — SAC extends DQN)."""

    def __init__(self, config: SACConfig):
        import ray_tpu

        self.config = config
        runner_cls = ray_tpu.remote(SACEnvRunner)
        self._runners = [
            runner_cls.options(num_cpus=1).remote(
                config.env_name, config.num_envs_per_env_runner,
                config.rollout_fragment_length, dict(config.model),
                seed=config.seed + 1000 * (i + 1))
            for i in range(config.num_env_runners)]
        obs_shape = ray_tpu.get(
            self._runners[0].observation_shape.remote(), timeout=120)
        act_dim = ray_tpu.get(
            self._runners[0].action_dim.remote(), timeout=120)
        buffer_cls = ray_tpu.remote(ReplayBufferActor)
        per_shard = config.buffer_capacity // config.num_replay_shards
        self._buffers = [
            buffer_cls.options(num_cpus=0.5).remote(
                per_shard, obs_shape, seed=config.seed + i,
                action_shape=(act_dim,), action_dtype="float32")
            for i in range(config.num_replay_shards)]
        self._learner = SACLearner(
            obs_shape, act_dim, dict(config.model), config.actor_lr,
            config.critic_lr, config.alpha_lr, config.gamma, config.tau,
            config.initial_alpha, config.target_entropy,
            config.grad_clip, seed=config.seed)
        self._broadcast_weights()
        self._env_steps = 0
        self._updates = 0
        self._trained_transitions = 0
        self._iteration = 0
        self._recent_returns: List[float] = []
        self._rr = 0

    def _broadcast_weights(self):
        import ray_tpu
        weights = self._learner.get_weights()
        ray_tpu.get([r.set_weights.remote(weights)
                     for r in self._runners], timeout=120)

    def train(self) -> Dict[str, Any]:
        import ray_tpu

        c = self.config
        t0 = time.perf_counter()
        fragments = ray_tpu.get(
            [r.sample.remote() for r in self._runners], timeout=300)
        adds = []
        sampled = 0
        gamma = c.gamma
        for frag in fragments:
            sampled += len(frag["actions"])
            self._recent_returns.extend(frag["episode_returns"].tolist())
            buf = self._buffers[self._rr % len(self._buffers)]
            self._rr += 1
            adds.append(buf.add_batch.remote(
                frag["obs"], frag["actions"], frag["rewards"],
                frag["next_obs"], frag["dones"],
                np.full_like(frag["rewards"], gamma)))
        if len(self._buffers) == 1:
            buffer_size = ray_tpu.get(adds, timeout=120)[-1] if adds \
                else 0
        else:
            ray_tpu.get(adds, timeout=120)
            buffer_size = sum(ray_tpu.get(
                [b.size.remote() for b in self._buffers], timeout=120))
        self._env_steps += sampled
        sample_time = time.perf_counter() - t0

        metrics: Dict[str, float] = {}
        t1 = time.perf_counter()
        if buffer_size >= c.learning_starts:
            target_trained = self._env_steps * c.training_intensity
            while self._trained_transitions < target_trained:
                remaining = int((target_trained -
                                 self._trained_transitions)
                                // c.batch_size)
                # fixed burst sizes keep the scan at three compiled
                # shapes total
                k = 64 if remaining >= 64 else (8 if remaining >= 8
                                                else 1)
                buf = self._buffers[self._updates % len(self._buffers)]
                flat = ray_tpu.get(
                    buf.sample_many.remote(c.batch_size, k), timeout=120)
                metrics = self._learner.update_burst(flat, k)
                self._updates += k
                self._trained_transitions += k * c.batch_size
            # Runners only sample between train() calls, so one sync at
            # the end of the update burst is as fresh as per-update
            # broadcasting — without the per-update RPC round trips.
            self._broadcast_weights()
        learn_time = time.perf_counter() - t1
        self._iteration += 1
        self._recent_returns = self._recent_returns[-100:]
        return {
            "training_iteration": self._iteration,
            "num_env_steps_sampled": self._env_steps,
            "num_updates": self._updates,
            "replay_buffer_size": buffer_size,
            "episode_return_mean": float(np.mean(self._recent_returns))
            if self._recent_returns else float("nan"),
            "sample_time_s": sample_time,
            "learn_time_s": learn_time,
            **metrics,
        }

    def stop(self):
        import ray_tpu
        for actor in self._runners + self._buffers:
            try:
                ray_tpu.kill(actor)
            except Exception:  # noqa: BLE001
                logger.debug("actor kill at stop failed", exc_info=True)
