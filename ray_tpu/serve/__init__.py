"""ray_tpu.serve — model serving on the TPU-native runtime
(reference: python/ray/serve — serve.run api.py:685, ServeController
_private/controller.py:103, deployment state machine
_private/deployment_state.py:1712,3220, replicas _private/replica.py,
HTTP proxy _private/proxy.py:706,1125, pow-2 router
_private/request_router/pow_2_router.py:27, autoscaling formula
serve/autoscaling_policy.py:13).

The design keeps the reference's split — control plane (controller actor
reconciling replica sets) vs data plane (proxy/handle → router → replica
actor) — but the replica hot path is TPU-shaped: model replicas hold jitted
programs and KV caches on device, and scale-out follows mesh placement rather
than process-per-request concurrency."""

from .api import (Application, Deployment, delete, deployment,
                  get_app_handle, get_deployment_handle, get_grpc_address,
                  get_http_address, run, shutdown, start, status)
from .batching import batch
from .config import AutoscalingConfig, HTTPOptions
from .handle import DeploymentHandle, DeploymentResponse
from .multiplex import get_multiplexed_model_id, multiplexed

__all__ = [
    "Application", "AutoscalingConfig", "Deployment", "DeploymentHandle",
    "DeploymentResponse", "HTTPOptions", "batch", "delete", "deployment",
    "get_app_handle", "get_deployment_handle", "get_grpc_address",
    "get_http_address", "get_multiplexed_model_id", "multiplexed", "run",
    "shutdown", "start", "status",
]
