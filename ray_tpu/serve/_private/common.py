"""Shared serve-internal names and small types
(reference: serve/_private/common.py DeploymentID/ReplicaID/statuses)."""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

CONTROLLER_NAME = "SERVE_CONTROLLER"
PROXY_NAME = "SERVE_PROXY"
SERVE_NAMESPACE = "serve"

# Replica lifecycle (reference: deployment_state.py ReplicaState).
STARTING = "STARTING"
RUNNING = "RUNNING"
STOPPING = "STOPPING"
UNHEALTHY = "UNHEALTHY"

# Deployment status (reference: common.py DeploymentStatus).
DEPLOY_UPDATING = "UPDATING"
DEPLOY_HEALTHY = "HEALTHY"
DEPLOY_UNHEALTHY = "UNHEALTHY"
DEPLOY_UPSCALING = "UPSCALING"
DEPLOY_DOWNSCALING = "DOWNSCALING"


def replica_actor_name(app: str, deployment: str, replica_tag: str) -> str:
    return f"SERVE_REPLICA::{app}#{deployment}#{replica_tag}"


@dataclasses.dataclass
class DeploymentID:
    name: str
    app: str = "default"

    def key(self) -> str:
        return f"{self.app}#{self.name}"

    @staticmethod
    def from_key(key: str) -> "DeploymentID":
        app, name = key.split("#", 1)
        return DeploymentID(name=name, app=app)


@dataclasses.dataclass
class ReplicaInfo:
    """What the router needs to reach one replica. Carries the actor id so
    handles are constructed without a GCS name lookup (the actor submitter
    resolves addresses lazily — keeps the router loop-safe and RPC-free)."""
    replica_tag: str
    actor_name: str
    actor_id: Any = None
    max_ongoing_requests: int = 100
