"""ServeController: the serving control plane
(reference: serve/_private/controller.py:103 — detached actor whose
reconciliation loop drives DeploymentStateManager.deploy, health checks,
autoscaling, and config push to proxies via long-poll long_poll.py).

Async actor. The reconcile loop runs as a background asyncio task; RPCs
from handles/proxies (get_replica_set, listen_for_change) interleave on the
same loop. Nothing on the request data plane goes through the controller."""

from __future__ import annotations

import asyncio
import logging
import time
import uuid
from typing import Any, Dict, List, Optional, Tuple

from .common import PROXY_NAME, SERVE_NAMESPACE
from .deployment_state import DeploymentState
from ..config import DeploymentConfig

logger = logging.getLogger(__name__)


class ServeController:
    def __init__(self, http_host: str = "127.0.0.1", http_port: int = 8000):
        self.deployments: Dict[str, DeploymentState] = {}
        # app -> {"route_prefix": str, "ingress": deployment key}
        self.apps: Dict[str, Dict[str, Any]] = {}
        self._replica_set_version: Dict[str, int] = {}
        self._route_version = 0
        self._change_events: Dict[str, asyncio.Event] = {}
        self._http_host = http_host
        self._http_port = http_port
        self._proxy_handle = None
        # __init__ runs off-loop (actor creation executes in a pool thread);
        # the reconcile loop is started lazily from the first async RPC.
        self._loop_task = None
        self._shutdown = False

    def _ensure_loop(self):
        if self._loop_task is None or self._loop_task.done():
            self._loop_task = asyncio.ensure_future(self._reconcile_loop())

    # -- deploy API (driver-facing) ---------------------------------------

    async def deploy_application(self, app_name: str, route_prefix: str,
                                 ingress_key: str,
                                 deployments: List[dict],
                                 router: str = "pow2") -> bool:
        """deployments: [{key, definition, init_args, init_kwargs, config,
        version}]. The whole app deploys atomically (reference:
        deploy_applications → DeploymentStateManager.deploy :3220)."""
        self._ensure_loop()
        for spec in deployments:
            key = spec["key"]
            state = self.deployments.get(key)
            if state is None:
                state = DeploymentState(key, self._on_replica_set_change)
                self.deployments[key] = state
            state.set_target(
                spec["definition"], spec.get("init_args"),
                spec.get("init_kwargs"),
                DeploymentConfig(**spec["config"]),
                spec.get("version") or uuid.uuid4().hex[:8])
        old = self.apps.get(app_name)
        self.apps[app_name] = {"route_prefix": route_prefix,
                               "ingress": ingress_key,
                               "router": router}
        if old is None or old.get("route_prefix") != route_prefix or \
                old.get("ingress") != ingress_key or \
                old.get("router") != router:
            self._route_version += 1
            self._signal("routes")
        return True

    async def delete_application(self, app_name: str) -> bool:
        app = self.apps.pop(app_name, None)
        if app is None:
            return False
        prefix = f"{app_name}#"
        for key, state in self.deployments.items():
            if key.startswith(prefix):
                state.set_deleting()
        self._route_version += 1
        self._signal("routes")
        return True

    async def shutdown(self) -> bool:
        self._shutdown = True
        for state in self.deployments.values():
            state.set_deleting()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if all(s.is_deleted() for s in self.deployments.values()):
                break
            for state in self.deployments.values():
                await state.reconcile()
            await asyncio.sleep(0.05)
        for handle in (self._proxy_handle,
                       getattr(self, "_grpc_proxy_handle", None)):
            if handle is None:
                continue
            import ray_tpu
            try:
                await asyncio.get_running_loop().run_in_executor(
                    None, lambda h=handle: ray_tpu.kill(h))
            except Exception:  # noqa: BLE001
                logger.debug("replica kill at shutdown failed",
                             exc_info=True)
        return True

    # -- proxy management --------------------------------------------------

    async def ensure_proxy(self) -> Tuple[str, int]:
        self._ensure_loop()
        if self._proxy_handle is None:
            host, port = self._http_host, self._http_port

            def _create():
                # Blocking GCS round-trips — keep off the event loop.
                import ray_tpu
                from ..api import head_node_strategy
                from .common import CONTROLLER_NAME
                from .proxy import ProxyActor
                try:
                    return ray_tpu.get_actor(PROXY_NAME,
                                             namespace=SERVE_NAMESPACE)
                except ValueError:
                    controller = ray_tpu.get_actor(CONTROLLER_NAME,
                                                   namespace=SERVE_NAMESPACE)
                    proxy_cls = ray_tpu.remote(ProxyActor)
                    options = dict(
                        name=PROXY_NAME, namespace=SERVE_NAMESPACE,
                        lifetime="detached", num_cpus=0, get_if_exists=True,
                        max_concurrency=1000)
                    strategy = head_node_strategy()
                    if strategy is not None:
                        # the proxy owns the PUBLISHED http address:
                        # it must live on the head, not wherever the
                        # hybrid policy spills under load (a worker
                        # drain would migrate it mid-connection)
                        options["scheduling_strategy"] = strategy
                    return proxy_cls.options(**options).remote(
                        controller, host, port)
            loop = asyncio.get_running_loop()
            self._proxy_handle = await loop.run_in_executor(None, _create)
            # Block until the HTTP server is listening.
            host, port = await self._proxy_handle.ready.remote()
            self._http_host, self._http_port = host, port
        return self._http_host, self._http_port

    async def ensure_grpc_proxy(self, port: int = 0) -> Tuple[str, int]:
        """Start (once) the gRPC ingress proxy actor (reference:
        proxy.py:530 gRPCProxy)."""
        self._ensure_loop()
        if getattr(self, "_grpc_proxy_handle", None) is None:
            host = self._http_host

            def _create():
                import ray_tpu
                from ..api import head_node_strategy
                from .common import CONTROLLER_NAME
                from .grpc_proxy import GrpcProxyActor
                try:
                    return ray_tpu.get_actor("SERVE_GRPC_PROXY",
                                             namespace=SERVE_NAMESPACE)
                except ValueError:
                    controller = ray_tpu.get_actor(
                        CONTROLLER_NAME, namespace=SERVE_NAMESPACE)
                    proxy_cls = ray_tpu.remote(GrpcProxyActor)
                    options = dict(
                        name="SERVE_GRPC_PROXY",
                        namespace=SERVE_NAMESPACE, lifetime="detached",
                        num_cpus=0, get_if_exists=True,
                        max_concurrency=1000)
                    strategy = head_node_strategy()
                    if strategy is not None:
                        options["scheduling_strategy"] = strategy
                    return proxy_cls.options(**options).remote(
                        controller, host, port)
            loop = asyncio.get_running_loop()
            self._grpc_proxy_handle = await loop.run_in_executor(
                None, _create)
            addr = await self._grpc_proxy_handle.ready.remote()
            self._grpc_addr = tuple(addr)
        return self._grpc_addr

    # -- router/proxy-facing -----------------------------------------------

    async def get_replica_set(self, key: str) -> Tuple[int, List[dict]]:
        state = self.deployments.get(key)
        if state is None:
            return (0, [])
        version = self._replica_set_version.get(key, 0)
        return (version, state.running_replica_infos())

    async def get_routes(self) -> Tuple[int, Dict[str, Dict[str, str]]]:
        """route_prefix -> {key: ingress deployment key, router: kind}."""
        return (self._route_version,
                {info["route_prefix"]: {
                    "key": info["ingress"],
                    "router": info.get("router", "pow2")}
                 for info in self.apps.values()})

    async def listen_for_change(self, topic: str, known_version: int,
                                timeout_s: float = 30.0):
        """Long-poll (reference: _private/long_poll.py LongPollHost): block
        until `topic`'s version exceeds known_version, then return the new
        snapshot. Topics: 'routes' or a deployment key."""
        deadline = time.monotonic() + timeout_s
        while not self._shutdown:
            if topic == "routes":
                version, snapshot = await self.get_routes()
            else:
                version, snapshot = await self.get_replica_set(topic)
            if version > known_version:
                return (version, snapshot)
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return (known_version, None)  # timed out, nothing new
            event = self._change_events.setdefault(topic, asyncio.Event())
            try:
                await asyncio.wait_for(event.wait(),
                                       min(remaining, 1.0))
            except asyncio.TimeoutError:
                pass
        return (known_version, None)

    def _signal(self, topic: str):
        event = self._change_events.pop(topic, None)
        if event is not None:
            event.set()

    def _on_replica_set_change(self, key: str):
        self._replica_set_version[key] = \
            self._replica_set_version.get(key, 0) + 1
        self._signal(key)

    # -- status ------------------------------------------------------------

    async def get_serve_status(self) -> Dict[str, Any]:
        return {
            "apps": {
                name: {
                    "route_prefix": info["route_prefix"],
                    "deployments": {
                        key.split("#", 1)[1]: self.deployments[key].status()
                        for key in self.deployments
                        if key.startswith(f"{name}#")
                    },
                } for name, info in self.apps.items()
            },
        }

    async def ping(self) -> bool:
        return True

    # -- reconcile loop ----------------------------------------------------

    async def _reconcile_loop(self):
        from ray_tpu._internal.backoff import Backoff
        metrics_interval = 0.25
        last_metrics = 0.0
        bo = None  # armed while ticks fail (GCS failover, replica churn)
        while not self._shutdown:
            try:
                for key, state in list(self.deployments.items()):
                    await state.reconcile()
                    if state.is_deleted() and state.deleting:
                        del self.deployments[key]
                        self._on_replica_set_change(key)
                now = time.monotonic()
                if now - last_metrics >= metrics_interval:
                    last_metrics = now
                    await self._collect_metrics_and_autoscale()
                bo = None
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("reconcile tick failed")
                if bo is None:
                    # Failing ticks (e.g. the control plane mid-failover)
                    # back off jittered-exponentially instead of spinning
                    # the failure at full tick rate.
                    bo = Backoff(base_s=0.05, max_s=2.0)
            if bo is not None:
                await bo.async_sleep()
            else:
                await asyncio.sleep(0.05)

    async def _collect_metrics_and_autoscale(self):
        for state in self.deployments.values():
            auto = state.target_config.autoscaling_config \
                if state.target_config else None
            if not auto:
                continue
            total = 0.0
            queued = 0.0
            ttfts = []
            kv_occs = []
            probes = []
            replicas = [r for r in state.replicas.values()
                        if r.state == "RUNNING" and r.handle is not None]
            for r in replicas:
                probes.append(r.handle.get_metrics.remote())
            if probes:
                try:
                    results = await asyncio.wait_for(
                        asyncio.gather(*probes, return_exceptions=True), 5)
                except asyncio.TimeoutError:
                    results = []
                for r, res in zip(replicas, results):
                    if isinstance(res, dict):
                        state.last_metrics[r.tag] = res
                        total += res.get("ongoing", 0)
                        # Flight-recorder signals a replica's engine
                        # reports (queue depth / TTFT) drive the
                        # metric-based scale path when the autoscaling
                        # config targets them.
                        queued += res.get("queued", 0) or 0
                        if res.get("ttft_s"):
                            ttfts.append(res["ttft_s"])
                        if res.get("kv_occupancy") is not None:
                            kv_occs.append(res["kv_occupancy"])
            ttfts.sort()
            state.autoscale_tick(
                total, total_queued=queued,
                p50_ttft_s=ttfts[len(ttfts) // 2] if ttfts else None,
                kv_occupancy=(sum(kv_occs) / len(kv_occs)
                              if kv_occs else None))
